"""Control-flow layers: StaticRNN, While, array ops, cond.

Reference parity: python/paddle/fluid/layers/control_flow.py
(StaticRNN:383, While:608, IfElse:1252, DynamicRNN:1354, array ops).
TPU-native design: these build sub-blocks in the IR which the executor
lowers to jax.lax.scan / while_loop / cond — compiler-friendly control
flow instead of the reference's nested-Executor interpretation
(while_op.cc:35, recurrent_op.cc:222).
"""
from __future__ import annotations

from typing import List, Optional

from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper

__all__ = ["StaticRNN", "While", "Switch", "increment_shared",
           "array_write", "array_read", "array_length", "less_than_v",
           "cond_op"]


class StaticRNN:
    """Fixed-length RNN over the time axis, lowered to one scan op.

    Usage parity with reference StaticRNN (control_flow.py:383):
        rnn = StaticRNN()
        with rnn.step():
            word = rnn.step_input(x_t)           # x_t: [T, B, D]
            prev = rnn.memory(init=h0)           # or shape/value init
            h = some_layers(word, prev)
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        outs = rnn()
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._inputs: List[Variable] = []
        self._mem_init: List[Variable] = []
        self._mem_pre: List[Variable] = []
        self._mem_new: List[Optional[Variable]] = []
        self._outputs: List[Variable] = []
        self._block = None
        self._parent_prog = None
        self._entered = False

    class _StepGuard:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            prog = default_main_program()
            self.rnn._parent_prog = prog
            self.rnn._block = prog.create_block()
            self.rnn._entered = True
            return self.rnn

        def __exit__(self, *exc):
            self.rnn._entered = False
            prog = self.rnn._parent_prog
            prog.rollback()
            self.rnn._finalize()
            return False

    def step(self):
        return StaticRNN._StepGuard(self)

    def step_input(self, x: Variable) -> Variable:
        """x: [T, ...]; returns the per-step slice variable."""
        sv = self._block.create_var(
            name=f"{x.name}@step", shape=list(x.shape[1:]) if x.shape
            else None, dtype=x.dtype)
        self._inputs.append((x, sv))
        return sv

    def memory(self, init: Variable = None, shape=None, value=0.0,
               dtype="float32") -> Variable:
        if init is None:
            # The init constant must live in the PARENT block (it feeds the
            # static_rnn op there), not the step sub-block we're inside.
            prog = self._parent_prog
            parent = prog.block(self._block.desc.parent_idx)
            from ..framework import unique_name
            init = parent.create_var(name=unique_name("rnn_mem_init"),
                                     shape=list(shape), dtype=dtype)
            parent.append_op("fill_constant", outputs={"Out": init},
                             attrs={"shape": list(shape), "dtype": dtype,
                                    "value": float(value)})
        pre = self._block.create_var(name=f"{init.name}@pre",
                                     shape=list(init.shape)
                                     if init.shape else None,
                                     dtype=init.dtype)
        self._mem_init.append(init)
        self._mem_pre.append(pre)
        self._mem_new.append(None)
        return pre

    def update_memory(self, pre: Variable, new: Variable):
        idx = self._mem_pre.index(pre)
        self._mem_new[idx] = new

    def step_output(self, out: Variable):
        self._outputs.append(out)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self):
        helper = self.helper
        self._result_vars = [
            helper.create_tmp_variable(o.dtype) for o in self._outputs]
        helper.append_op(
            type="static_rnn",
            inputs={"X": [x for x, _ in self._inputs],
                    "MemInit": self._mem_init},
            outputs={"Out": self._result_vars},
            attrs={"sub_block_idx": self._block.idx,
                   "step_in_names": [sv.name for _, sv in self._inputs],
                   "mem_pre_names": [v.name for v in self._mem_pre],
                   "mem_new_names": [v.name for v in self._mem_new],
                   "out_names": [o.name for o in self._outputs]})

    def __call__(self):
        res = self._result_vars
        return res[0] if len(res) == 1 else res


class While:
    """While loop over a boolean condition var (reference:
    control_flow.py:608 / while_op.cc). Loop-carried state is every var
    the body writes that exists before the loop; lowered to
    jax.lax.while_loop."""

    def __init__(self, cond: Variable, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self._block = None

    def block(self):
        return While._Guard(self)

    class _Guard:
        def __init__(self, w):
            self.w = w

        def __enter__(self):
            prog = default_main_program()
            self.w._prog = prog
            self.w._block = prog.create_block()
            return self.w

        def __exit__(self, *exc):
            prog = self.w._prog
            prog.rollback()
            self.w._finalize()
            return False

    def _finalize(self):
        blk = self._block
        # loop-carried state: vars written in body that exist in parent
        parent = self._prog.block(blk.desc.parent_idx)
        written = []
        for op in blk.desc.ops:
            for n in op.output_names():
                if parent.desc.find_var_recursive(n) is not None \
                        and n not in written:
                    written.append(n)
        self.helper.append_op(
            type="while", inputs={"Cond": self.cond_var},
            outputs={"Out": written},
            attrs={"sub_block_idx": blk.idx,
                   "carried_names": written,
                   "cond_name": self.cond_var.name})


class Switch:
    """Reference parity for layers.Switch (control_flow.py:1163): builds
    nested conds. Minimal host-side version for LR schedules."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.cases = []

    def case(self, condition):
        raise NotImplementedError(
            "Switch is provided via learning_rate_scheduler host-side "
            "schedules in the TPU build")

    def default(self):
        raise NotImplementedError


def increment_shared(x, value=1.0):
    from .nn import increment
    return increment(x, value)


def array_write(x, i, array=None, capacity=None):
    """TensorArray write (reference: tensor_array_read_write_op.cc).
    Arrays are dense [capacity, ...] tensors with dynamic_update_slice.
    Writes back into the array var itself (reference in-place semantics)
    so a write inside a While body carries the array through the loop.
    `capacity` sizes a NEW array only — an existing array's capacity is
    fixed at creation (writes past it clamp to the last slot)."""
    helper = LayerHelper("array_write")
    inputs = {"X": x, "I": i}
    attrs = {}
    if array is None:
        array = helper.create_tmp_variable(x.dtype)
        array.desc.type = "tensor_array"
        attrs["capacity"] = capacity if capacity is not None else 128
    else:
        if capacity is not None:
            raise ValueError(
                "array_write: capacity only applies when creating a new "
                "array; this array's capacity was fixed at creation")
        inputs["Array"] = array
    helper.append_op(type="array_write", inputs=inputs,
                     outputs={"Out": array}, attrs=attrs)
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(array.dtype)
    helper.append_op(type="array_read", inputs={"Array": array, "I": i},
                     outputs={"Out": out})
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="array_length", inputs={"Array": array},
                     outputs={"Out": out})
    return out


def less_than_v(x, y, cond=None):
    """cond= writes the result into an existing var — the book-test idiom
    for refreshing a While condition inside the loop body."""
    helper = LayerHelper("less_than")
    out = cond if cond is not None else helper.create_tmp_variable("bool")
    helper.append_op(type="less_than", inputs={"X": x, "Y": y},
                     outputs={"Out": out})
    return out


def cond_op(pred, true_fn, false_fn):
    """Functional cond: both branches are built as sub-blocks and lowered
    to lax.cond (reference capability: conditional_block_op.cc)."""
    prog = default_main_program()
    helper = LayerHelper("cond")

    tb = prog.create_block()
    true_out = true_fn()
    prog.rollback()
    fb = prog.create_block()
    false_out = false_fn()
    prog.rollback()

    out = helper.create_tmp_variable(true_out.dtype)
    helper.append_op(type="cond",
                     inputs={"Pred": pred},
                     outputs={"Out": out},
                     attrs={"true_block_idx": tb.idx,
                            "false_block_idx": fb.idx,
                            "true_out": true_out.name,
                            "false_out": false_out.name})
    return out
