"""In-graph CSP channel layers (reference: fluid/concurrency.py
make_channel/channel_send/channel_recv/channel_close building channel
ops into programs). See ops/csp_ops.py for the host-callback lowering;
`register_channel` bridges host `concurrency.Channel` objects into the
graph so go() threads and in-graph ops share one channel.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["make_channel", "channel_send", "channel_recv",
           "channel_close", "select", "Go"]


def make_channel(dtype=None, capacity: int = 0):
    """Create a channel inside the program; returns the channel var
    (an int32 id routed to the host registry). `dtype` is accepted for
    reference-API parity; values carry their own dtype. In-graph
    channels must be buffered (capacity >= 1) — the op rejects
    unbuffered ones at trace time, since ordered callbacks cannot
    rendezvous within one program (use concurrency.Channel +
    ops.csp_ops.register_channel for host-side unbuffered channels)."""
    helper = LayerHelper("channel_create")
    out = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="channel_create", outputs={"Out": out},
                     attrs={"capacity": int(capacity)})
    return out


def channel_send(channel, value, timeout: float = -1.0):
    """Send `value` into `channel` (blocks the program per rendezvous
    semantics; timeout<0 waits forever). Returns the status var."""
    helper = LayerHelper("channel_send")
    status = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="channel_send",
                     inputs={"Channel": channel, "X": value},
                     outputs={"Status": status},
                     attrs={"timeout": float(timeout)})
    return status


def channel_recv(channel, shape, dtype="float32", timeout: float = -1.0):
    """Receive one value of static `shape`/`dtype` from `channel`."""
    helper = LayerHelper("channel_recv")
    out = helper.create_tmp_variable(dtype, shape=list(shape))
    helper.append_op(type="channel_recv", inputs={"Channel": channel},
                     outputs={"Out": out},
                     attrs={"shape": [int(d) for d in shape],
                            "dtype": dtype,
                            "timeout": float(timeout)})
    return out


def channel_close(channel):
    helper = LayerHelper("channel_close")
    status = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="channel_close", inputs={"Channel": channel},
                     outputs={"Status": status})
    return status


class Go:
    """In-graph go block (reference: go_op.cc + fluid.concurrency Go):
    ops built inside `with Go().block():` form a sub-block that a host
    thread executes (eagerly) when the program reaches the go op —
    fire-and-forget, typically feeding/draining channels the main
    program shares.

        g = Go()
        with g.block():
            layers.channel_send(ch, v)   # runs on the spawned thread
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("go", name=name)
        self._block = None

    def block(self):
        return Go._Guard(self)

    class _Guard:
        def __init__(self, g):
            self.g = g

        def __enter__(self):
            from ..framework import default_main_program
            prog = default_main_program()
            self.g._prog = prog
            self.g._block = prog.create_block()
            return self.g

        def __exit__(self, *exc):
            prog = self.g._prog
            prog.rollback()
            self.g._finalize()
            return False

    def _finalize(self):
        blk = self._block
        parent = self._prog.block(blk.desc.parent_idx)
        # captured inputs: names the body reads that it did not produce
        # and that exist in the parent scope chain
        produced, captured = set(), []
        for op in blk.desc.ops:
            for n in op.input_names():
                if n not in produced and n not in captured and \
                        parent.desc.find_var_recursive(n) is not None:
                    captured.append(n)
            produced.update(op.output_names())
        self.status = self.helper.create_tmp_variable("int32", shape=[])
        self.helper.append_op(
            type="go", inputs={"X": captured},
            outputs={"Status": self.status},
            attrs={"sub_block_idx": blk.idx,
                   "captured_names": captured})


def select(cases, timeout: float = -1.0, return_ok: bool = False):
    """In-graph multi-way select (reference: select_op.cc; Go
    semantics — pick one ready case, block until some case is ready).

    cases: list of
      ("recv", channel_var, shape, dtype) — receive one value, or
      ("send", channel_var, value_var)    — send value_var.

    Returns (case_index, recv_outs): case_index is an int32 scalar var
    naming the fired case (branch on it with IfElse/cond/switch);
    recv_outs holds one output var per recv case, in case order (the
    received value when that case fired, zeros otherwise). With
    return_ok=True also returns recv_ok, an int32 [n_recv] var whose
    fired slot is 1 iff the recv delivered a real value — 0 means the
    case fired because its channel closed (Go's `v, ok := <-ch`)."""
    helper = LayerHelper("select")
    channels, send_x, kinds = [], [], []
    recv_shapes, recv_dtypes, recv_outs = [], [], []
    for case in cases:
        kind = case[0]
        kinds.append(kind)
        channels.append(case[1])
        if kind == "recv":
            _, _, shape, dtype = case
            recv_shapes.append([int(d) for d in shape])
            recv_dtypes.append(dtype)
            recv_outs.append(
                helper.create_tmp_variable(dtype, shape=list(shape)))
        elif kind == "send":
            send_x.append(case[2])
        else:
            raise ValueError(f"unknown select case kind {kind!r}")
    idx = helper.create_tmp_variable("int32", shape=[])
    inputs = {"Channels": channels}
    if send_x:
        inputs["SendX"] = send_x
    outputs = {"CaseIndex": idx, "Out": recv_outs}
    recv_ok = None
    if recv_outs:
        recv_ok = helper.create_tmp_variable("int32",
                                             shape=[len(recv_outs)])
        outputs["RecvOk"] = recv_ok
    helper.append_op(type="select", inputs=inputs,
                     outputs=outputs,
                     attrs={"kinds": kinds,
                            "timeout": float(timeout),
                            "recv_shapes": recv_shapes,
                            "recv_dtypes": recv_dtypes})
    if return_ok:
        return idx, recv_outs, recv_ok
    return idx, recv_outs
