"""In-graph CSP channel layers (reference: fluid/concurrency.py
make_channel/channel_send/channel_recv/channel_close building channel
ops into programs). See ops/csp_ops.py for the host-callback lowering;
`register_channel` bridges host `concurrency.Channel` objects into the
graph so go() threads and in-graph ops share one channel.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["make_channel", "channel_send", "channel_recv",
           "channel_close"]


def make_channel(dtype=None, capacity: int = 0):
    """Create a channel inside the program; returns the channel var
    (an int32 id routed to the host registry). `dtype` is accepted for
    reference-API parity; values carry their own dtype. In-graph
    channels must be buffered (capacity >= 1) — the op rejects
    unbuffered ones at trace time, since ordered callbacks cannot
    rendezvous within one program (use concurrency.Channel +
    ops.csp_ops.register_channel for host-side unbuffered channels)."""
    helper = LayerHelper("channel_create")
    out = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="channel_create", outputs={"Out": out},
                     attrs={"capacity": int(capacity)})
    return out


def channel_send(channel, value, timeout: float = -1.0):
    """Send `value` into `channel` (blocks the program per rendezvous
    semantics; timeout<0 waits forever). Returns the status var."""
    helper = LayerHelper("channel_send")
    status = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="channel_send",
                     inputs={"Channel": channel, "X": value},
                     outputs={"Status": status},
                     attrs={"timeout": float(timeout)})
    return status


def channel_recv(channel, shape, dtype="float32", timeout: float = -1.0):
    """Receive one value of static `shape`/`dtype` from `channel`."""
    helper = LayerHelper("channel_recv")
    out = helper.create_tmp_variable(dtype, shape=list(shape))
    helper.append_op(type="channel_recv", inputs={"Channel": channel},
                     outputs={"Out": out},
                     attrs={"shape": [int(d) for d in shape],
                            "dtype": dtype,
                            "timeout": float(timeout)})
    return out


def channel_close(channel):
    helper = LayerHelper("channel_close")
    status = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="channel_close", inputs={"Channel": channel},
                     outputs={"Status": status})
    return status
