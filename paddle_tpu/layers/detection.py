"""Detection layers (reference: paddle/fluid/operators/ detection ops —
prior_box_op.cc, box_coder_op.cc, iou ops, multiclass_nms). Round-1 subset:
prior_box and box_coder as pure-XLA ops; NMS follows in the detection
op module (fixed-output-capacity TPU form)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "bipartite_match",
           "target_assign", "detection_output"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    helper = LayerHelper("prior_box")
    boxes = helper.create_tmp_variable(input.dtype)
    variances = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="prior_box",
                     inputs={"Input": input, "Image": image},
                     outputs={"Boxes": boxes, "Variances": variances},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios),
                            "variances": list(variance),
                            "flip": flip, "clip": clip,
                            "step_w": steps[0], "step_h": steps[1],
                            "offset": offset})
    return boxes, variances


def box_coder(prior_box_var, prior_box_v, target_box,
              code_type="encode_center_size", box_normalized=True):
    helper = LayerHelper("box_coder")
    out = helper.create_tmp_variable(target_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": prior_box_v,
                             "PriorBoxVar": prior_box_var,
                             "TargetBox": target_box},
                     outputs={"OutputBox": out},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out


def iou_similarity(x, y):
    """Pairwise IoU (reference: iou_similarity_op.cc)."""
    helper = LayerHelper("iou_similarity")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": x, "Y": y},
                     outputs={"Out": out})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5):
    """Greedy bipartite matching (reference: detection.py bipartite_match)."""
    helper = LayerHelper("bipartite_match")
    match_indices = helper.create_tmp_variable("int32")
    match_dist = helper.create_tmp_variable(dist_matrix.dtype)
    helper.append_op(type="bipartite_match",
                     inputs={"DistMat": dist_matrix},
                     outputs={"ColToRowMatchIndices": match_indices,
                              "ColToRowMatchDist": match_dist},
                     attrs={"match_type": match_type,
                            "dist_threshold": dist_threshold})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0):
    """Per-prior target assignment (reference: detection.py target_assign)."""
    helper = LayerHelper("target_assign")
    out = helper.create_tmp_variable(input.dtype)
    out_weight = helper.create_tmp_variable("float32")
    inputs = {"X": input, "MatchIndices": matched_indices}
    if negative_indices is not None:
        inputs["NegIndices"] = negative_indices
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": out, "OutWeight": out_weight},
                     attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01):
    """Decode predicted deltas against priors, softmax the class logits,
    then multiclass NMS (reference: detection.py:125-152 — box_coder +
    softmax + transpose + multiclass_nms). `scores` is [N, M, C] raw
    logits as in the reference. Static-shape output: [N, keep_top_k, 6]
    rows (label, score, x1, y1, x2, y2), padded rows carry score -1."""
    helper = LayerHelper("detection_output")
    decoded = helper.create_tmp_variable(loc.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": prior_box,
                             "PriorBoxVar": prior_box_var,
                             "TargetBox": loc},
                     outputs={"OutputBox": decoded},
                     attrs={"code_type": "decode_center_size",
                            "box_normalized": True})
    probs = helper.create_tmp_variable(scores.dtype)
    helper.append_op(type="softmax", inputs={"X": scores},
                     outputs={"Out": probs}, attrs={"axis": -1})
    probs_t = helper.create_tmp_variable(scores.dtype)
    helper.append_op(type="transpose", inputs={"X": probs},
                     outputs={"Out": probs_t}, attrs={"axis": [0, 2, 1]})
    out = helper.create_tmp_variable(loc.dtype)
    helper.append_op(type="multiclass_nms",
                     inputs={"BBoxes": decoded, "Scores": probs_t},
                     outputs={"Out": out},
                     attrs={"background_label": background_label,
                            "nms_threshold": nms_threshold,
                            "nms_top_k": nms_top_k,
                            "keep_top_k": keep_top_k,
                            "score_threshold": score_threshold})
    return out
