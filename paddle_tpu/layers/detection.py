"""Detection layers (reference: paddle/fluid/operators/ detection ops —
prior_box_op.cc, box_coder_op.cc, iou ops, multiclass_nms). Round-1 subset:
prior_box and box_coder as pure-XLA ops; NMS follows in the detection
op module (fixed-output-capacity TPU form)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    helper = LayerHelper("prior_box")
    boxes = helper.create_tmp_variable(input.dtype)
    variances = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="prior_box",
                     inputs={"Input": input, "Image": image},
                     outputs={"Boxes": boxes, "Variances": variances},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios),
                            "variances": list(variance),
                            "flip": flip, "clip": clip,
                            "step_w": steps[0], "step_h": steps[1],
                            "offset": offset})
    return boxes, variances


def box_coder(prior_box_var, prior_box_v, target_box,
              code_type="encode_center_size", box_normalized=True):
    helper = LayerHelper("box_coder")
    out = helper.create_tmp_variable(target_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": prior_box_v,
                             "PriorBoxVar": prior_box_var,
                             "TargetBox": target_box},
                     outputs={"OutputBox": out},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out
