"""Detection layers (reference: paddle/fluid/operators/ detection ops —
prior_box_op.cc, box_coder_op.cc, iou ops, multiclass_nms). Round-1 subset:
prior_box and box_coder as pure-XLA ops; NMS follows in the detection
op module (fixed-output-capacity TPU form)."""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "bipartite_match",
           "target_assign", "detection_output", "ssd_loss",
           "multi_box_head"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    helper = LayerHelper("prior_box")
    boxes = helper.create_tmp_variable(input.dtype)
    variances = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="prior_box",
                     inputs={"Input": input, "Image": image},
                     outputs={"Boxes": boxes, "Variances": variances},
                     attrs={"min_sizes": list(min_sizes),
                            "max_sizes": list(max_sizes or []),
                            "aspect_ratios": list(aspect_ratios),
                            "variances": list(variance),
                            "flip": flip, "clip": clip,
                            "step_w": steps[0], "step_h": steps[1],
                            "offset": offset})
    return boxes, variances


def box_coder(prior_box_var, prior_box_v, target_box,
              code_type="encode_center_size", box_normalized=True):
    helper = LayerHelper("box_coder")
    out = helper.create_tmp_variable(target_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": prior_box_v,
                             "PriorBoxVar": prior_box_var,
                             "TargetBox": target_box},
                     outputs={"OutputBox": out},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized})
    return out


def iou_similarity(x, y):
    """Pairwise IoU (reference: iou_similarity_op.cc)."""
    helper = LayerHelper("iou_similarity")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": x, "Y": y},
                     outputs={"Out": out})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5):
    """Greedy bipartite matching (reference: detection.py bipartite_match)."""
    helper = LayerHelper("bipartite_match")
    match_indices = helper.create_tmp_variable("int32")
    match_dist = helper.create_tmp_variable(dist_matrix.dtype)
    helper.append_op(type="bipartite_match",
                     inputs={"DistMat": dist_matrix},
                     outputs={"ColToRowMatchIndices": match_indices,
                              "ColToRowMatchDist": match_dist},
                     attrs={"match_type": match_type,
                            "dist_threshold": dist_threshold})
    return match_indices, match_dist


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0):
    """Per-prior target assignment (reference: detection.py target_assign)."""
    helper = LayerHelper("target_assign")
    out = helper.create_tmp_variable(input.dtype)
    out_weight = helper.create_tmp_variable("float32")
    inputs = {"X": input, "MatchIndices": matched_indices}
    if negative_indices is not None:
        inputs["NegIndices"] = negative_indices
    helper.append_op(type="target_assign", inputs=inputs,
                     outputs={"Out": out, "OutWeight": out_weight},
                     attrs={"mismatch_value": mismatch_value})
    return out, out_weight


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01):
    """Decode predicted deltas against priors, softmax the class logits,
    then multiclass NMS (reference: detection.py:125-152 — box_coder +
    softmax + transpose + multiclass_nms). `scores` is [N, M, C] raw
    logits as in the reference. Static-shape output: [N, keep_top_k, 6]
    rows (label, score, x1, y1, x2, y2), padded rows carry score -1."""
    helper = LayerHelper("detection_output")
    decoded = helper.create_tmp_variable(loc.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": prior_box,
                             "PriorBoxVar": prior_box_var,
                             "TargetBox": loc},
                     outputs={"OutputBox": decoded},
                     attrs={"code_type": "decode_center_size",
                            "box_normalized": True})
    probs = helper.create_tmp_variable(scores.dtype)
    helper.append_op(type="softmax", inputs={"X": scores},
                     outputs={"Out": probs}, attrs={"axis": -1})
    probs_t = helper.create_tmp_variable(scores.dtype)
    helper.append_op(type="transpose", inputs={"X": probs},
                     outputs={"Out": probs_t}, attrs={"axis": [0, 2, 1]})
    out = helper.create_tmp_variable(loc.dtype)
    helper.append_op(type="multiclass_nms",
                     inputs={"BBoxes": decoded, "Scores": probs_t},
                     outputs={"Out": out},
                     attrs={"background_label": background_label,
                            "nms_threshold": nms_threshold,
                            "nms_top_k": nms_top_k,
                            "keep_top_k": keep_top_k,
                            "score_threshold": score_threshold})
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             normalize=True):
    """SSD multibox loss (reference: detection.py ssd_loss:349). The
    reference chains six LoD ops; here one fused op runs the whole
    matching/mining/loss pipeline vmapped over the batch (see
    ops/detection_ops.py ssd_loss). Ground truth is dense padded:
    gt_box [N, G, 4], gt_label [N, G] with -1 marking absent rows —
    the static-shape replacement for LoD gt. Returns per-image loss
    [N, 1]."""
    helper = LayerHelper("ssd_loss")
    loss = helper.create_tmp_variable(location.dtype)
    inputs = {"Location": location, "Confidence": confidence,
              "GtBox": gt_box, "GtLabel": gt_label,
              "PriorBox": prior_box}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = prior_box_var
    helper.append_op(type="ssd_loss", inputs=inputs,
                     outputs={"Loss": loss},
                     attrs={"background_label": background_label,
                            "overlap_threshold": overlap_threshold,
                            "neg_pos_ratio": neg_pos_ratio,
                            "neg_overlap": neg_overlap,
                            "loc_loss_weight": loc_loss_weight,
                            "conf_loss_weight": conf_loss_weight,
                            "match_type": match_type,
                            "normalize": normalize})
    return loss


def multi_box_head(inputs, image, num_classes, min_sizes, max_sizes=None,
                   aspect_ratios=None, flip=True, clip=False,
                   steps=None, offset=0.5,
                   variance=(0.1, 0.1, 0.2, 0.2)):
    """Per-feature-map loc/conf heads + concatenated priors (reference:
    detection.py multi_box_head:567). For each input feature map i:
    3x3 conv heads predict num_priors_i * 4 locations and
    num_priors_i * num_classes confidences; priors come from prior_box.
    Returns (mbox_loc [N, M, 4], mbox_conf [N, M, C], boxes [M, 4],
    variances [M, 4])."""
    from . import nn, tensor
    if aspect_ratios is None:
        aspect_ratios = [[1.0]] * len(inputs)
    max_sizes = max_sizes or [None] * len(inputs)
    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = min_sizes[i]
        mins = mins if isinstance(mins, (list, tuple)) else [mins]
        maxs = max_sizes[i]
        if maxs is not None and not isinstance(maxs, (list, tuple)):
            maxs = [maxs]
        ars = aspect_ratios[i]
        ars = list(ars) if isinstance(ars, (list, tuple)) else [ars]
        step_i = (steps[i], steps[i]) if steps is not None else (0.0, 0.0)
        box, var = prior_box(feat, image, min_sizes=mins, max_sizes=maxs,
                             aspect_ratios=ars, flip=flip, clip=clip,
                             variance=list(variance), offset=offset,
                             steps=step_i)
        # priors per cell = |expanded ars| * |mins| + |maxs|, using the
        # op's OWN expansion so head channels always match prior counts
        from ..ops.detection_ops import expand_aspect_ratios
        n_ar = len(expand_aspect_ratios(ars, flip))
        num_priors = n_ar * len(mins) + (len(maxs) if maxs else 0)
        loc = nn.conv2d(feat, num_filters=num_priors * 4, filter_size=3,
                        padding=1)
        conf = nn.conv2d(feat, num_filters=num_priors * num_classes,
                         filter_size=3, padding=1)
        # [N, P*K, H, W] -> [N, H, W, P*K] -> [N, H*W*P, K]
        loc = tensor.transpose(loc, [0, 2, 3, 1])
        loc = tensor.reshape(loc, [0, -1, 4])
        conf = tensor.transpose(conf, [0, 2, 3, 1])
        conf = tensor.reshape(conf, [0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_l.append(tensor.reshape(box, [-1, 4]))
        vars_l.append(tensor.reshape(var, [-1, 4]))
    mbox_loc = locs[0] if len(locs) == 1 else tensor.concat(locs, axis=1)
    mbox_conf = confs[0] if len(confs) == 1 else \
        tensor.concat(confs, axis=1)
    boxes = boxes_l[0] if len(boxes_l) == 1 else \
        tensor.concat(boxes_l, axis=0)
    variances = vars_l[0] if len(vars_l) == 1 else \
        tensor.concat(vars_l, axis=0)
    return mbox_loc, mbox_conf, boxes, variances
