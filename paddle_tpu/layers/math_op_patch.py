"""Operator overloading on Variable (reference:
python/paddle/fluid/layers/math_op_patch.py)."""
from __future__ import annotations

from ..framework import Variable
from ..layer_helper import LayerHelper


def _scalar_op(var, scale, bias):
    helper = LayerHelper("scale")
    out = helper.create_tmp_variable(var.dtype, lod_level=var.lod_level)
    helper.append_op(type="scale", inputs={"X": var}, outputs={"Out": out},
                     attrs={"scale": float(scale), "bias": float(bias)})
    return out


def _binary_creator(op_type, reverse=False):
    def __impl__(self, other):
        if isinstance(other, (int, float)):
            if op_type == "elementwise_add":
                return _scalar_op(self, 1.0, other)
            if op_type == "elementwise_sub":
                if reverse:
                    return _scalar_op(self, -1.0, other)
                return _scalar_op(self, 1.0, -other)
            if op_type == "elementwise_mul":
                return _scalar_op(self, other, 0.0)
            if op_type == "elementwise_div" and not reverse:
                return _scalar_op(self, 1.0 / other, 0.0)
            # fall through: build a constant like self (handles -1 batch dim)
            val = other
            helper_c = LayerHelper("const_like")
            other = helper_c.create_tmp_variable(self.dtype,
                                                 lod_level=self.lod_level)
            helper_c.append_op(type="fill_constant_like",
                               inputs={"X": self}, outputs={"Out": other},
                               attrs={"value": float(val)})
        helper = LayerHelper(op_type)
        out = helper.create_tmp_variable(self.dtype,
                                         lod_level=self.lod_level)
        x, y = (other, self) if reverse else (self, other)
        helper.append_op(type=op_type, inputs={"X": x, "Y": y},
                         outputs={"Out": out}, attrs={"axis": -1})
        return out
    return __impl__


def monkey_patch_variable():
    Variable.__add__ = _binary_creator("elementwise_add")
    Variable.__radd__ = _binary_creator("elementwise_add")
    Variable.__sub__ = _binary_creator("elementwise_sub")
    Variable.__rsub__ = _binary_creator("elementwise_sub", reverse=True)
    Variable.__mul__ = _binary_creator("elementwise_mul")
    Variable.__rmul__ = _binary_creator("elementwise_mul")
    Variable.__truediv__ = _binary_creator("elementwise_div")
    Variable.__rtruediv__ = _binary_creator("elementwise_div", reverse=True)
    Variable.__pow__ = _binary_creator("elementwise_pow")
    Variable.__lt__ = _binary_creator("less_than")
    Variable.__le__ = _binary_creator("less_equal")
    Variable.__gt__ = _binary_creator("greater_than")
    Variable.__ge__ = _binary_creator("greater_equal")
    Variable.__neg__ = lambda self: _scalar_op(self, -1.0, 0.0)
