"""Input layers (reference: python/paddle/fluid/layers/io.py — data:29)."""
from __future__ import annotations

from ..framework import default_main_program, default_startup_program


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=None, stop_gradient=True, main_program=None):
    """Declare an input variable fed at run time. With append_batch_size,
    -1 is prepended as the batch dim (reference: layers/io.py:29)."""
    prog = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = prog.global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient)
    return var
