"""Input layers (reference: python/paddle/fluid/layers/io.py — data:29,
open_recordio_file:287, read_file, and the decorated readers). In-graph
readers follow the CSP-channel pattern: host-side iterator state,
ordered io_callback reads — see ops/reader_ops.py."""
from __future__ import annotations

import random as _random

from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper

__all__ = ["data", "open_recordio_file", "open_files", "read_file",
           "create_shuffle_reader", "create_double_buffer_reader",
           "create_multi_pass_reader"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         type=None, stop_gradient=True, main_program=None):
    """Declare an input variable fed at run time. With append_batch_size,
    -1 is prepended as the batch dim (reference: layers/io.py:29)."""
    prog = main_program or default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    var = prog.global_block().create_var(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        stop_gradient=stop_gradient)
    return var


class _ReaderHandle:
    """Build-time handle for an in-graph reader: the registered host
    reader id plus the static batch schema read_file bakes into the
    program."""

    def __init__(self, reader_id, var_names, shapes, dtypes):
        self.reader_id = int(reader_id)
        self.var_names = list(var_names)
        self.shapes = [list(s) for s in shapes]
        self.dtypes = list(dtypes)

    def _wrap(self, make_iter):
        from ..ops.reader_ops import register_reader
        return _ReaderHandle(register_reader(make_iter), self.var_names,
                             self.shapes, self.dtypes)

    def close(self):
        """Unregister the host reader (a decorator chain's handles are
        independent registrations; close each, or rely on
        reset_default_programs clearing the registry)."""
        from ..ops.reader_ops import unregister_reader
        unregister_reader(self.reader_id)


def _reader_schema(first_file, shapes, dtypes, var_names, caller):
    """Shared schema validation for the open_* readers."""
    from ..recordio_writer import read_recordio_feeds
    if var_names is None:
        probe = next(iter(read_recordio_feeds(first_file)))
        var_names = list(probe.keys())
    if len(var_names) != len(shapes) or len(shapes) != len(dtypes):
        raise ValueError(
            f"{caller}: {len(var_names)} vars vs {len(shapes)} shapes "
            f"vs {len(dtypes)} dtypes")
    return var_names


def open_recordio_file(filename, shapes, dtypes, lod_levels=None,
                       var_names=None):
    """In-graph reader over a recordio feed file (reference:
    layers/io.py open_recordio_file over
    operators/reader/create_recordio_file_reader_op.cc). The file holds
    the records recordio_writer.convert_reader_to_recordio_file wrote;
    `shapes`/`dtypes` declare the static per-batch schema, `var_names`
    the record keys (defaults to the record's own key order)."""
    from ..ops.reader_ops import register_reader
    from ..recordio_writer import read_recordio_feeds

    var_names = _reader_schema(filename, shapes, dtypes, var_names,
                               "open_recordio_file")
    rid = register_reader(lambda: read_recordio_feeds(filename))
    return _ReaderHandle(rid, var_names, shapes, dtypes)


def open_files(filenames, shapes, dtypes, lod_levels=None,
               var_names=None):
    """Multi-file variant (reference: layers/io.py open_files): files
    are read in order, one stream."""
    from ..recordio_writer import read_recordio_feeds

    if not filenames:
        raise ValueError("open_files: empty filename list")

    def chain():
        for fn in filenames:
            for feed in read_recordio_feeds(fn):
                yield feed

    var_names = _reader_schema(filenames[0], shapes, dtypes, var_names,
                               "open_files")
    from ..ops.reader_ops import register_reader
    rid = register_reader(chain)
    return _ReaderHandle(rid, var_names, shapes, dtypes)


def read_file(reader: _ReaderHandle):
    """Append a read op: returns one program variable per declared var,
    filled with the next batch each execution (reference read_file over
    read_op.cc). Reads keep program order (ordered callback)."""
    helper = LayerHelper("read_file")
    rid_var = helper.create_tmp_variable("int32", shape=[])
    helper.append_op(type="fill_constant", inputs={},
                     outputs={"Out": rid_var},
                     attrs={"shape": [], "dtype": "int32",
                            "value": float(reader.reader_id)})
    outs = [helper.create_variable(
        name=f"{helper.name}.{n}", dtype=dt, shape=list(s))
        for n, s, dt in zip(reader.var_names, reader.shapes,
                            reader.dtypes)]
    helper.append_op(type="read_file", inputs={"Reader": rid_var},
                     outputs={"Out": outs},
                     attrs={"var_names": reader.var_names,
                            "shapes": reader.shapes,
                            "dtypes": reader.dtypes})
    return outs if len(outs) != 1 else outs[0]


def create_shuffle_reader(reader: _ReaderHandle, buffer_size: int,
                          seed=None):
    """Buffered-shuffle decorator (reference:
    create_shuffle_reader_op.cc): fill a host buffer, yield shuffled."""
    inner = reader

    def make_iter():
        rng = _random.Random(seed)
        from ..ops.reader_ops import get_reader
        src = get_reader(inner.reader_id).make_iter()
        buf = []
        for feed in src:
            buf.append(feed)
            if len(buf) >= buffer_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return reader._wrap(make_iter)


def create_double_buffer_reader(reader: _ReaderHandle, place=None):
    """Prefetch decorator (reference:
    create_double_buffer_reader_op.cc): a background thread keeps the
    next batches ready while the program computes."""
    from ..reader import buffered as _buffered

    inner = reader

    def make_iter():
        from ..ops.reader_ops import get_reader
        return _buffered(lambda: get_reader(inner.reader_id).make_iter(),
                         size=2)()

    return reader._wrap(make_iter)


def create_multi_pass_reader(reader: _ReaderHandle, pass_num: int):
    """Epoch-loop decorator (reference:
    create_multi_pass_reader_op.cc): replay the underlying stream
    `pass_num` times before exhausting."""
    inner = reader

    def make_iter():
        from ..ops.reader_ops import get_reader
        for _ in range(int(pass_num)):
            for feed in get_reader(inner.reader_id).make_iter():
                yield feed

    return reader._wrap(make_iter)
