from .tensor import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .io import data  # noqa: F401
from . import ops  # noqa: F401  (auto-generated elementwise wrappers)
from .ops import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .csp import *  # noqa: F401,F403
from . import math_op_patch

math_op_patch.monkey_patch_variable()
