from .tensor import *  # noqa: F401,F403
from .nn import *  # noqa: F401,F403
from .io import (data, create_double_buffer_reader,  # noqa: F401
                 create_multi_pass_reader, create_shuffle_reader,
                 open_files, open_recordio_file, read_file)
from . import ops  # noqa: F401  (auto-generated elementwise wrappers)
from .ops import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .csp import *  # noqa: F401,F403
from . import math_op_patch
from .math_op_patch import monkey_patch_variable  # noqa: F401

math_op_patch.monkey_patch_variable()
