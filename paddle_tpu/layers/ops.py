"""Auto-generated thin layer wrappers for simple ops.

Reference parity: python/paddle/fluid/layers/ops.py via
layer_function_generator.py — one python function per registered unary op.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "sqrt", "rsqrt",
    "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal", "square",
    "softplus", "softsign", "log", "sign",
]

_CMP_OPS = ["equal", "not_equal", "less_than", "less_equal",
            "greater_than", "greater_equal", "logical_and", "logical_or",
            "logical_xor"]

__all__ = list(_UNARY_OPS) + list(_CMP_OPS) + [
    "uniform_random", "gaussian_random", "logical_not", "isfinite"]


def _make_unary(op_type):
    def fn(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
        helper.append_op(type=op_type, inputs={"X": x},
                         outputs={"Out": out})
        return out
    fn.__name__ = op_type
    fn.__doc__ = f"Elementwise {op_type} (auto-generated wrapper)."
    return fn


for _op in _UNARY_OPS:
    globals()[_op] = _make_unary(_op)


def _make_cmp(op_type):
    def fn(x, y, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable("bool", lod_level=x.lod_level)
        out.stop_gradient = True
        helper.append_op(type=op_type, inputs={"X": x, "Y": y},
                         outputs={"Out": out}, attrs={"axis": -1})
        return out
    fn.__name__ = op_type
    return fn


for _op in _CMP_OPS:
    globals()[_op] = _make_cmp(_op)


def logical_not(x, name=None):
    helper = LayerHelper("logical_not", name=name)
    out = helper.create_tmp_variable("bool", lod_level=x.lod_level)
    out.stop_gradient = True
    helper.append_op(type="logical_not", inputs={"X": x},
                     outputs={"Out": out})
    return out


def isfinite(x):
    helper = LayerHelper("isfinite")
    out = helper.create_tmp_variable("bool")
    out.stop_gradient = True
    helper.append_op(type="isfinite", inputs={"X": x},
                     outputs={"Out": out})
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="uniform_random", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "min": min, "max": max,
                            "seed": seed or
                            helper.main_program.desc.next_seed()})
    out.stop_gradient = True
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0):
    helper = LayerHelper("gaussian_random")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="gaussian_random", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "mean": mean, "std": std,
                            "seed": seed or
                            helper.main_program.desc.next_seed()})
    out.stop_gradient = True
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Crop x to `shape` (a list or a reference Variable) at `offsets`
    (reference: crop_op.cc)."""
    helper = LayerHelper("crop", name=name)
    out = helper.create_tmp_variable(x.dtype)
    inputs = {"X": x}
    attrs = {"offsets": list(offsets or [])}
    if shape is not None and not isinstance(shape, (list, tuple)):
        inputs["Y"] = shape
    else:
        attrs["shape"] = list(shape or [])
    helper.append_op(type="crop", inputs=inputs, outputs={"Out": out},
                     attrs=attrs)
    return out


def _make_batch_size_like(op_type):
    def fn(input, shape, dtype="float32", input_dim_idx=0,
           output_dim_idx=0, **kw):
        helper = LayerHelper(op_type)
        out = helper.create_tmp_variable(dtype)
        out.stop_gradient = True
        helper.append_op(type=op_type, inputs={"Input": input},
                         outputs={"Out": out},
                         attrs={"shape": list(shape), "dtype": dtype,
                                "input_dim_idx": input_dim_idx,
                                "output_dim_idx": output_dim_idx, **kw})
        return out
    fn.__name__ = op_type
    return fn


uniform_random_batch_size_like = _make_batch_size_like(
    "uniform_random_batch_size_like")
gaussian_random_batch_size_like = _make_batch_size_like(
    "gaussian_random_batch_size_like")
__all__ += ["crop", "uniform_random_batch_size_like",
            "gaussian_random_batch_size_like"]
