"""Tensor-construction and manipulation layers.

Reference parity: python/paddle/fluid/layers/tensor.py (create_tensor,
cast, concat, sums, assign, fill_constant, ones, zeros, reverse...).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "cast",
    "concat", "sums", "sum", "assign", "fill_constant",
    "fill_constant_batch_size_like",
    "ones", "zeros", "reverse", "reshape", "transpose", "split", "squeeze",
    "unsqueeze", "stack", "expand", "gather", "scatter", "pad", "one_hot",
    "argmax", "argmin", "shape", "range", "linspace", "zeros_like",
    "ones_like", "diag", "eye", "slice", "Print",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    helper = LayerHelper("create_parameter")
    from ..layer_helper import ParamAttr
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(shape=shape, dtype=dtype,
                                        persistable=persistable,
                                        name=name)
    from ..initializer import ConstantInitializer
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    out = helper.create_tmp_variable(dtype, lod_level=x.lod_level)
    helper.append_op(type="cast", inputs={"X": x}, outputs={"Out": out},
                     attrs={"out_dtype": dtype})
    return out


def concat(input: Sequence[Variable], axis: int = 0, name=None):
    helper = LayerHelper("concat", name=name)
    shape = None
    ranks = {len(v.shape) for v in input if v.shape is not None}
    if len(ranks) == 1 and all(v.shape is not None for v in input):
        shape = list(input[0].shape)
        ax = axis if axis >= 0 else len(shape) + axis
        if 0 <= ax < len(shape):
            dims = [v.shape[ax] for v in input]
            # builtins.sum: the module-level `sum = sums` layer alias
            # (reference API parity) shadows the builtin here
            import builtins
            shape[ax] = -1 if any(d is None or d < 0 for d in dims) \
                else builtins.sum(dims)
        else:
            # Declared shapes are loose metadata (ragged vars declare 2D);
            # leave it to the runtime op when the axis is out of range.
            shape = None
    out = helper.create_tmp_variable(input[0].dtype,
                                     lod_level=input[0].lod_level,
                                     shape=shape)
    helper.append_op(type="concat", inputs={"X": list(input)},
                     outputs={"Out": out}, attrs={"axis": axis})
    return out


def sums(input: Sequence[Variable], out=None):
    helper = LayerHelper("sums")
    out = out or helper.create_tmp_variable(input[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": out})
    return out


# reference layers/ops.py exports `sum` (same op) alongside `sums`
sum = sums  # noqa: A001


def Print(input, first_n=-1, message=None, summarize=-1,  # noqa: N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug-print a tensor from inside the compiled program
    (reference: layers/control_flow.py Print over print_op.cc; the
    formatting knobs are accepted for API parity — jax.debug.print
    renders the value)."""
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(input.dtype,
                                     lod_level=input.lod_level,
                                     shape=input.shape)
    helper.append_op(type="print", inputs={"X": input},
                     outputs={"Out": out},
                     attrs={"message": message or input.name})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray):
        output = output or helper.create_tmp_variable(str(input.dtype))
        helper.append_op(type="assign_value", outputs={"Out": output},
                         attrs={"shape": list(input.shape),
                                "dtype": str(input.dtype),
                                "values": input.reshape(-1).tolist()})
    else:
        output = output or helper.create_tmp_variable(input.dtype)
        helper.append_op(type="assign", inputs={"X": input},
                         outputs={"Out": output})
    return output


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    out = out or helper.create_tmp_variable(dtype, shape=list(shape))
    helper.append_op(type="fill_constant", outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    # Static-shape regime: batch dim comes from the input's known shape.
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="fill_constant_batch_size_like",
                     inputs={"Input": input}, outputs={"Out": out},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    out = out or helper.create_tmp_variable(x.dtype)
    helper.append_op(type="fill_zeros_like", inputs={"X": x},
                     outputs={"Out": out})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    out = out or helper.create_tmp_variable(x.dtype)
    helper.append_op(type="fill_constant_like", inputs={"X": x},
                     outputs={"Out": out}, attrs={"value": 1.0})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="reverse", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis if isinstance(axis, (list, tuple))
                            else [axis]})
    return out


def reshape(x, shape, inplace=False, name=None, act=None):
    helper = LayerHelper("reshape", name=name, act=act)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="reshape", inputs={"X": x}, outputs={"Out": out},
                     attrs={"shape": list(shape)})
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="transpose", inputs={"X": x},
                     outputs={"Out": out}, attrs={"axis": list(perm)})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = None
    else:
        num = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [helper.create_tmp_variable(input.dtype) for _ in range(num)]
    helper.append_op(type="split", inputs={"X": input},
                     outputs={"Out": outs},
                     attrs={"num": num if sections is None else 0,
                            "sections": sections or [],
                            "axis": dim})
    return outs


def squeeze(input, axes=None, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="squeeze", inputs={"X": input},
                     outputs={"Out": out}, attrs={"axes": axes or []})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="unsqueeze", inputs={"X": input},
                     outputs={"Out": out}, attrs={"axes": axes})
    return out


def stack(x: Sequence[Variable], axis: int = 0):
    helper = LayerHelper("stack")
    out = helper.create_tmp_variable(x[0].dtype)
    helper.append_op(type="stack", inputs={"X": list(x)},
                     outputs={"Y": out}, attrs={"axis": axis})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="expand", inputs={"X": x}, outputs={"Out": out},
                     attrs={"expand_times": list(expand_times)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="gather", inputs={"X": input, "Index": index},
                     outputs={"Out": out})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="scatter",
                     inputs={"X": input, "Ids": index, "Updates": updates},
                     outputs={"Out": out}, attrs={"overwrite": overwrite})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="pad", inputs={"X": x}, outputs={"Out": out},
                     attrs={"paddings": list(paddings),
                            "pad_value": float(pad_value)})
    return out


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_tmp_variable("float32")
    helper.append_op(type="one_hot", inputs={"X": input},
                     outputs={"Out": out}, attrs={"depth": depth})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("argmax")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="arg_max", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def argmin(x, axis=0):
    helper = LayerHelper("argmin")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="arg_min", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="shape", inputs={"X": input},
                     outputs={"Out": out})
    return out


def range(start, end, step, dtype="int64"):
    helper = LayerHelper("range")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="range", outputs={"Out": out},
                     attrs={"start": start, "end": end, "step": step})
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="linspace", outputs={"Out": out},
                     attrs={"start": float(start), "stop": float(stop),
                            "num": int(num)})
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_tmp_variable(diagonal.dtype)
    helper.append_op(type="diag", inputs={"Diagonal": diagonal},
                     outputs={"Out": out})
    return out


def eye(num_rows, num_columns=None, dtype="float32"):
    helper = LayerHelper("eye")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="eye", outputs={"Out": out},
                     attrs={"num_rows": num_rows,
                            "num_columns": num_columns or num_rows})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="slice", inputs={"Input": input},
                     outputs={"Out": out},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out
