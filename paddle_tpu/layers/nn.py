"""Neural-net layer functions — the primary user API.

Reference parity: python/paddle/fluid/layers/nn.py (fc:83, embedding:218,
dynamic_lstm:277, conv2d:1150, pool2d, batch_norm:1508, layer_norm:1597,
dropout, cross_entropy, softmax_with_cross_entropy:3165, sequence_*,
topk, accuracy, beam_search, matmul, nce:2836...). Each function builds
IR ops; XLA does the fusing.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from ..framework import Variable
from ..layer_helper import LayerHelper, ParamAttr
from ..initializer import ConstantInitializer, NormalInitializer, \
    XavierInitializer

__all__ = [
    "fc", "embedding", "dynamic_lstm", "dynamic_gru", "conv2d",
    "depthwise_conv2d", "conv2d_transpose", "pool2d", "batch_norm",
    "layer_norm", "dropout", "cross_entropy", "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits", "square_error_cost", "accuracy",
    "topk", "sequence_pool", "sequence_conv", "sequence_softmax",
    "sequence_expand", "sequence_first_step", "sequence_last_step",
    "sequence_reshape", "sequence_mask", "sequence_pad", "sequence_unpad",
    "sequence_reverse",
    "nested_sequence_flatten", "nested_sequence_pack",
    "im2sequence", "matmul", "mul", "softmax", "log_softmax", "relu", "lrn",
    "l2_normalize", "mean", "reduce_sum", "reduce_mean", "reduce_max",
    "reduce_min", "reduce_prod", "warpctc", "nce", "smooth_l1", "one_hot_v2",
    "clip", "clip_by_norm", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_max",
    "elementwise_min", "elementwise_pow", "scale", "cos_sim", "dot",
    "row_conv", "maxout", "scaled_dot_product_attention", "hsigmoid",
    "auc", "huber_loss", "log_loss", "kldiv_loss", "margin_rank_loss",
    "hinge_loss", "edit_distance", "pad2d", "leaky_relu", "elu", "pow",
    "swish", "hard_sigmoid", "relu6", "soft_relu", "flatten", "gelu",
    "beam_search", "beam_search_decode", "increment", "cumsum",
    "linear_chain_crf", "crf_decoding",
    "multiplex", "lstm_unit", "gru_unit", "dynamic_lstmp",
    "ctc_greedy_decoder", "chunk_eval", "autoincreased_step_counter",
    "lod_reset", "prelu", "label_smooth", "rank_loss", "roi_pool",
    "bilinear_interp", "nearest_interp", "resize_bilinear", "upsample",
    "sampling_id", "random_crop", "random_flip", "image_normalize",
    "augment_image",
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None, dtype=None):
    """Fully-connected layer (reference: layers/nn.py:83). Multiple inputs
    are projected separately and summed, as in the reference."""
    helper = LayerHelper("fc", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    dtype = dtype or inputs[0].dtype
    mul_results = []
    for inp in inputs:
        in_shape = inp.shape
        flat_dim = 1
        for d in in_shape[num_flatten_dims:]:
            flat_dim *= int(d)
        w = helper.create_parameter(helper.param_attr,
                                    shape=[flat_dim, size], dtype=dtype)
        out_shape = list(in_shape[:num_flatten_dims]) + [size]
        tmp = helper.create_tmp_variable(dtype, lod_level=inp.lod_level,
                                         shape=out_shape)
        helper.append_op(type="mul", inputs={"X": inp, "Y": w},
                         outputs={"Out": tmp},
                         attrs={"x_num_col_dims": num_flatten_dims
                                if inp.lod_level == 0 else inp.lod_level + 1,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(dtype)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": pre_bias})
    pre_act = helper.append_bias_op(pre_bias, size=size)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32",
              shard_axis="model"):
    """Embedding lookup (reference: layers/nn.py:218). is_distributed
    row-shards the table over the mesh `shard_axis` and looks up via
    shard_map + psum with row-sparse backward (parallel/sparse.py) —
    the ICI replacement for the reference's pserver sparse path.
    is_sparse is accepted for reference API parity only: on TPU the
    single-chip gradient is a dense scatter-add XLA fuses into the
    step, so the flag has no separate path here."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype)
    out = helper.create_tmp_variable(dtype, lod_level=input.lod_level)
    helper.append_op(type="lookup_table",
                     inputs={"W": w, "Ids": input}, outputs={"Out": out},
                     attrs={"is_sparse": is_sparse,
                            "is_distributed": is_distributed,
                            "shard_axis": shard_axis,
                            "padding_idx": -1 if padding_idx is None
                            else padding_idx})
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """Dynamic-length LSTM over a ragged input of gate pre-activations
    [*, 4*hidden] (reference: layers/nn.py:277 / lstm_op.cc)."""
    helper = LayerHelper("dynamic_lstm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    hidden_size = size // 4
    w = helper.create_parameter(helper.param_attr,
                                shape=[hidden_size, 4 * hidden_size],
                                dtype=dtype)
    bias_size = 4 * hidden_size if not use_peepholes else 7 * hidden_size
    b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                shape=[1, bias_size], dtype=dtype,
                                is_bias=True)
    hidden = helper.create_tmp_variable(dtype, lod_level=1,
                                        shape=[-1, hidden_size])
    cell = helper.create_tmp_variable(dtype, lod_level=1,
                                      shape=[-1, hidden_size])
    last_h = helper.create_tmp_variable(dtype, shape=[-1, hidden_size])
    last_c = helper.create_tmp_variable(dtype, shape=[-1, hidden_size])
    inputs = {"Input": input, "Weight": w, "Bias": b}
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op(type="lstm", inputs=inputs,
                     outputs={"Hidden": hidden, "Cell": cell,
                              "LastH": last_h, "LastC": last_c},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None, h_0=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", dtype="float32"):
    helper = LayerHelper("dynamic_gru", param_attr=param_attr,
                         bias_attr=bias_attr)
    w = helper.create_parameter(helper.param_attr, shape=[size, 3 * size],
                                dtype=dtype)
    b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                shape=[1, 3 * size], dtype=dtype,
                                is_bias=True)
    hidden = helper.create_tmp_variable(dtype, lod_level=1,
                                        shape=[-1, size])
    last_h = helper.create_tmp_variable(dtype, shape=[-1, size])
    inputs = {"Input": input, "Weight": w, "Bias": b}
    if h_0 is not None:
        inputs["H0"] = h_0
    helper.append_op(type="gru", inputs=inputs,
                     outputs={"Hidden": hidden, "LastH": last_h},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    return hidden


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           use_cudnn=True, name=None):
    """2-D convolution, NCHW (reference: layers/nn.py:1150)."""
    helper = LayerHelper("conv2d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    num_channels = int(input.shape[1])
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    import math
    fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
    std = math.sqrt(2.0 / fan_in)
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=NormalInitializer(0.0, std))
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="conv2d",
                     inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": _pair(stride),
                            "paddings": _pair(padding),
                            "dilations": _pair(dilation),
                            "groups": groups})
    pre_act = _append_channel_bias(helper, pre_bias, num_filters)
    return helper.append_activation(pre_act)


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def _append_channel_bias(helper, pre_bias, channels=None):
    bias_attr = helper.bias_attr
    if bias_attr is None:
        return pre_bias
    if channels is None:
        channels = int(pre_bias.shape[1]) if pre_bias.shape else None
    b = helper.create_parameter(bias_attr, shape=[channels],
                                dtype=pre_bias.dtype, is_bias=True)
    out = helper.create_tmp_variable(pre_bias.dtype)
    helper.append_op(type="elementwise_add",
                     inputs={"X": pre_bias, "Y": b},
                     outputs={"Out": out}, attrs={"axis": 1})
    return out


def depthwise_conv2d(input, num_filters, filter_size, stride=1, padding=0,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("depthwise_conv2d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, 1] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="depthwise_conv2d",
                     inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": _pair(stride),
                            "paddings": _pair(padding),
                            "dilations": [1, 1]})
    pre_act = _append_channel_bias(helper, pre_bias, num_filters)
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    in_channels = int(input.shape[1])
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    w = helper.create_parameter(
        helper.param_attr, shape=[in_channels, num_filters] + list(
            filter_size), dtype=dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op(type="conv2d_transpose",
                     inputs={"Input": input, "Filter": w},
                     outputs={"Output": pre_bias},
                     attrs={"strides": _pair(stride),
                            "paddings": _pair(padding),
                            "dilations": _pair(dilation)})
    pre_act = _append_channel_bias(helper, pre_bias, num_filters)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=2, pool_type="max", pool_stride=2,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="pool2d", inputs={"X": input},
                     outputs={"Out": out},
                     attrs={"pooling_type": pool_type,
                            "ksize": _pair(pool_size),
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding),
                            "global_pooling": global_pooling,
                            "exclusive": exclusive})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None):
    """Batch normalization with persistable moving stats
    (reference: layers/nn.py:1508)."""
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    ch = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    scale = helper.create_parameter(
        helper.param_attr, shape=[ch], dtype=dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                   shape=[ch], dtype=dtype, is_bias=True)
    mean = helper.create_global_variable(
        shape=[ch], dtype=dtype, persistable=True,
        name=moving_mean_name or None)
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        shape=[ch], dtype=dtype, persistable=True,
        name=moving_variance_name or None)
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = helper.create_tmp_variable(dtype)
    saved_var = helper.create_tmp_variable(dtype)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="batch_norm",
                     inputs={"X": input, "Scale": scale, "Bias": bias,
                             "Mean": mean, "Variance": variance},
                     outputs={"Y": out, "MeanOut": mean,
                              "VarianceOut": variance,
                              "SavedMean": saved_mean,
                              "SavedVariance": saved_var},
                     attrs={"momentum": momentum, "epsilon": epsilon,
                            "is_test": is_test,
                            "data_layout": data_layout})
    return helper.append_activation(out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    norm_dim = 1
    for d in input.shape[begin_norm_axis:]:
        norm_dim *= int(d)
    inputs = {"X": input}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, shape=[norm_dim], dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        inputs["Scale"] = s
    if shift:
        b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                    shape=[norm_dim], dtype=dtype,
                                    is_bias=True)
        inputs["Bias"] = b
    out = helper.create_tmp_variable(dtype)
    mean = helper.create_tmp_variable(dtype)
    var = helper.create_tmp_variable(dtype)
    helper.append_op(type="layer_norm", inputs=inputs,
                     outputs={"Y": out, "Mean": mean, "Variance": var},
                     attrs={"epsilon": epsilon,
                            "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
    mask = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="dropout", inputs={"X": x},
                     outputs={"Out": out, "Mask": mask},
                     attrs={"dropout_prob": dropout_prob,
                            "is_test": is_test,
                            "seed": seed or helper.main_program.desc.next_seed(),
                            "dropout_implementation": dropout_implementation})
    return out


def random_crop(x, shape, pad=0, seed=None, name=None):
    """Per-sample random spatial crop of an NCHW batch to
    ``shape=[h, w]`` after zero-padding ``pad`` on each spatial edge
    (ops/augment_ops.py — runs on device where XLA fuses it into the
    step). Deterministic under the program seed."""
    helper = LayerHelper("random_crop", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="random_crop", inputs={"X": x},
                     outputs={"Out": out},
                     attrs={"shape": list(shape), "pad": int(pad),
                            "seed":
                                seed or helper.main_program.desc.next_seed()})
    return out


def random_flip(x, prob=0.5, seed=None, name=None):
    """Per-sample horizontal flip (last axis) with probability `prob`
    (ops/augment_ops.py)."""
    helper = LayerHelper("random_flip", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="random_flip", inputs={"X": x},
                     outputs={"Out": out},
                     attrs={"prob": float(prob),
                            "seed":
                                seed or helper.main_program.desc.next_seed()})
    return out


def image_normalize(x, mean, std, scale=1.0, dtype="float32", name=None):
    """Per-channel ``(x * scale - mean) / std`` for NCHW batches,
    emitting `dtype` ("bfloat16" = the TPU training path). Feed the
    reader's raw uint8 batch straight in: the float conversion happens
    on device (ops/augment_ops.py), not on the input-pipeline host."""
    helper = LayerHelper("image_normalize", name=name)
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="image_normalize", inputs={"X": x},
                     outputs={"Out": out},
                     attrs={"mean": [float(m) for m in mean],
                            "std": [float(s) for s in std],
                            "scale": float(scale), "dtype": dtype})
    return out


def augment_image(x, crop_shape=None, pad=0, flip_prob=0.5,
                  mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
                  scale=1.0 / 255.0, dtype="float32", is_test=False):
    """The standard train-time image augmentation chain as device ops:
    [random_crop] -> random_flip -> image_normalize. With is_test=True
    the random stages are skipped (center behaviour: no crop offset
    support — pass crop_shape=None and pre-sized eval batches)."""
    if not is_test:
        if crop_shape is not None:
            x = random_crop(x, crop_shape, pad=pad)
        if flip_prob > 0:
            x = random_flip(x, prob=flip_prob)
    return image_normalize(x, mean, std, scale=scale, dtype=dtype)


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper("cross_entropy")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="cross_entropy",
                     inputs={"X": input, "Label": label},
                     outputs={"Y": out}, attrs={"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = helper.create_tmp_variable(logits.dtype)
    loss = helper.create_tmp_variable(logits.dtype)
    helper.append_op(type="softmax_with_cross_entropy",
                     inputs={"Logits": logits, "Label": label},
                     outputs={"Softmax": softmax, "Loss": loss},
                     attrs={"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="sigmoid_cross_entropy_with_logits",
                     inputs={"X": x, "Label": label}, outputs={"Out": out})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="square_error_cost",
                     inputs={"X": input, "Y": label}, outputs={"Out": out})
    return out


def accuracy(input, label, k=1, correct=None, total=None):
    """Classification accuracy (reference: layers/nn.py accuracy via
    accuracy_op.cc): top-k over logits then compare with labels."""
    helper = LayerHelper("accuracy")
    topk_out = helper.create_tmp_variable(input.dtype)
    topk_indices = helper.create_tmp_variable("int64")
    helper.append_op(type="top_k", inputs={"X": input},
                     outputs={"Out": topk_out, "Indices": topk_indices},
                     attrs={"k": k})
    acc_out = helper.create_tmp_variable("float32")
    correct = correct or helper.create_tmp_variable("int32")
    total = total or helper.create_tmp_variable("int32")
    helper.append_op(type="accuracy",
                     inputs={"Out": topk_out, "Indices": topk_indices,
                             "Label": label},
                     outputs={"Accuracy": acc_out, "Correct": correct,
                              "Total": total})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200):
    helper = LayerHelper("auc")
    auc_out = helper.create_tmp_variable("float32")
    tp = helper.create_tmp_variable("float32")
    fp = helper.create_tmp_variable("float32")
    tn = helper.create_tmp_variable("float32")
    fn = helper.create_tmp_variable("float32")
    helper.append_op(type="auc",
                     inputs={"Predict": input, "Label": label},
                     outputs={"AUC": auc_out, "TPOut": tp, "FPOut": fp,
                              "TNOut": tn, "FNOut": fn},
                     attrs={"num_thresholds": num_thresholds})
    return auc_out


def topk(input, k):
    helper = LayerHelper("top_k")
    values = helper.create_tmp_variable(input.dtype)
    indices = helper.create_tmp_variable("int64")
    helper.append_op(type="top_k", inputs={"X": input},
                     outputs={"Out": values, "Indices": indices},
                     attrs={"k": k})
    return values, indices


# -- sequence layers --------------------------------------------------------

def sequence_pool(input, pool_type):
    helper = LayerHelper("sequence_pool")
    # reduction keeps the per-step feature shape
    out = helper.create_tmp_variable(
        input.dtype, shape=list(input.shape) if input.shape else None)
    # both spellings circulate: fluid pool2d-style "avg", v2 "average"
    ptype = {"AVG": "AVERAGE"}.get(pool_type.upper(), pool_type.upper())
    helper.append_op(type="sequence_pool", inputs={"X": input},
                     outputs={"Out": out},
                     attrs={"pooltype": ptype})
    return out


def sequence_first_step(input):
    helper = LayerHelper("sequence_first_step")
    out = helper.create_tmp_variable(
        input.dtype, shape=list(input.shape) if input.shape else None)
    helper.append_op(type="sequence_first_step", inputs={"X": input},
                     outputs={"Out": out})
    return out


def sequence_last_step(input):
    helper = LayerHelper("sequence_last_step")
    out = helper.create_tmp_variable(
        input.dtype, shape=list(input.shape) if input.shape else None)
    helper.append_op(type="sequence_last_step", inputs={"X": input},
                     outputs={"Out": out})
    return out


def sequence_reverse(x, name=None):
    """Reverse each sequence's valid steps (reference:
    sequence_reverse_op.h)."""
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_tmp_variable(x.dtype, lod_level=1)
    helper.append_op(type="sequence_reverse", inputs={"X": x},
                     outputs={"Y": out})
    return out


def nested_sequence_flatten(input):
    """Nested ragged -> one level shallower (level-2
    paragraph->sentence->token becomes a level-1 batch of sub-sequences;
    deeper LoD peels one level per call). See ops/sequence_ops.py."""
    helper = LayerHelper("nested_sequence_flatten")
    out = helper.create_tmp_variable(
        input.dtype, lod_level=max(1, (input.lod_level or 2) - 1))
    helper.append_op(type="nested_sequence_flatten", inputs={"X": input},
                     outputs={"Out": out})
    return out


def nested_sequence_pack(input, ref):
    """Per-sub-sequence dense rows -> level-1 ragged over the outer level
    of `ref` (a level-2 ragged variable)."""
    helper = LayerHelper("nested_sequence_pack")
    # batch dim becomes the outer level; feature dims carry over (shape
    # inference can't see that input's batch is n*max_sub of ref)
    shape = ([-1] + list(input.shape[1:])) if input.shape else None
    out = helper.create_tmp_variable(input.dtype, lod_level=1, shape=shape)
    helper.append_op(type="nested_sequence_pack",
                     inputs={"X": input, "Ref": ref},
                     outputs={"Out": out})
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    dtype = input.dtype
    in_dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[filter_size * in_dim, num_filters],
                                dtype=dtype)
    pre_bias = helper.create_tmp_variable(dtype, lod_level=1)
    helper.append_op(type="sequence_conv",
                     inputs={"X": input, "Filter": w},
                     outputs={"Out": pre_bias},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2),
                            "contextStride": filter_stride})
    pre_act = helper.append_bias_op(pre_bias, size=num_filters)
    return helper.append_activation(pre_act)


def sequence_softmax(input):
    helper = LayerHelper("sequence_softmax")
    out = helper.create_tmp_variable(input.dtype, lod_level=1)
    helper.append_op(type="sequence_softmax", inputs={"X": input},
                     outputs={"Out": out})
    return out


def sequence_expand(x, y, ref_level=-1):
    helper = LayerHelper("sequence_expand")
    out = helper.create_tmp_variable(x.dtype, lod_level=1)
    helper.append_op(type="sequence_expand", inputs={"X": x, "Y": y},
                     outputs={"Out": out}, attrs={"ref_level": ref_level})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_tmp_variable(input.dtype, lod_level=1)
    helper.append_op(type="sequence_reshape", inputs={"X": input},
                     outputs={"Out": out}, attrs={"new_dim": new_dim})
    return out


def sequence_mask(x, maxlen, dtype="float32"):
    helper = LayerHelper("sequence_mask")
    out = helper.create_tmp_variable(dtype)
    helper.append_op(type="sequence_mask", inputs={"X": x},
                     outputs={"Y": out}, attrs={"maxlen": maxlen})
    return out


def sequence_pad(x, pad_value=None, maxlen=None):
    helper = LayerHelper("sequence_pad")
    out = helper.create_tmp_variable(x.dtype)
    length = helper.create_tmp_variable("int64")
    helper.append_op(type="sequence_pad", inputs={"X": x},
                     outputs={"Out": out, "Length": length})
    return out, length


def sequence_unpad(x, length):
    helper = LayerHelper("sequence_unpad")
    out = helper.create_tmp_variable(x.dtype, lod_level=1)
    helper.append_op(type="sequence_unpad",
                     inputs={"X": x, "Length": length},
                     outputs={"Out": out})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_tmp_variable(input.dtype, lod_level=1)
    helper.append_op(type="im2sequence", inputs={"X": input},
                     outputs={"Out": out},
                     attrs={"kernels": _pair(filter_size),
                            "strides": _pair(stride),
                            "paddings": _pair(padding)})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    dtype = input.dtype
    d = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[future_context_size + 1, d],
                                dtype=dtype)
    out = helper.create_tmp_variable(dtype, lod_level=1)
    helper.append_op(type="row_conv",
                     inputs={"X": input, "Filter": w},
                     outputs={"Out": out})
    return helper.append_activation(out)


# -- math wrappers ----------------------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="matmul", inputs={"X": x, "Y": y},
                     outputs={"Out": out},
                     attrs={"transpose_X": transpose_x,
                            "transpose_Y": transpose_y, "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="mul", inputs={"X": x, "Y": y},
                     outputs={"Out": out},
                     attrs={"x_num_col_dims": x_num_col_dims,
                            "y_num_col_dims": y_num_col_dims})
    return out


def _unary(op_type):
    def fn(x, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
        helper.append_op(type=op_type, inputs={"X": x},
                         outputs={"Out": out})
        return out
    fn.__name__ = op_type
    return fn


relu = _unary("relu")
gelu = _unary("gelu")


def softmax(input, axis=-1, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="softmax", inputs={"X": input},
                     outputs={"Out": out}, attrs={"axis": axis})
    return out


def log_softmax(input, axis=-1):
    helper = LayerHelper("log_softmax")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="log_softmax", inputs={"X": input},
                     outputs={"Out": out}, attrs={"axis": axis})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_tmp_variable(x.dtype, shape=[1])
    helper.append_op(type="mean", inputs={"X": x}, outputs={"Out": out})
    return out


def _reduce(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(input.dtype)
        helper.append_op(type=op_type, inputs={"X": input},
                         outputs={"Out": out},
                         attrs={"dim": dim, "keep_dim": keep_dim,
                                "reduce_all": dim is None})
        return out
    fn.__name__ = op_type
    return fn


reduce_sum = _reduce("reduce_sum")
reduce_mean = _reduce("reduce_mean")
reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")
reduce_prod = _reduce("reduce_prod")


def _binary(op_type):
    def fn(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, act=act, name=name)
        out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
        helper.append_op(type=op_type, inputs={"X": x, "Y": y},
                         outputs={"Out": out}, attrs={"axis": axis})
        return helper.append_activation(out)
    fn.__name__ = op_type
    return fn


elementwise_add = _binary("elementwise_add")
elementwise_sub = _binary("elementwise_sub")
elementwise_mul = _binary("elementwise_mul")
elementwise_div = _binary("elementwise_div")
elementwise_max = _binary("elementwise_max")
elementwise_min = _binary("elementwise_min")
elementwise_pow = _binary("elementwise_pow")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_tmp_variable(x.dtype, lod_level=x.lod_level)
    helper.append_op(type="scale", inputs={"X": x}, outputs={"Out": out},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(x.dtype)
    helper.append_op(type="increment", inputs={"X": x},
                     outputs={"Out": out}, attrs={"step": float(value)})
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="cumsum", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis, "exclusive": exclusive,
                            "reverse": reverse})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="clip", inputs={"X": x}, outputs={"Out": out},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="clip_by_norm", inputs={"X": x},
                     outputs={"Out": out},
                     attrs={"max_norm": float(max_norm)})
    return out


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_tmp_variable(x.dtype)
    norm = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="l2_normalize", inputs={"X": x},
                     outputs={"Out": out, "Norm": norm},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_tmp_variable(input.dtype)
    mid = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="lrn", inputs={"X": input},
                     outputs={"Out": out, "MidOut": mid},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def cos_sim(x, y):
    helper = LayerHelper("cos_sim")
    out = helper.create_tmp_variable(x.dtype)
    xn = helper.create_tmp_variable(x.dtype)
    yn = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="cos_sim", inputs={"X": x, "Y": y},
                     outputs={"Out": out, "XNorm": xn, "YNorm": yn})
    return out


def dot(x, y):
    helper = LayerHelper("dot")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="dot", inputs={"X": x, "Y": y},
                     outputs={"Out": out})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="maxout", inputs={"X": x}, outputs={"Out": out},
                     attrs={"groups": groups})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="flatten", inputs={"X": x}, outputs={"Out": out},
                     attrs={"axis": axis})
    return out


def _act_layer(op_type, **default_attrs):
    def fn(x, name=None, **kw):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_tmp_variable(x.dtype)
        attrs = dict(default_attrs)
        attrs.update(kw)
        helper.append_op(type=op_type, inputs={"X": x},
                         outputs={"Out": out}, attrs=attrs)
        return out
    fn.__name__ = op_type
    return fn


leaky_relu = _act_layer("leaky_relu", alpha=0.02)
elu = _act_layer("elu", alpha=1.0)
pow = _act_layer("pow", factor=1.0)
swish = _act_layer("swish", beta=1.0)
hard_sigmoid = _act_layer("hard_sigmoid", slope=0.2, offset=0.5)
relu6 = _act_layer("relu6")
soft_relu = _act_layer("soft_relu", threshold=40.0)


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          name=None):
    helper = LayerHelper("pad2d", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="pad2d", inputs={"X": input},
                     outputs={"Out": out},
                     attrs={"paddings": list(paddings), "mode": mode,
                            "pad_value": float(pad_value)})
    return out


# -- losses / misc ----------------------------------------------------------

def smooth_l1(x, y, sigma=1.0):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_tmp_variable(x.dtype)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="smooth_l1_loss", inputs={"X": x, "Y": y},
                     outputs={"Diff": diff, "Out": out},
                     attrs={"sigma": sigma})
    return out


def huber_loss(input, label, delta=1.0):
    helper = LayerHelper("huber_loss")
    residual = helper.create_tmp_variable(input.dtype)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="huber_loss",
                     inputs={"X": input, "Y": label},
                     outputs={"Residual": residual, "Out": out},
                     attrs={"delta": delta})
    return out


def log_loss(input, label, epsilon=1e-4):
    helper = LayerHelper("log_loss")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="log_loss",
                     inputs={"Predicted": input, "Labels": label},
                     outputs={"Loss": out}, attrs={"epsilon": epsilon})
    return out


def kldiv_loss(x, target, reduction="mean"):
    helper = LayerHelper("kldiv_loss")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="kldiv_loss",
                     inputs={"X": x, "Target": target},
                     outputs={"Loss": out}, attrs={"reduction": reduction})
    return out


def margin_rank_loss(label, left, right, margin=0.1):
    helper = LayerHelper("margin_rank_loss")
    out = helper.create_tmp_variable(left.dtype)
    act = helper.create_tmp_variable(left.dtype)
    helper.append_op(type="margin_rank_loss",
                     inputs={"X1": left, "X2": right, "Label": label},
                     outputs={"Out": out, "Activated": act},
                     attrs={"margin": margin})
    return out


def hinge_loss(logits, labels):
    helper = LayerHelper("hinge_loss")
    out = helper.create_tmp_variable(logits.dtype)
    helper.append_op(type="hinge_loss",
                     inputs={"Logits": logits, "Labels": labels},
                     outputs={"Loss": out})
    return out


def edit_distance(input, label, normalized=False):
    helper = LayerHelper("edit_distance")
    out = helper.create_tmp_variable("float32")
    seq_num = helper.create_tmp_variable("int64")
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": input, "Refs": label},
                     outputs={"Out": out, "SequenceNum": seq_num},
                     attrs={"normalized": normalized})
    return out, seq_num


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10):
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(helper.bias_attr or ParamAttr(),
                                shape=[num_total_classes],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="nce",
                     inputs={"Input": input, "Label": label, "Weight": w,
                             "Bias": b},
                     outputs={"Cost": cost},
                     attrs={"num_neg_samples": num_neg_samples,
                            "seed": helper.main_program.desc.next_seed()})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None):
    """Hierarchical sigmoid via a complete binary tree over classes
    (reference: hierarchical_sigmoid_op.cc) — composed from dense ops."""
    import math
    helper = LayerHelper("hsigmoid", param_attr=param_attr,
                         bias_attr=bias_attr)
    # Simplified capability-parity implementation: logistic ova reduction.
    dim = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[dim, num_classes], dtype=input.dtype)
    logits = mul(input, w)
    lbl = one_hot_v2(label, num_classes)
    loss = sigmoid_cross_entropy_with_logits(logits, lbl)
    return reduce_sum(loss, dim=1, keep_dim=True)


def one_hot_v2(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_tmp_variable("float32")
    helper.append_op(type="one_hot", inputs={"X": input},
                     outputs={"Out": out}, attrs={"depth": depth})
    return out


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss over ragged logits/labels (reference: warpctc_op.cc wraps
    the warp-ctc CUDA lib; here a pure-XLA dynamic-program)."""
    helper = LayerHelper("warpctc")
    loss = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="warpctc",
                     inputs={"Logits": input, "Label": label},
                     outputs={"Loss": loss},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    helper = LayerHelper("scaled_dot_product_attention")
    shape = None
    if queries.shape is not None and values.shape is not None:
        shape = list(queries.shape[:-1]) + [values.shape[-1]]
    out = helper.create_tmp_variable(queries.dtype,
                                     lod_level=queries.lod_level,
                                     shape=shape)
    helper.append_op(type="scaled_dot_product_attention",
                     inputs={"Q": queries, "K": keys, "V": values},
                     outputs={"Out": out})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=False):
    """One beam-search expansion step (reference: beam_search_op.cc),
    fixed-beam dense form: scores [batch, beam, cand] (or flat
    [batch*beam, cand]) step log-probs; totals accumulate against
    pre_scores unless `is_accumulated`. Finished lanes (pre_id ==
    end_id) are frozen instead of pruned — see ops/beam_search_ops.py.
    Initialize pre_scores to 0 for lane 0 and a large negative value
    for other lanes so identical initial beams don't duplicate."""
    helper = LayerHelper("beam_search")
    selected_ids = helper.create_tmp_variable(ids.dtype)
    selected_scores = helper.create_tmp_variable(scores.dtype)
    parent_idx = helper.create_tmp_variable("int32")
    inputs = {"pre_ids": pre_ids, "ids": ids, "scores": scores}
    if pre_scores is not None:
        inputs["pre_scores"] = pre_scores
    helper.append_op(type="beam_search",
                     inputs=inputs,
                     outputs={"selected_ids": selected_ids,
                              "selected_scores": selected_scores,
                              "parent_idx": parent_idx},
                     attrs={"beam_size": beam_size, "end_id": end_id,
                            "is_accumulated": is_accumulated})
    return selected_ids, selected_scores, parent_idx


def beam_search_decode(ids, scores, beam_size, end_id, parents=None,
                       length=None):
    """Backtrack beam-search step arrays into sentences (reference:
    beam_search_decode_op.cc). `ids`/`scores`/`parents` are the stacked
    step arrays ([T, ...]); `length` the valid-step count. Outputs
    SentenceIds [batch, beam, T] (end_id padded) + SentenceScores
    [batch, beam], best beam first. When `length` is omitted the FULL
    array capacity is decoded — only correct for exactly-sized arrays;
    loop-built arrays must pass their step counter."""
    if parents is not None and length is None:
        raise ValueError(
            "beam_search_decode: parents implies a decode loop whose "
            "arrays are capacity-padded; pass length= (the step counter) "
            "or unwritten slots would be decoded as real steps")
    helper = LayerHelper("beam_search_decode")
    sentence_ids = helper.create_tmp_variable(ids.dtype)
    sentence_scores = helper.create_tmp_variable(scores.dtype)
    inputs = {"Ids": ids, "Scores": scores}
    if parents is not None:
        inputs["ParentIdx"] = parents
    if length is not None:
        inputs["Length"] = length
    helper.append_op(type="beam_search_decode",
                     inputs=inputs,
                     outputs={"SentenceIds": sentence_ids,
                              "SentenceScores": sentence_scores},
                     attrs={"beam_size": beam_size, "end_id": end_id})
    return sentence_ids, sentence_scores


def linear_chain_crf(input, label, param_attr=None):
    """Linear-chain CRF negative log-likelihood over ragged sequences
    (reference: layers/nn.py linear_chain_crf / linear_chain_crf_op.cc).
    Creates the [num_tags+2, num_tags] transition parameter (rows 0/1 =
    start/end weights)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    num_tags = int(input.shape[-1])
    transition = helper.create_parameter(
        helper.param_attr, shape=[num_tags + 2, num_tags],
        dtype=input.dtype)
    ll = helper.create_tmp_variable(input.dtype)
    alpha = helper.create_tmp_variable(input.dtype)
    em_exps = helper.create_tmp_variable(input.dtype)
    tr_exps = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="linear_chain_crf",
                     inputs={"Emission": input, "Label": label,
                             "Transition": transition},
                     outputs={"LogLikelihood": ll, "Alpha": alpha,
                              "EmissionExps": em_exps,
                              "TransitionExps": tr_exps})
    return ll


def crf_decoding(input, param_attr=None, label=None):
    """Viterbi decode with a trained CRF transition parameter (reference:
    layers/nn.py crf_decoding / crf_decoding_op.h). With `label`, emits
    per-position 0/1 correctness instead of the path."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    attr = helper.param_attr
    num_tags = int(input.shape[-1])
    if attr is not None and attr.name is not None and \
            helper.main_program.global_block().has_var(attr.name):
        # Share the transition parameter trained by linear_chain_crf.
        transition = helper.main_program.global_block().var(attr.name)
    else:
        # Decode-only/inference programs create it fresh (it is then
        # loaded from a checkpoint by name).
        transition = helper.create_parameter(
            attr, shape=[num_tags + 2, num_tags], dtype=input.dtype)
    path = helper.create_tmp_variable("int64", lod_level=input.lod_level)
    inputs = {"Emission": input, "Transition": transition}
    if label is not None:
        inputs["Label"] = label
    helper.append_op(type="crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": path})
    return path


def multiplex(inputs, index):
    """Select rows among candidates by index (reference: nn.py multiplex)."""
    helper = LayerHelper("multiplex")
    out = helper.create_tmp_variable(inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": index},
                     outputs={"Out": out})
    return out


# -- single-step RNN cells (reference: nn.py lstm_unit:  gru_unit:) ---------

def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step: projects [x_t, h_prev] to 4*d gates then applies the
    cell update (reference: nn.py lstm_unit — built on lstm_unit op)."""
    helper = LayerHelper("lstm_unit", name=name)
    d = cell_t_prev.shape[-1]
    concat_in = fc(x_t, size=4 * d, bias_attr=bias_attr,
                   param_attr=param_attr)
    h_proj = fc(hidden_t_prev, size=4 * d, bias_attr=False)
    gates = helper.create_tmp_variable(x_t.dtype)
    helper.append_op(type="elementwise_add",
                     inputs={"X": concat_in, "Y": h_proj},
                     outputs={"Out": gates})
    c = helper.create_tmp_variable(x_t.dtype)
    h = helper.create_tmp_variable(x_t.dtype)
    helper.append_op(type="lstm_unit",
                     inputs={"X": gates, "C_prev": cell_t_prev},
                     outputs={"C": c, "H": h},
                     attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """One GRU step (reference: nn.py gru_unit). size = 3*d."""
    helper = LayerHelper("gru_unit")
    d = size // 3
    weight = helper.create_parameter(attr=param_attr, shape=[d, 3 * d],
                                     dtype=input.dtype)
    bias = helper.create_parameter(attr=bias_attr, shape=[1, 3 * d],
                                   dtype=input.dtype, is_bias=True)
    gate = helper.create_tmp_variable(input.dtype)
    reset_h = helper.create_tmp_variable(input.dtype)
    hid = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="gru_unit",
                     inputs={"Input": input, "HiddenPrev": hidden,
                             "Weight": weight, "Bias": bias},
                     outputs={"Gate": gate, "ResetHiddenPrev": reset_h,
                              "Hidden": hid},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    return hid, reset_h, gate


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  name=None):
    """Projected LSTM over ragged input (reference: nn.py dynamic_lstmp:
    input already projected to [*, 4*d]; recurrence on the p-dim
    projection)."""
    helper = LayerHelper("dynamic_lstmp", name=name)
    d = size // 4
    weight = helper.create_parameter(attr=param_attr, shape=[proj_size, 4 * d],
                                     dtype=input.dtype)
    proj_weight = helper.create_parameter(attr=param_attr,
                                          shape=[d, proj_size],
                                          dtype=input.dtype)
    # peepholes pack W_ic/W_fc/W_oc after the gate bias (reference layout)
    bias_size = 7 * d if use_peepholes else 4 * d
    bias = helper.create_parameter(attr=bias_attr, shape=[1, bias_size],
                                   dtype=input.dtype, is_bias=True)
    proj = helper.create_tmp_variable(input.dtype, lod_level=input.lod_level)
    cell = helper.create_tmp_variable(input.dtype, lod_level=input.lod_level)
    last_h = helper.create_tmp_variable(input.dtype)
    last_c = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="lstmp",
                     inputs={"Input": input, "Weight": weight,
                             "ProjWeight": proj_weight, "Bias": bias},
                     outputs={"Projection": proj, "Cell": cell,
                              "LastH": last_h, "LastC": last_c},
                     attrs={"gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation,
                            "use_peepholes": use_peepholes,
                            "is_reverse": is_reverse})
    return proj, cell


# -- decode/eval wrappers ---------------------------------------------------

def ctc_greedy_decoder(input, blank, name=None):
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    out = helper.create_tmp_variable("int32", lod_level=1)
    helper.append_op(type="ctc_greedy_decoder", inputs={"Input": input},
                     outputs={"Out": out}, attrs={"blank": blank})
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval")
    precision = helper.create_tmp_variable("float32")
    recall = helper.create_tmp_variable("float32")
    f1 = helper.create_tmp_variable("float32")
    num_infer = helper.create_tmp_variable("int64")
    num_label = helper.create_tmp_variable("int64")
    num_correct = helper.create_tmp_variable("int64")
    helper.append_op(type="chunk_eval",
                     inputs={"Inference": input, "Label": label},
                     outputs={"Precision": precision, "Recall": recall,
                              "F1-Score": f1, "NumInferChunks": num_infer,
                              "NumLabelChunks": num_label,
                              "NumCorrectChunks": num_correct},
                     attrs={"num_chunk_types": num_chunk_types,
                            "chunk_scheme": chunk_scheme,
                            "excluded_chunk_types":
                                list(excluded_chunk_types or [])})
    return precision, recall, f1, num_infer, num_label, num_correct


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistent int64 step counter incremented per run (reference:
    nn.py autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    counter = helper.create_global_variable(
        shape=[1], dtype="int64", name=counter_name or "@STEP_COUNTER@",
        persistable=True)
    helper.set_variable_initializer(
        counter, ConstantInitializer(float(begin - step)))
    helper.append_op(type="increment", inputs={"X": counter},
                     outputs={"Out": counter}, attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


def lod_reset(x, y=None, target_lod=None):
    """Reassign sequence boundaries (reference: nn.py lod_reset)."""
    helper = LayerHelper("lod_reset")
    out = helper.create_tmp_variable(x.dtype, lod_level=1)
    inputs = {"X": x}
    if y is not None:
        inputs["Y"] = y
    helper.append_op(type="lod_reset", inputs=inputs, outputs={"Out": out},
                     attrs={"target_lod": list(target_lod or [])})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    alpha_len = x.shape[1] if mode == "channel" else 1
    alpha = helper.create_parameter(attr=param_attr, shape=[alpha_len],
                                    dtype=x.dtype,
                                    default_initializer=ConstantInitializer(
                                        0.25))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(type="prelu", inputs={"X": x, "Alpha": alpha},
                     outputs={"Out": out}, attrs={"mode": mode})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_tmp_variable(dtype)
    inputs = {"X": label}
    if prior_dist is not None:
        inputs["PriorDist"] = prior_dist
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": out}, attrs={"epsilon": float(epsilon)})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_tmp_variable("float32")
    helper.append_op(type="rank_loss",
                     inputs={"Label": label, "Left": left, "Right": right},
                     outputs={"Out": out})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper("roi_pool")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op(type="roi_pool",
                     inputs={"X": input, "ROIs": rois},
                     outputs={"Out": out},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return out


def _interp_layer(op_type, input, out_shape=None, scale=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_tmp_variable(input.dtype)
    attrs = {}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), \
            int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op(type=op_type, inputs={"X": input},
                     outputs={"Out": out}, attrs=attrs)
    return out


def bilinear_interp(input, out_shape=None, scale=None, name=None):
    """Bilinear NCHW resize (reference: legacy bilinear_interp layer)."""
    return _interp_layer("bilinear_interp", input, out_shape, scale, name)


def nearest_interp(input, out_shape=None, scale=None, name=None):
    """Nearest-neighbor NCHW resize (reference: legacy upsample/resize)."""
    return _interp_layer("nearest_interp", input, out_shape, scale, name)


resize_bilinear = bilinear_interp


def upsample(input, scale=2, name=None):
    return _interp_layer("nearest_interp", input, None, scale, name)


def sampling_id(x, seed=0, name=None):
    """Sample one id per row from probabilities (reference: sampling_id
    layer; stochastic generation)."""
    helper = LayerHelper("sampling_id", name=name)
    out = helper.create_tmp_variable("int64")
    helper.append_op(type="sampling_id", inputs={"X": x},
                     outputs={"Out": out}, attrs={"seed": seed})
    return out

