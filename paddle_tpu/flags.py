"""Central registry of the framework's environment flags.

The reference wires gflags end-to-end and re-exports selected C++ flags
into Python via `core.init_gflags(["--tryfromenv=..."])` at import
(reference: python/paddle/fluid/__init__.py:76-111 — use_pinned_memory,
check_nan_inf, benchmark, fraction_of_gpu_memory_to_use, ...). The
TPU-native analog is plain environment variables read at trace/run time;
this module is the single place they are all documented and inspectable
(`paddle_tpu.flags.dump()`), replacing the reference's --help surface.
"""
from __future__ import annotations

import os
from typing import Dict

# name -> (default, where it is read, what it does)
FLAGS: Dict[str, tuple] = {
    "PADDLE_TPU_AMP": (
        "0", "amp.py / bench.py",
        "bf16 mixed precision (f32 master weights); bench enables it"),
    "PADDLE_TPU_CHECK_NAN_INF": (
        "0", "core/executor.py",
        "scan fetched values for NaN/Inf after each run (reference "
        "FLAGS_check_nan_inf)"),
    "PADDLE_TPU_DONATE_STATE": (
        "1", "core/executor.py",
        "donate rw persistable state to the jitted step (XLA aliases "
        "state-in to state-out in place of a copy per step); 0 restores "
        "copy-per-step for callers holding scope state across runs"),
    "PADDLE_TPU_CONV_LAYOUT": (
        "nchw", "ops/nn_ops.py",
        "conv internal layout A/B knob ('nhwc' transposes at conv "
        "boundaries; XLA cancels them between convs). NCHW measured "
        ">= NHWC on chip"),
    "PADDLE_TPU_RNN_UNROLL": (
        "4", "ops/sequence_ops.py",
        "lax.scan unroll factor for masked RNN scans; 1 disables "
        "(also accepts off/false/no/none/disabled)"),
    "PADDLE_TPU_PALLAS_LSTM": (
        "1", "ops/sequence_ops.py",
        "fused Pallas LSTM kernel on TPU ('force' = interpret mode "
        "anywhere for tests, '0' = scan path)"),
    "PADDLE_TPU_PALLAS_GRU": (
        "1", "ops/sequence_ops.py",
        "fused Pallas GRU kernel on TPU (~1.8x over scan on v5e; same "
        "force/0/1 semantics)"),
    "PADDLE_TPU_CHECK_WHILE_BOUND": (
        "0", "core/executor.py",
        "raise when a top-level bounded While (max_steps=N) truncated a "
        "loop whose condition was still true; default 0 warns once per "
        "flag instead (per-run host readback; the `<name>.exhausted` "
        "bool var is always available to fetch; loops nested in "
        "sub-blocks keep their flag block-local)"),
    "PADDLE_TPU_VERIFY": (
        "1", "analysis/verifier.py (gates in core/executor.py, "
        "serving/model.py, trainer.py, io.py)",
        "static program verification gates: pre-compile (executor "
        "cache miss), serving model load, trainer setup, and "
        "save_inference_model all raise VerificationError on "
        "error-severity diagnostics; 0 disables every gate (the "
        "executor trace remains the runtime authority)"),
    "PADDLE_TPU_DATA_HOME": (
        "~/.cache/paddle_tpu/dataset", "dataset/common.py",
        "dataset download/cache directory"),
    "PADDLE_TPU_FEED_CACHE_MAX": (
        "8", "core/executor.py",
        "max entries in the device-side feed cache (frozen ndarrays "
        "uploaded once)"),
    # bench-only knobs
    "BENCH_BATCH": ("128", "bench.py", "ResNet bench batch size"),
    "BENCH_WARMUP": ("3", "bench.py", "warmup steps"),
    "BENCH_N1": ("5", "bench.py", "short marginal-timing run"),
    "BENCH_N2": ("25", "bench.py", "long marginal-timing run"),
    "BENCH_EXTRAS": ("1", "bench.py", "run the LSTM-LM extra metric"),
    "BENCH_REAL_INPUT": ("1", "bench.py",
                         "measure end-to-end throughput with the real "
                         "input pipeline (recordio loader -> device "
                         "prefetch) in the timed loop"),
    "BENCH_DATA_DIR": ("/tmp/pt_bench_imagenet", "bench.py",
                       "synthetic recordio shard directory for the "
                       "real-input bench"),
    "BENCH_TRANSFORMER": ("1", "bench.py",
                          "run the transformer extra metric"),
    "PADDLE_TPU_FUSED_XENT": (
        "0", "ops/nn_ops.py",
        "opt-in streaming softmax-cross-entropy (custom vjp, no "
        "full-vocab f32 buffer) for very large vocabularies; measured "
        "15% slower than the autodiff path at 32k vocab on v5e"),
    "BENCH_REPEATS": ("2", "bench.py",
                      "repeat the headline marginal measurement and "
                      "report median + spread"),
    "PADDLE_TPU_FLASH_MIN_SEQ": (
        "512", "ops/nn_ops.py",
        "minimum sequence length at which fused attention auto-routes "
        "to the Pallas flash kernel; below it the naive composition "
        "wins on v5e (measured crossover ~512 — MFU_BREAKDOWN.md "
        "round 3)"),
    "PADDLE_TPU_ATTRIBUTION": (
        "1", "observability/attribution.py (published from trainer.py, "
        "serving/engine.py)",
        "live performance attribution: paddle_tpu_mfu / "
        "paddle_tpu_model_flops gauges and the per-phase step-time "
        "breakdown; 0 disables publication (the disabled metrics "
        "registry also turns it off; set_attribution_enabled() "
        "overrides the env)"),
    "PADDLE_TPU_PEAK_FLOPS": (
        "197e12", "observability/attribution.py",
        "device peak FLOP/s the MFU gauge is normalized against "
        "(default: v5e bf16 peak, same constant as "
        "benchmarks/profile_mfu.py); read per step so tests can "
        "flip it"),
    "PADDLE_TPU_FLIGHT_RECORDER": (
        "1", "observability/flight_recorder.py",
        "failure flight recorder: bounded ring of recent profiler "
        "events dumped as a chrome-trace + JSON bundle when a failure "
        "trigger fires (NaN at fetch, circuit-breaker open, checkpoint "
        "failure, VerificationError); 0 removes the listener entirely "
        "(zero overhead, nothing ever written)"),
    "PADDLE_TPU_FLIGHT_DIR": (
        "<tmpdir>/paddle_tpu_flightrec", "observability/flight_recorder.py",
        "directory flight-recorder dump bundles are written to "
        "(flightrec_<ms>_<pid>_<seq>_<reason>/, pruned to this "
        "process's newest 8)"),
    "PADDLE_TPU_OPTIMIZE": (
        "1", "analysis/rewrite.py (gate in core/executor.py)",
        "ProgramDesc rewrite pipeline on every compile-cache miss: "
        "dead-op elimination, CSE, constant folding, fusion outlining "
        "onto the Pallas kernels, and kernel-dispatch annotation — "
        "each pass verified by fast_passes() and discarded on failure; "
        "0 compiles every program exactly as built"),
    "PADDLE_TPU_INPLACE_REUSE": (
        "1", "analysis/rewrite.py (inplace_reuse pass)",
        "liveness-driven buffer reuse during rewrite: rename an op's "
        "output onto a dead same-signature buffer so the arena holds "
        "one allocation instead of two (value-preserving, root block "
        "only, never touches persistable/donated/fetched names); "
        "0 keeps every var its own buffer"),
    "PADDLE_TPU_HBM_BYTES": (
        str(16 * 1024 ** 3), "analysis/memory.py (gate in "
        "core/executor.py)",
        "per-core HBM budget for the pre-compile OOM gate: a program "
        "whose static peak-memory estimate exceeds this raises a "
        "structured VerificationError (top offenders + high-water op) "
        "before XLA compiles it. Default one v5e core (16 GiB); "
        "0 disables the gate (the MemoryReport is still attached)"),
    "PADDLE_TPU_PALLAS_SDPA": (
        "1", "analysis/rewrite.py (kernel_dispatch pass)",
        "flash-kernel dispatch annotation for "
        "scaled_dot_product_attention ops during rewrite: '1' leaves "
        "the op's measured min-seq auto policy in charge "
        "(PADDLE_TPU_FLASH_MIN_SEQ), 'force' stamps use_flash=True "
        "(interpret mode off-TPU — test coverage), '0' pins the naive "
        "composition"),
    "PADDLE_TPU_INPUT_WORKERS": (
        "2", "reader/streaming.py",
        "initial worker-process count of a StreamingInputService "
        "(capped at the shard count; elastic scaling moves it between "
        "MIN and MAX at runtime)"),
    "PADDLE_TPU_INPUT_MIN_WORKERS": (
        "1", "reader/streaming.py",
        "elastic-scaling floor for the streaming input worker pool"),
    "PADDLE_TPU_INPUT_MAX_WORKERS": (
        "4", "reader/streaming.py",
        "elastic-scaling ceiling for the streaming input worker pool "
        "(also capped at the shard count — a shard is the unit of "
        "parallelism)"),
    "PADDLE_TPU_INPUT_SLOTS": (
        "4", "reader/streaming.py",
        "shared-memory ring slots per streaming input worker; bounds "
        "each worker's produced-but-undelivered batches (backpressure) "
        "and so the service's reorder-buffer memory"),
    "PADDLE_TPU_INPUT_SCALE_INTERVAL_S": (
        "2.0", "reader/streaming.py",
        "elastic-scaling evaluation window: starvation above "
        "PADDLE_TPU_INPUT_SCALE_UP_STARVED spawns a worker, a full "
        "queue with zero starvation retires one; 0 disables scaling"),
    "PADDLE_TPU_INPUT_SCALE_UP_STARVED": (
        "0.25", "reader/streaming.py",
        "fraction of deliveries in a scaling window that found the "
        "prefetch queue dry above which the pool scales up"),
    "PADDLE_TPU_INPUT_START_METHOD": (
        "spawn", "reader/streaming.py",
        "multiprocessing start method for streaming input workers "
        "('spawn' default — fork duplicates live JAX runtime threads; "
        "chaos tests use 'fork' so workers inherit the armed "
        "FaultInjector)"),
    "PADDLE_TPU_INPUT_MAX_RESPAWNS": (
        "3", "reader/streaming.py",
        "total worker respawns a StreamingInputService attempts across "
        "its lifetime before surfacing the crash to the consumer"),
    "PADDLE_TPU_DECODE_SLOTS": (
        "4", "serving/generation/model.py",
        "default in-flight slot count of a generation model's "
        "continuous-batching array (per-request KV-cache rows; also "
        "the decode executable's batch dimension)"),
    "PADDLE_TPU_DECODE_CACHE_BUCKETS": (
        "16,32,64", "serving/generation/model.py",
        "default cache-length buckets for the decode-step executables, "
        "comma-separated ascending; each bucket is one compiled "
        "executable, a step runs the smallest bucket covering the "
        "deepest active position"),
    "PADDLE_TPU_DECODE_MODEL_BUDGET": (
        "8", "serving/generation/host.py",
        "default per-model admission budget of a GenerationHost: max "
        "concurrently admitted (queued + in-flight) requests per "
        "hosted model before sheds with reason=model_budget"),
    "PADDLE_TPU_EMBED_HOT_CACHE_ROWS": (
        "1024", "embedding/hot_cache.py (via embedding/table.py)",
        "default row capacity of a ShardedTable's replicated hot-row "
        "cache (top-K by observed frequency); 0 disables the cache so "
        "every id takes the cold sharded-gather path"),
    "PADDLE_TPU_EMBED_CACHE_REFRESH_STEPS": (
        "50", "embedding/hot_cache.py",
        "steps between hot-cache refreshes: the host-side frequency "
        "tracker re-elects the top-K rows and re-gathers their current "
        "values; also the cache's staleness bound — between refreshes "
        "only write-through updates (rows this worker touched) land "
        "in the cache"),
    "PADDLE_TPU_EMBED_FREQ_CAPACITY": (
        "8192", "embedding/hot_cache.py",
        "bounded id-frequency tracker capacity (lossy top-K counting "
        "— a dense per-row counter would be O(vocab) host memory, "
        "unpayable at 1e9 rows); pruned back to this size whenever it "
        "doubles"),
    "PADDLE_TPU_BN_CUSTOM_VJP": (
        "0", "ops/nn_ops.py",
        "use the round-2 hand-written BatchNorm backward (custom_vjp) "
        "instead of autodiff; the autodiff default lets XLA fuse the "
        "backward reductions into conv gradient fusions — see "
        "MFU_BREAKDOWN.md round 3"),
}


def get(name: str) -> str:
    """Current value of a registered flag (env or default)."""
    if name not in FLAGS:
        raise KeyError(f"unknown flag {name!r}; see paddle_tpu.flags.FLAGS")
    return os.environ.get(name, FLAGS[name][0])


def dump() -> str:
    """Human-readable table of every flag: current value, default,
    reader, description."""
    lines = []
    for name, (default, where, desc) in sorted(FLAGS.items()):
        cur = os.environ.get(name)
        mark = f"{cur} (set)" if cur is not None else f"{default}"
        lines.append(f"{name} = {mark}\n    [{where}] {desc}")
    return "\n".join(lines)
