"""paddle_tpu_embed_* metric families — observability for the sharded
embedding subsystem.

One family per question an embedding-serving oncall actually asks
(mirroring the reference pserver's sparse-table stats surface,
ParameterServer2 stat sets): how many lookups/ids, what fraction of
ids the replicated hot cache absorbed (the model-axis traffic saver),
how many rows each optimizer apply actually touched (the SelectedRows
"only touched rows" number the cost model prices), and how stale the
hot cache is allowed to get between refreshes.

All families live in the process-wide registry
(observability/registry.py) under the enforced ``paddle_tpu_*``
namespace; tests/test_metric_names.py asserts every one of them is
published by the smoke run and carries help text.
"""
from __future__ import annotations

from ..observability import default_registry

_LABELS = ("table",)


def families():
    """Create-or-get every embed family. Idempotent: the registry
    returns the existing family when the declaration matches."""
    reg = default_registry()
    return {
        "lookups": reg.counter(
            "paddle_tpu_embed_lookups_total",
            "sharded-table lookup calls (one per batch gather)",
            _LABELS),
        "ids": reg.counter(
            "paddle_tpu_embed_ids_total",
            "ids presented to sharded-table lookups (pre-dedup, "
            "padding ids excluded)", _LABELS),
        "hits": reg.counter(
            "paddle_tpu_embed_hot_cache_hits_total",
            "unique ids resolved from the replicated hot-row cache "
            "(no model-axis crossing)", _LABELS),
        "misses": reg.counter(
            "paddle_tpu_embed_hot_cache_misses_total",
            "unique ids that took the cold sharded-gather path",
            _LABELS),
        "hit_ratio": reg.gauge(
            "paddle_tpu_embed_hot_cache_hit_ratio",
            "hot-cache hit ratio over unique ids, most recent lookup",
            _LABELS),
        "touched_rows": reg.gauge(
            "paddle_tpu_embed_touched_rows",
            "unique non-padding rows updated by the most recent "
            "sparse optimizer apply (the SelectedRows touched-row "
            "count the cost model prices)", _LABELS),
        "applies": reg.counter(
            "paddle_tpu_embed_applies_total",
            "sparse optimizer applies against the sharded table",
            ("table", "optimizer")),
        "refreshes": reg.counter(
            "paddle_tpu_embed_cache_refreshes_total",
            "hot-cache refreshes (frequency tracker re-elected the "
            "top-K rows and their values were re-gathered)", _LABELS),
        "staleness": reg.gauge(
            "paddle_tpu_embed_cache_staleness_steps",
            "applies since the hot cache was last refreshed (its "
            "staleness bound; write-through keeps rows touched by "
            "THIS worker current in between)", _LABELS),
        "rows": reg.gauge(
            "paddle_tpu_embed_table_rows",
            "vocab rows of the sharded table (pre-padding)", _LABELS),
    }


def record_lookup(table: str, n_ids: int, hits: int, misses: int):
    fams = families()
    fams["lookups"].labels(table=table).inc()
    fams["ids"].labels(table=table).inc(n_ids)
    if hits or misses:
        fams["hits"].labels(table=table).inc(hits)
        fams["misses"].labels(table=table).inc(misses)
        fams["hit_ratio"].labels(table=table).set(
            hits / float(hits + misses))


def record_apply(table: str, optimizer: str, touched: int):
    fams = families()
    fams["applies"].labels(table=table, optimizer=optimizer).inc()
    fams["touched_rows"].labels(table=table).set(touched)


def record_refresh(table: str):
    families()["refreshes"].labels(table=table).inc()


def record_staleness(table: str, steps: int):
    families()["staleness"].labels(table=table).set(steps)


def record_table(table: str, vocab: int):
    families()["rows"].labels(table=table).set(vocab)
