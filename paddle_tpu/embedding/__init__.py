"""paddle_tpu.embedding — billion-row sharded embedding subsystem.

TPU-native rebuild of the reference's distributed sparse parameter
path (SelectedRows, distributed lookup table, pserver sparse
optimizer): a production layer over parallel/sparse.sharded_lookup.

- :class:`TableConfig` / :class:`ShardedTable` (table.py): row-sharded
  param + per-shard optimizer slots, per-shard seeded init — the dense
  [vocab, dim] value never exists anywhere.
- sparse_optimizer.py: unique-ids dedup + scatter row updates for
  sgd/adagrad/adam with row-wise lazy slots, bit-identical to the
  dense single-chip optimizer on touched rows.
- :class:`HotRowCache` (hot_cache.py): frequency-elected replicated
  top-K rows so hot ids never cross the model axis; periodic refresh
  bounds staleness.
- checkpoint.py: save/load over distributed/sharded_checkpoint, one
  piece per shard, never densified.
- serving.py: ParallelExecutor-backed ServableModel so a
  distributed=True export serves sharded under the PR 7 lifecycle.
- metrics.py: the paddle_tpu_embed_* observability families.

Driven end-to-end by models/deepfm.py (DeepFMSharded) and
benchmarks/embedding_scale.py.
"""
from .table import ShardedTable, TableConfig  # noqa: F401
from .sparse_optimizer import (dedup_ids, dense_reference_apply,  # noqa
                               masked_gather, segment_sum_rows,
                               sparse_apply)
from .hot_cache import (FrequencyTracker, HotRowCache,  # noqa: F401
                        cached_gather)
from .checkpoint import load_table, save_table  # noqa: F401
from .serving import load_sharded_servable  # noqa: F401
from . import metrics  # noqa: F401
