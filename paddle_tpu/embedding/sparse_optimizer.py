"""Sparse optimizer application for row-sharded tables: unique-ids
dedup + scatter row updates with row-wise (lazy) slot state.

Reference capability (SURVEY.md sparse/embedding distribution):
SelectedRows gradients + the pserver-side sparse optimizer
(ParameterServer2 sparse update path, sgd/adagrad/adam SelectedRows
branches) — only the rows a batch touched are read, updated, and
written, so update cost scales with TOUCHED rows, never with vocab.

TPU-native shape: the deduped (ids, row-grads) pair is replicated (the
row gradients come out of the psum-assembled forward, so every shard
already holds them); each shard gathers its OWN slice of the touched
rows, runs the identical dense update formulas
(ops/optimizer_ops.sparse_row_update) on that block, and scatters the
results back locally. No collective crosses the model axis during
apply — the only model-axis traffic of a training step is the forward
gather's psum.

Bit-identity contract: on rows present in the update, the result is
bit-identical to the dense single-chip optimizer ops (same formula
expressions, same dtype, elementwise) — tested 3-step in
tests/test_embedding_subsystem.py. Rows NOT in the update keep their
param AND slot state (lazy semantics; see KNOWN_GAPS on adam).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.optimizer_ops import SPARSE_HYPER_DEFAULTS, sparse_row_update

try:
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

#: per-kind row slots, in the order the dense op reads them
ROW_SLOTS = {"sgd": (), "adagrad": ("moment",),
             "adam": ("moment1", "moment2")}
#: per-kind scalar slots ([1]-shaped, replicated, advanced per step)
SCALAR_SLOTS = {"sgd": (), "adagrad": (),
                "adam": ("beta1_pow", "beta2_pow")}


def dedup_ids(ids, vocab: int, padding_idx: Optional[int] = None):
    """Unique touched rows of an id batch, at static size.

    Returns ``(uniq, inv, valid)`` with ``uniq.shape == (ids.size,)``:
    ids are clipped to ``[0, vocab)`` first (the dense lookup's clip
    semantics, so OOB ids accumulate where the dense path would), then
    positions holding ``padding_idx`` are routed to the sentinel id
    ``vocab`` — the padding row is never a touched row. Unused slots of
    ``uniq`` are filled with the same sentinel; ``valid`` marks real
    rows. Every downstream consumer drops sentinel rows: the masked
    gather returns zeros for them (which also reproduces the dense
    path's zeroed padding output through ``rows[inv]``), and the
    scatter-apply drops them.
    """
    flat = ids.reshape(-1).astype(jnp.int32)
    clipped = jnp.clip(flat, 0, vocab - 1)
    if padding_idx is not None:
        clipped = jnp.where(flat == padding_idx, vocab, clipped)
    uniq, inv = jnp.unique(clipped, size=flat.shape[0],
                           fill_value=vocab, return_inverse=True)
    return uniq, inv.reshape(ids.shape), uniq < vocab


def segment_sum_rows(grads, inv, num_rows: int):
    """Accumulate per-occurrence row gradients onto their unique row
    (the dedup-side half of a SelectedRows merge_add)."""
    return jax.ops.segment_sum(grads.reshape(-1, grads.shape[-1]),
                               inv.reshape(-1), num_segments=num_rows)


def masked_gather(table, ids, mesh=None, axis: str = "model"):
    """Rows of a row-sharded table; ids outside ``[0, vocab)`` yield
    ZERO rows (no clip) — the sparse path's internal contract: the
    dedup sentinel, padding rows, and hot-cache-hit ids are all routed
    out of bounds to cross the model axis as zeros that cost nothing to
    combine. Without a mesh, the dense single-chip equivalent."""
    vocab = table.shape[0]
    if mesh is None:
        hit = (ids >= 0) & (ids < vocab)
        safe = jnp.clip(ids, 0, vocab - 1)
        got = jnp.take(table, safe, axis=0)
        return jnp.where(hit[..., None], got, jnp.zeros_like(got))
    rows_per = vocab // mesh.shape[axis]

    def local(shard, ids_l):
        my = jax.lax.axis_index(axis)
        loc = ids_l - my * rows_per
        hit = (loc >= 0) & (loc < rows_per)
        safe = jnp.clip(loc, 0, rows_per - 1)
        got = jnp.take(shard, safe, axis=0)
        got = jnp.where(hit[..., None], got, jnp.zeros_like(got))
        return jax.lax.psum(got, axis)

    return shard_map(local, mesh=mesh, in_specs=(P(axis, None), P()),
                     out_specs=P())(table, ids)


def sparse_apply(kind: str, param, slots: Dict[str, jax.Array],
                 uniq, grad_rows, valid, lr, hyper: Dict[str, float],
                 mesh=None, axis: str = "model"
                 ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Apply one sparse optimizer step to the touched rows.

    ``uniq``/``grad_rows``/``valid`` are the replicated dedup outputs
    ([U], [U, D], [U]); ``slots`` holds the per-kind accumulators (row
    slots sharded like the param, scalar slots replicated [1]).
    Returns ``(param_out, slots_out)``. Invalid rows (sentinel fill,
    padding) and rows outside a shard's range are dropped by the
    scatter — their param and slot rows are bit-unchanged.
    """
    if kind not in ROW_SLOTS:
        raise ValueError(f"no sparse rule for optimizer {kind!r}; "
                         f"have {sorted(ROW_SLOTS)}")
    lr = jnp.asarray(lr, param.dtype)
    hyper = dict(SPARSE_HYPER_DEFAULTS[kind], **(hyper or {}))
    b1p = slots.get("beta1_pow")
    b2p = slots.get("beta2_pow")
    row_slot_vals = tuple(slots[s] for s in ROW_SLOTS[kind])
    vocab = param.shape[0]
    n_shards = 1 if mesh is None else mesh.shape[axis]
    rows_per = vocab // n_shards

    # adam's scalar state rides along as [1] replicated operands (a
    # closure over traced values is not portable through shard_map)
    scalars = (b1p, b2p) if kind == "adam" else ()

    def local(p_sh, slot_shs, uniq_, grads_, valid_, lr_, scalars_):
        lo = (0 if mesh is None
              else jax.lax.axis_index(axis) * rows_per)
        loc = uniq_ - lo
        hit = valid_ & (loc >= 0) & (loc < rows_per)
        safe = jnp.clip(loc, 0, rows_per - 1)
        p_rows = jnp.take(p_sh, safe, axis=0)
        s_rows = tuple(jnp.take(s, safe, axis=0) for s in slot_shs)
        b1p_, b2p_ = scalars_ if scalars_ else (None, None)
        new_p, new_s = sparse_row_update(kind, p_rows, s_rows, grads_,
                                         lr_, hyper, b1p_, b2p_)
        tgt = jnp.where(hit, loc, rows_per)   # OOB -> dropped
        p_out = p_sh.at[tgt].set(new_p, mode="drop")
        s_out = tuple(s.at[tgt].set(ns, mode="drop")
                      for s, ns in zip(slot_shs, new_s))
        return p_out, s_out

    if mesh is None:
        p_out, s_out = local(param, row_slot_vals, uniq, grad_rows,
                             valid, lr, scalars)
    else:
        sharded = P(axis, None)
        p_out, s_out = shard_map(
            local, mesh=mesh,
            in_specs=(sharded, tuple(sharded for _ in row_slot_vals),
                      P(), P(), P(), P(),
                      tuple(P() for _ in scalars)),
            out_specs=(sharded, tuple(sharded for _ in row_slot_vals)),
        )(param, row_slot_vals, uniq, grad_rows, valid, lr, scalars)

    slots_out = dict(slots)
    for name, val in zip(ROW_SLOTS[kind], s_out):
        slots_out[name] = val
    if kind == "adam":
        slots_out["beta1_pow"] = b1p * hyper["beta1"]
        slots_out["beta2_pow"] = b2p * hyper["beta2"]
    return p_out, slots_out


def dense_reference_apply(kind: str, param, slots: Dict[str, jax.Array],
                          grad, lr, hyper: Optional[Dict[str, float]]
                          = None):
    """The dense single-chip optimizer step (the exact op formulas,
    applied to the whole table with a dense gradient) — the oracle the
    bit-identity tests compare the sparse path against."""
    hyper = dict(SPARSE_HYPER_DEFAULTS[kind], **(hyper or {}))
    lr = jnp.asarray(lr, param.dtype)
    row_slot_vals = tuple(slots[s] for s in ROW_SLOTS[kind])
    new_p, new_s = sparse_row_update(
        kind, param, row_slot_vals, grad, lr, hyper,
        slots.get("beta1_pow"), slots.get("beta2_pow"))
    slots_out = dict(slots)
    for name, val in zip(ROW_SLOTS[kind], new_s):
        slots_out[name] = val
    if kind == "adam":
        slots_out["beta1_pow"] = slots["beta1_pow"] * hyper["beta1"]
        slots_out["beta2_pow"] = slots["beta2_pow"] * hyper["beta2"]
    return new_p, slots_out
