"""Frequency-aware hot-row caching for sharded embedding tables.

Recommender id streams are zipfian: a few thousand hot rows absorb most
of the lookup volume (the reference's distributed lookup table design
doc motivates its pserver-side cache the same way). Here the hot set is
REPLICATED: a sorted top-K id vector plus their rows live on every
chip, so a hot id resolves locally — it never crosses the model axis.
Cold ids still take the sharded masked-gather + psum path.

Mechanics:

- a host-side bounded :class:`FrequencyTracker` (lossy top-K counting;
  a dense per-row counter would be O(vocab) host memory) observes the
  raw id stream;
- every ``PADDLE_TPU_EMBED_CACHE_REFRESH_STEPS`` applies, the top-K
  rows are re-elected and their CURRENT values re-gathered — the
  cache's staleness bound;
- between refreshes, write-through keeps rows updated by THIS worker
  exact; rows updated by other workers may be up to one refresh
  interval stale (single-worker: the cache is always exact). See
  KNOWN_GAPS "Sharded embedding boundaries".

Byte accounting: cache hits alone do not shrink the psum payload —
that is sized by the gather's static shape. The savings come from
:func:`cached_gather`'s miss COMPACTION (``miss_budget``): only a
miss-sized id vector crosses the model axis. Overflow (more misses
than budget) is reported loudly in the returned stats; callers that
cannot tolerate a re-run must size the budget for their stream.
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from .. import flags
from . import metrics as embed_metrics
from .sparse_optimizer import masked_gather

#: cache-slot sentinel: never equals a real id (ids are int32 row
#: numbers well below this), so empty slots can never hit
_EMPTY = np.iinfo(np.int32).max


class FrequencyTracker:
    """Bounded lossy id-frequency counter (space-saving flavor): counts
    live in a dict pruned back to ``capacity`` whenever it doubles, so
    host memory is O(capacity) however large the vocab. Heavy hitters
    of a zipfian stream survive pruning by construction."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(capacity if capacity is not None
                            else flags.get(
                                "PADDLE_TPU_EMBED_FREQ_CAPACITY"))
        self.counts = {}

    def update(self, ids: np.ndarray):
        u, c = np.unique(ids, return_counts=True)
        for i, n in zip(u.tolist(), c.tolist()):
            self.counts[i] = self.counts.get(i, 0) + n
        if len(self.counts) > 2 * self.capacity:
            keep = sorted(self.counts.items(),
                          key=lambda kv: -kv[1])[:self.capacity]
            self.counts = dict(keep)

    def top(self, k: int) -> np.ndarray:
        """The up-to-k hottest ids (unsorted)."""
        top = sorted(self.counts.items(), key=lambda kv: -kv[1])[:k]
        return np.asarray([i for i, _ in top], np.int32)


def cached_gather(param, cache_ids, cache_rows, uniq, valid,
                  mesh=None, axis: str = "model", sentinel: int = None,
                  miss_budget: Optional[int] = None):
    """Resolve unique ids against the replicated cache, gathering only
    the misses from the sharded table.

    Returns ``(rows, n_hits, n_misses, overflow)`` (the counts/flag as
    0-d arrays). ``miss_budget=None`` gathers a full-size id vector
    (misses routed through it, hits as sentinels — correct for any
    stream, no byte savings); an integer budget compacts the misses to
    that static size, shrinking the psum payload to budget x dim.
    Misses beyond the budget come back as ZERO rows and ``overflow``
    is set — callers must check it (the benchmark sizes the budget
    from the observed hit ratio).
    """
    sentinel = param.shape[0] if sentinel is None else sentinel
    k = cache_ids.shape[0]
    pos = jnp.searchsorted(cache_ids, uniq)
    posc = jnp.clip(pos, 0, k - 1)
    hit = (jnp.take(cache_ids, posc) == uniq) & valid
    cached = jnp.take(cache_rows, posc, axis=0)
    if miss_budget is None:
        cold_ids = jnp.where(hit, sentinel, uniq)
        cold = masked_gather(param, cold_ids, mesh, axis)
        overflow = jnp.zeros((), bool)
    else:
        u = uniq.shape[0]
        miss = valid & ~hit
        (midx,) = jnp.nonzero(miss, size=int(miss_budget),
                              fill_value=u)
        safe = jnp.clip(midx, 0, u - 1)
        miss_ids = jnp.where(midx < u, jnp.take(uniq, safe), sentinel)
        cold_small = masked_gather(param, miss_ids, mesh, axis)
        tgt = jnp.where(midx < u, midx, u)
        cold = jnp.zeros((u, param.shape[1]), param.dtype) \
            .at[tgt].set(cold_small, mode="drop")
        overflow = jnp.sum(miss) > miss_budget
    rows = jnp.where(hit[:, None], cached, cold)
    return rows, jnp.sum(hit), jnp.sum(valid & ~hit), overflow


class HotRowCache:
    """Replicated top-K hot rows of one table (see module docstring)."""

    def __init__(self, table_name: str, dim: int, dtype: str,
                 capacity: Optional[int] = None,
                 refresh_interval: Optional[int] = None,
                 tracker_capacity: Optional[int] = None):
        self.table_name = table_name
        self.capacity = int(capacity if capacity is not None
                            else flags.get(
                                "PADDLE_TPU_EMBED_HOT_CACHE_ROWS"))
        self.refresh_interval = int(
            refresh_interval if refresh_interval is not None
            else flags.get("PADDLE_TPU_EMBED_CACHE_REFRESH_STEPS"))
        self.tracker = FrequencyTracker(tracker_capacity)
        # sorted ids (all-empty sorts trivially); searchsorted is the
        # hit test
        self.ids = jnp.full((self.capacity,), _EMPTY, jnp.int32)
        self.rows = jnp.zeros((self.capacity, dim), dtype)
        self.last_refresh = 0
        self.refreshes = 0

    def observe(self, ids_np: np.ndarray,
                padding_idx: Optional[int] = None):
        ids_np = np.asarray(ids_np).reshape(-1)
        if padding_idx is not None:
            ids_np = ids_np[ids_np != padding_idx]
        if ids_np.size:
            self.tracker.update(ids_np)

    def lookup(self, table, uniq, valid):
        """(rows, hits, misses) over the unique-id vector; full-size
        cold gather (no compaction — the training path must be correct
        for any stream)."""
        rows, h, m, _ovf = cached_gather(
            table.param, self.ids, self.rows, uniq, valid,
            table.mesh, table.config.axis, table.sentinel)
        return rows, int(np.asarray(h)), int(np.asarray(m))

    def write_through(self, uniq, valid, new_rows):
        k = self.capacity
        pos = jnp.searchsorted(self.ids, uniq)
        posc = jnp.clip(pos, 0, k - 1)
        hit = (jnp.take(self.ids, posc) == uniq) & valid
        tgt = jnp.where(hit, posc, k)
        self.rows = self.rows.at[tgt].set(new_rows, mode="drop")

    def refresh(self, table):
        """Re-elect the top-K rows and re-gather their current values
        (the staleness reset)."""
        top = self.tracker.top(self.capacity)
        ids = np.full((self.capacity,), _EMPTY, np.int32)
        ids[:top.size] = np.sort(top)
        self.ids = jnp.asarray(ids)
        safe = jnp.where(self.ids == _EMPTY, table.sentinel, self.ids)
        self.rows = masked_gather(table.param, safe, table.mesh,
                                  table.config.axis)
        self.last_refresh = table.step
        self.refreshes += 1
        embed_metrics.record_refresh(self.table_name)
        embed_metrics.record_staleness(self.table_name, 0)

    def maybe_refresh(self, table, step: int):
        embed_metrics.record_staleness(self.table_name,
                                       step - self.last_refresh)
        if self.tracker.counts and \
                step - self.last_refresh >= self.refresh_interval:
            self.refresh(table)
