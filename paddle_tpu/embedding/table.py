"""ShardedTable: a row-sharded embedding table whose parameter AND
optimizer slot state are created, updated, and checkpointed PER SHARD.

The bigger-than-HBM contract: no code path ever materializes the dense
``[vocab, dim]`` array on a single host or device —

- init is per-shard seeded (``jax.make_array_from_callback``: each
  addressable shard's rows are generated from a counter-based seed
  keyed by ``(seed, row_start)``, so a host only ever holds one
  shard-sized block);
- lookups ride ``sparse_optimizer.masked_gather`` (each shard answers
  its own row range, psum assembles — model-axis bytes scale with
  TOUCHED rows, never vocab);
- the sparse optimizer apply gathers/updates/scatters only the touched
  rows of each shard locally (no collective at all);
- checkpointing (embedding/checkpoint.py over
  distributed/sharded_checkpoint) writes one piece per shard.

On one chip (mesh=None) everything degrades to the real dense-math
single-chip path at full fidelity; >1-chip layouts at 1e8–1e9 vocab are
exercised in dryrun (compile + collective audit, no data) — see
KNOWN_GAPS "Sharded embedding boundaries".
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import get_mesh
from . import metrics as embed_metrics
from .sparse_optimizer import (ROW_SLOTS, SCALAR_SLOTS, dedup_ids,
                               masked_gather, segment_sum_rows,
                               sparse_apply)


class TableConfig:
    """Static description of one sharded table — everything needed to
    rebuild it (init included) without its data, so checkpoints and
    dryrun layouts carry the config, not the rows."""

    def __init__(self, name: str, vocab: int, dim: int,
                 dtype: str = "float32", optimizer: str = "sgd",
                 lr: float = 0.01, hyper: Optional[Dict[str, float]]
                 = None, init_scale: float = 0.01, seed: int = 0,
                 axis: str = "model", padding_idx: Optional[int] = None):
        if optimizer not in ROW_SLOTS:
            raise ValueError(
                f"table {name!r}: no sparse rule for {optimizer!r}; "
                f"have {sorted(ROW_SLOTS)}")
        self.name = name
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.dtype = dtype
        self.optimizer = optimizer
        self.lr = float(lr)
        self.hyper = dict(hyper or {})
        self.init_scale = float(init_scale)
        self.seed = int(seed)
        self.axis = axis
        self.padding_idx = padding_idx

    def to_dict(self) -> Dict:
        return {k: getattr(self, k) for k in
                ("name", "vocab", "dim", "dtype", "optimizer", "lr",
                 "hyper", "init_scale", "seed", "axis", "padding_idx")}

    @classmethod
    def from_dict(cls, d: Dict) -> "TableConfig":
        return cls(**d)

    def init_rows(self, row_start: int, n_rows: int) -> np.ndarray:
        """Seeded init for one row block — the per-shard init callback.
        Deterministic in ``(seed, row_start)`` only, so a host
        materializes exactly its own block; rows past ``vocab`` (the
        shard-alignment padding) are zero."""
        rng = np.random.default_rng([self.seed, int(row_start)])
        block = (self.init_scale *
                 rng.standard_normal((int(n_rows), self.dim))) \
            .astype(self.dtype)
        first_pad = max(0, min(int(n_rows),
                               self.vocab - int(row_start)))
        block[first_pad:] = 0
        return block


class ShardedTable:
    """Row-sharded embedding table + its per-shard optimizer state.

    ``mesh=None`` (or a mesh without the table's axis… is an error; no
    silent dense fallback at scale) runs the single-chip dense-layout
    path with identical math. ``hot_cache=True`` attaches a replicated
    top-K hot-row cache (embedding/hot_cache.py) sized by the
    embed flags (see flags.py).
    """

    def __init__(self, config: TableConfig, mesh=None,
                 hot_cache: bool = False):
        self.config = config
        self.mesh = mesh if mesh is not None else get_mesh()
        if self.mesh is not None and \
                config.axis not in self.mesh.axis_names:
            raise ValueError(
                f"table {config.name!r}: shard axis {config.axis!r} is "
                f"not an axis of the mesh {self.mesh.axis_names}")
        self.n_shards = (1 if self.mesh is None
                         else self.mesh.shape[config.axis])
        self.padded_vocab = (-(-config.vocab // self.n_shards)
                             * self.n_shards)
        #: sentinel id: strictly out of bounds on every path (dedup
        #: fill, padding rows, hot-cache hits all route here)
        self.sentinel = self.padded_vocab
        self.step = 0
        self.param = self._rowwise_array(self.config.init_rows)
        self.slots: Dict[str, jax.Array] = {}
        for slot in ROW_SLOTS[config.optimizer]:
            self.slots[slot] = self._rowwise_array(
                lambda start, n: np.zeros((n, config.dim),
                                          config.dtype))
        hyper = dict(config.hyper)
        if config.optimizer == "adam":
            self.slots["beta1_pow"] = jnp.full(
                (1,), hyper.get("beta1", 0.9), jnp.float32)
            self.slots["beta2_pow"] = jnp.full(
                (1,), hyper.get("beta2", 0.999), jnp.float32)
        self.hot_cache = None
        if hot_cache:
            from .hot_cache import HotRowCache
            self.hot_cache = HotRowCache(config.name, config.dim,
                                         config.dtype)
        embed_metrics.record_table(config.name, config.vocab)

    # -- state ----------------------------------------------------------
    def _sharding(self):
        return (None if self.mesh is None else
                NamedSharding(self.mesh, P(self.config.axis, None)))

    def _rowwise_array(self, row_fn) -> jax.Array:
        """Build a [padded_vocab, dim] array one shard block at a time
        — the dense array never exists on any host."""
        shape = (self.padded_vocab, self.config.dim)
        sh = self._sharding()
        if sh is None:
            return jnp.asarray(row_fn(0, self.padded_vocab))

        def cb(index):
            rs = index[0]
            start = 0 if rs.start is None else int(rs.start)
            stop = shape[0] if rs.stop is None else int(rs.stop)
            return row_fn(start, stop - start)

        return jax.make_array_from_callback(shape, sh, cb)

    def state(self):
        """(param, slots) — the functional state for jitted loops and
        checkpointing; write back with :meth:`set_state`."""
        return self.param, dict(self.slots)

    def set_state(self, param, slots):
        self.param = param
        self.slots = dict(slots)

    # -- lookup ---------------------------------------------------------
    def dedup(self, ids):
        """(uniq, inv, valid) — unique touched rows at static size,
        padding ids routed to the sentinel (never touched, never
        counted)."""
        uniq, inv, valid = dedup_ids(jnp.asarray(ids),
                                     self.config.vocab,
                                     self.config.padding_idx)
        return uniq, inv, valid

    def lookup_unique(self, ids):
        """Dedup + gather: returns ``(rows, uniq, inv, valid)`` with
        ``rows[inv]`` the embedding output (zeros at padding
        positions). Hot-cache hits resolve from the replicated cache;
        misses (or everything, without a cache) take the sharded
        gather."""
        ids = jnp.asarray(ids)
        uniq, inv, valid = self.dedup(ids)
        if self.hot_cache is not None:
            rows, hits, misses = self.hot_cache.lookup(self, uniq,
                                                       valid)
            self.hot_cache.observe(np.asarray(ids).reshape(-1),
                                   self.config.padding_idx)
        else:
            rows = masked_gather(self.param, uniq, self.mesh,
                                 self.config.axis)
            hits, misses = 0, int(np.asarray(jnp.sum(valid)))
        n_ids = np.asarray(ids).reshape(-1)
        if self.config.padding_idx is not None:
            n_ids = n_ids[n_ids != self.config.padding_idx]
        embed_metrics.record_lookup(self.config.name, int(n_ids.size),
                                    hits, misses)
        return rows, uniq, inv, valid

    def lookup(self, ids):
        """Embedding forward: [*, dim] rows for an id batch (dense
        clip semantics for OOB ids; zeros at padding positions)."""
        rows, _uniq, inv, _valid = self.lookup_unique(ids)
        return jnp.take(rows, inv, axis=0)

    # -- sparse apply ---------------------------------------------------
    def apply_rows(self, uniq, valid, grad_rows):
        """One sparse optimizer step from deduped row gradients (the
        autodiff cotangent of ``rows`` in :meth:`lookup_unique` is
        already occurrence-accumulated). Only valid rows are touched —
        param and slots of every other row are bit-unchanged."""
        self.param, self.slots = sparse_apply(
            self.config.optimizer, self.param, self.slots, uniq,
            grad_rows, valid, self.config.lr, self.config.hyper,
            self.mesh, self.config.axis)
        self.step += 1
        touched = int(np.asarray(jnp.sum(valid)))
        embed_metrics.record_apply(self.config.name,
                                   self.config.optimizer, touched)
        if self.hot_cache is not None:
            # write-through: rows THIS worker just updated stay exact
            # in the cache between refreshes (one extra touched-rows
            # gather, only when a cache is attached)
            new_rows = masked_gather(
                self.param, jnp.where(valid, uniq, self.sentinel),
                self.mesh, self.config.axis)
            self.hot_cache.write_through(uniq, valid, new_rows)
            self.hot_cache.maybe_refresh(self, self.step)
        return touched

    def apply_gradients(self, ids, occurrence_grads):
        """SelectedRows entry point: per-occurrence row gradients
        (shaped ``ids.shape + (dim,)``) are deduped (segment-sum) and
        applied to the touched rows."""
        ids = jnp.asarray(ids)
        uniq, inv, valid = self.dedup(ids)
        grad_rows = segment_sum_rows(jnp.asarray(occurrence_grads),
                                     inv, uniq.shape[0])
        return self.apply_rows(uniq, valid, grad_rows)
