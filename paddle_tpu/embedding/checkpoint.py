"""Checkpoint round-trip for ShardedTable — param + optimizer slots,
never densified.

Rides distributed/sharded_checkpoint: each piece of the row-sharded
param and each row-slot accumulator is written per shard (one npz blob
per shard block — the dense [vocab, dim] value exists nowhere, host
included), scalar slots and the step counter ride in a small JSON
sidecar together with the TableConfig. Restore rebuilds the table from
its config (per-shard seeded init) and overwrites state piece-by-piece
through jax.make_array_from_callback with the table's own sharding —
the same elastic-resharding fallbacks as the rest of the framework's
sharded checkpoints apply.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax.numpy as jnp

from ..core.scope import Scope
from ..distributed.sharded_checkpoint import load_sharded, save_sharded
from .sparse_optimizer import ROW_SLOTS, SCALAR_SLOTS
from .table import ShardedTable, TableConfig

_META = "table_meta.json"


def _row_state_names(config: TableConfig):
    names = [f"{config.name}.param"]
    names += [f"{config.name}.{s}" for s in ROW_SLOTS[config.optimizer]]
    return names


def save_table(dirname: str, table: ShardedTable) -> str:
    """Write the table's param + row slots per shard, plus config,
    scalar slots, and step in a JSON sidecar."""
    os.makedirs(dirname, exist_ok=True)
    cfg = table.config
    scope = Scope()
    scope.set(f"{cfg.name}.param", table.param)
    for s in ROW_SLOTS[cfg.optimizer]:
        scope.set(f"{cfg.name}.{s}", table.slots[s])
    save_sharded(dirname, _row_state_names(cfg), scope)
    meta = {"config": cfg.to_dict(), "step": table.step,
            "scalar_slots": {s: np.asarray(table.slots[s]).tolist()
                             for s in SCALAR_SLOTS[cfg.optimizer]}}
    with open(os.path.join(dirname, _META), "w") as f:
        json.dump(meta, f)
    return dirname


def load_table(dirname: str, mesh=None, hot_cache: bool = False
               ) -> ShardedTable:
    """Rebuild a ShardedTable from its checkpoint. State is restored
    piece-by-piece onto the table's sharding; the dense value is never
    assembled when the mesh layout matches the save."""
    with open(os.path.join(dirname, _META)) as f:
        meta = json.load(f)
    cfg = TableConfig.from_dict(meta["config"])
    table = ShardedTable(cfg, mesh=mesh, hot_cache=hot_cache)
    sh = table._sharding()
    scope = Scope()
    names = _row_state_names(cfg)
    shardings = {n: sh for n in names} if sh is not None else None
    load_sharded(dirname, shardings=shardings, scope=scope)
    table.param = _as_device(scope.get(f"{cfg.name}.param"), sh)
    for s in ROW_SLOTS[cfg.optimizer]:
        table.slots[s] = _as_device(scope.get(f"{cfg.name}.{s}"), sh)
    for s, v in meta.get("scalar_slots", {}).items():
        table.slots[s] = jnp.asarray(np.asarray(v, np.float32))
    table.step = int(meta["step"])
    return table


def _as_device(val, sharding):
    if sharding is None:
        return jnp.asarray(val)
    import jax
    if isinstance(val, jax.Array) and val.sharding == sharding:
        return val
    return jax.device_put(val, sharding)
