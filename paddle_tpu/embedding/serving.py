"""Serving a model whose embedding tables are row-sharded.

A `save_inference_model` export of a `distributed=True` model (e.g.
models/deepfm.py) keeps `is_distributed` on its lookup_table ops, so
the frozen program still routes through parallel/sparse.sharded_lookup
— IF the executor carries a mesh. The plain Executor a ServableModel
builds does not; :func:`load_sharded_servable` injects a
ParallelExecutor (plus its run lock) and re-places each table onto its
row-sharded layout in the servable's private scope, exactly the moment
the reference would hand tables to the pserver-backed lookup at serve
time. The returned ServableModel drops into the PR 7 lifecycle
unchanged (`ModelHost(model=...)` accepts a prebuilt servable), so
hot-swap/canary/admission all apply to sharded-table serving.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.executor import ParallelExecutor, ShardingSpec
from ..parallel.mesh import get_mesh, make_mesh
from ..serving.model import ServableModel


def _table_param_names(program, scope) -> Sequence[str]:
    """Tables of the frozen program: inputs of is_distributed
    lookup_table ops that are present in the loaded scope."""
    names = []
    desc = program.desc if hasattr(program, "desc") else program
    for block in desc.blocks:
        for op in block.ops:
            if op.type == "lookup_table" and \
                    op.attrs.get("is_distributed"):
                for w in op.input("W"):
                    if scope.find(w) is not None and w not in names:
                        names.append(w)
    return names


def load_sharded_servable(dirname: str, mesh=None, axis: str = "model",
                          table_names: Optional[Sequence[str]] = None,
                          **load_kw) -> ServableModel:
    """Load a save_inference_model export whose embedding tables should
    serve row-sharded over ``axis``. Default mesh: the active one, or
    an inference mesh (1, n_devices) over ('data', 'model') — batch
    replicated, tables sharded."""
    mesh = mesh if mesh is not None else get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        n = len(jax.devices())
        mesh = make_mesh((1, n), ("data", axis))
    run_lock = threading.Lock()
    exe = ParallelExecutor(
        mesh=mesh, sharding=ShardingSpec(specs={}, feed_axis="data"))
    model = ServableModel.load(dirname, executor=exe,
                               run_lock=run_lock, **load_kw)
    names = (list(table_names) if table_names is not None
             else _table_param_names(model.program, model.scope))
    sharding = NamedSharding(mesh, P(axis, None))
    for name in names:
        val = model.scope.get(name)
        model.scope.set(name, jax.device_put(val, sharding))
        exe.sharding.specs[name] = P(axis, None)
    return model
