"""Profiler (reference: platform/profiler.h RecordEvent tables + CUPTI
device tracer + tools/timeline.py chrome-trace export).

TPU-native design: host-side events wrap executor runs; device activity
comes from jax.profiler (XLA/TPU trace), which natively emits
chrome://tracing-compatible output — the xprof analog of the reference's
CUPTI + timeline.py pipeline.
"""
from __future__ import annotations

import contextlib
import json
import time
from typing import Dict, List, Optional

_events: List[Dict] = []
_enabled = False


class RecordEvent:
    """RAII event (reference: profiler.h:106)."""

    def __init__(self, name: str):
        self.name = name
        self.t0 = None

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if _enabled:
            _events.append({"name": self.name, "ts": self.t0 * 1e6,
                            "dur": (time.perf_counter() - self.t0) * 1e6,
                            "ph": "X", "pid": 0, "tid": 0})
        return False


def start_profiler(state: str = "All"):
    global _enabled
    _enabled = True
    _events.clear()


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    global _enabled
    _enabled = False
    if profile_path:
        export_chrome_trace(profile_path)
    return summary()


def summary():
    agg: Dict[str, Dict] = {}
    for e in _events:
        a = agg.setdefault(e["name"], {"calls": 0, "total_us": 0.0})
        a["calls"] += 1
        a["total_us"] += e["dur"]
    return agg


def export_chrome_trace(path: str):
    with open(path, "w") as f:
        json.dump({"traceEvents": _events}, f)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None):
    """Context manager parity with fluid.profiler.profiler (profiler.py:126)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def device_profiler(logdir: str):
    """TPU device trace via jax.profiler (xprof); view with tensorboard or
    Perfetto. Replaces the reference's CUPTI DeviceTracer."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
