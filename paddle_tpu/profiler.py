"""Profiler (reference: platform/profiler.h RecordEvent tables + CUPTI
device tracer + tools/timeline.py chrome-trace export).

TPU-native design: host-side events wrap executor runs; device activity
comes from jax.profiler (XLA/TPU trace), which natively emits
chrome://tracing-compatible output — the xprof analog of the reference's
CUPTI + timeline.py pipeline.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Callable, Dict, List, Optional

_events: List[Dict] = []
_enabled = False
# Guards _events against concurrent RecordEvent emission (serving
# workers, prefetcher, trainer thread all append) racing a reader:
# export_chrome_trace/events/summary snapshot the list under this lock
# instead of iterating the live list, so a mid-export append can never
# tear the JSON or skip/duplicate events.
_events_lock = threading.Lock()

# Optional callback returning {"trace_id": ..., "span_id": ...} for the
# current thread — installed by observability.trace so every event
# closed under an active StepTrace span is attributable to its step.
# Kept as a late-bound hook: the profiler must not import observability.
_trace_args_provider: Optional[Callable[[], Optional[Dict]]] = None

# Always-on event listeners: called with every CLOSED RecordEvent's
# dict, even while the profiler itself is disabled. This is the feed
# for the observability layer's live attribution (step-phase breakdown)
# and the flight recorder's ring buffer — neither may depend on a user
# having started a profiling session. Listeners must be cheap and must
# not raise (exceptions are swallowed); with no listener installed the
# disabled-profiler cost stays one list truthiness test.
_event_listeners: List[Callable[[Dict], None]] = []
_listeners_lock = threading.Lock()


def add_event_listener(fn: Callable[[Dict], None]) -> None:
    """Register ``fn(event_dict)`` to observe every closed RecordEvent
    (profiler enabled or not). Idempotent and thread-safe: concurrent
    registration of the same listener installs it exactly once."""
    with _listeners_lock:
        if fn not in _event_listeners:
            _event_listeners.append(fn)


def remove_event_listener(fn: Callable[[Dict], None]) -> None:
    with _listeners_lock:
        try:
            _event_listeners.remove(fn)
        except ValueError:
            pass


def has_event_listener(fn: Callable[[Dict], None]) -> bool:
    with _listeners_lock:
        return fn in _event_listeners


def set_trace_args_provider(fn: Optional[Callable[[], Optional[Dict]]]):
    """Install a callable whose (dict) result is merged into each
    recorded event's chrome-trace ``args`` (None = no-op)."""
    global _trace_args_provider
    _trace_args_provider = fn

# Event categories ("cat" in the chrome-trace schema). Host events from
# the serving runtime (paddle_tpu.serving) are tagged so a trace of a
# live server separates queueing/batching/compile time from model time.
CAT_SERVING = "serving"
# Retry/backoff spans from paddle_tpu.resilience.retry: each retry::<op>
# event covers the backoff sleep before that retry attempt.
CAT_RESILIENCE = "resilience"
# Host/device pipelining spans (core/executor.py + trainer.py + reader
# FeedPrefetcher). The first four names partition a training step's
# SERIAL host-side time (observability.attribution maps them to the
# feed/dispatch/fetch_sync/prefetch_wait phases; anything else lands in
# the device residual):
#   pipeline::dispatch      - enqueueing the jitted step (async, cheap)
#   pipeline::fetch_sync    - materializing fetched values to host
#   pipeline::prefetch_wait - consumer waiting on the feed prefetcher
#   pipeline::host_blocked  - inline (un-prefetched) reader+feed assembly
#   pipeline::sync_barrier  - explicit device barriers (checkpoint
#                             snapshot, Executor.synchronize): device
#                             drain, deliberately NOT a feed phase
#   pipeline::prefetch_fill - producer-thread convert+upload; overlaps
#                             device compute, so never part of the
#                             serial step breakdown
CAT_PIPELINE = "pipeline"
# Per-attempt RPC spans from distributed/jsonrpc.py (rpc::<op>): one
# event per wire attempt, so retried calls show as distinct spans that
# share the originating step's trace id.
CAT_RPC = "rpc"
# StepTrace root/child spans (observability/trace.py): trace::step/N
# covers one dispatched training step; every event closed inside it
# carries the step's trace_id/span_id in its args.
CAT_TRACE = "trace"


class RecordEvent:
    """RAII event (reference: profiler.h:106). `cat` is an optional
    chrome-trace category (e.g. CAT_SERVING) used to filter summaries;
    `args` lands in the chrome-trace event's args dict (merged with the
    active StepTrace context, when one is installed)."""

    def __init__(self, name: str, cat: Optional[str] = None,
                 args: Optional[Dict] = None):
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = None

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        listeners = _event_listeners
        if not _enabled and not listeners:
            return False
        ev = {"name": self.name, "ts": self.t0 * 1e6,
              "dur": (time.perf_counter() - self.t0) * 1e6,
              "ph": "X", "pid": 0, "tid": 0}
        if self.cat:
            ev["cat"] = self.cat
        args = dict(self.args) if self.args else {}
        if _trace_args_provider is not None:
            targs = _trace_args_provider()
            if targs:
                args.update(targs)
        if args:
            ev["args"] = args
        if _enabled:
            with _events_lock:
                _events.append(ev)
        # snapshot: a concurrent remove_event_listener must not skip
        # another listener mid-iteration
        for fn in list(listeners):
            try:
                fn(ev)
            except Exception:
                pass  # a broken listener must never break the hot path
        return False


def events(cat: Optional[str] = None) -> List[Dict]:
    """Snapshot of recorded host events, optionally filtered by category."""
    with _events_lock:
        snap = list(_events)
    return [e for e in snap if cat is None or e.get("cat") == cat]


def start_profiler(state: str = "All"):
    global _enabled
    _enabled = True
    with _events_lock:
        _events.clear()


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None):
    global _enabled
    _enabled = False
    if profile_path:
        export_chrome_trace(profile_path)
    return summary()


def summary(cat: Optional[str] = None):
    agg: Dict[str, Dict] = {}
    for e in events(cat=cat):
        a = agg.setdefault(e["name"], {"calls": 0, "total_us": 0.0})
        a["calls"] += 1
        a["total_us"] += e["dur"]
    return agg


def export_chrome_trace(path: str):
    # snapshot under the lock: exporting while serving workers /
    # prefetcher threads still emit RecordEvents must serialize a
    # consistent list, not iterate one being appended to
    with _events_lock:
        snap = list(_events)
    with open(path, "w") as f:
        json.dump({"traceEvents": snap}, f)


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None):
    """Context manager parity with fluid.profiler.profiler (profiler.py:126)."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def device_profiler(logdir: str):
    """TPU device trace via jax.profiler (xprof); view with tensorboard or
    Perfetto. Replaces the reference's CUPTI DeviceTracer."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def _parse_device_trace(logdir: str) -> List[Dict]:
    """Newest chrome trace under an xprof logdir -> flat event list
    (only complete 'X' events, annotated with their process name)."""
    import glob
    import gzip
    import os

    candidates = sorted(
        glob.glob(os.path.join(logdir, "plugins", "profile", "*",
                               "*.trace.json.gz")),
        key=os.path.getmtime)
    if not candidates:
        return []
    with gzip.open(candidates[-1], "rt") as f:
        tr = json.load(f)
    raw = tr.get("traceEvents", [])
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in raw
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    out = []
    for e in raw:
        if e.get("ph") != "X":
            continue
        out.append({"name": e.get("name", ""), "ts": e.get("ts", 0),
                    "dur": e.get("dur", 0), "ph": "X",
                    "pid": e.get("pid", 0), "tid": e.get("tid", 0),
                    "proc": pid_names.get(e.get("pid"), "")})
    return out


class MergedProfile:
    """One sorted per-op table + one timeline combining host
    RecordEvents with device (xprof) activity — the TPU-native analog
    of the reference's merged profiler output
    (platform/device_tracer.cc:40-74 + profiler.h:153-158, which fold
    CUPTI device records into the CPU event table)."""

    def __init__(self):
        self.host_events: List[Dict] = []
        self.device_events: List[Dict] = []

    def table(self, limit: Optional[int] = None) -> List[Dict]:
        agg: Dict = {}
        for e in self.host_events:
            a = agg.setdefault(("host", e["name"]),
                               {"calls": 0, "total_us": 0.0})
            a["calls"] += 1
            a["total_us"] += e["dur"]
        for e in self.device_events:
            if "device" not in e.get("proc", "").lower() \
                    and "tpu" not in e.get("proc", "").lower():
                continue
            a = agg.setdefault(("device", e["name"]),
                               {"calls": 0, "total_us": 0.0})
            a["calls"] += 1
            a["total_us"] += e["dur"]
        rows = [{"place": k[0], "name": k[1], **v} for k, v in agg.items()]
        rows.sort(key=lambda r: -r["total_us"])
        return rows[:limit] if limit else rows

    def export_chrome_trace(self, path: str):
        """Host and device events in ONE timeline (host pid 0; device
        events keep their trace pids, offset to avoid collision)."""
        events = list(self.host_events)
        for e in self.device_events:
            d = dict(e)
            d.pop("proc", None)
            d["pid"] = 1000 + int(d.get("pid", 0))
            events.append(d)
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def __str__(self):
        lines = [f"{'place':8s} {'total ms':>10s} {'calls':>7s}  name"]
        for r in self.table(limit=40):
            lines.append(f"{r['place']:8s} {r['total_us'] / 1e3:10.3f} "
                         f"{r['calls']:7d}  {r['name'][:70]}")
        return "\n".join(lines)


@contextlib.contextmanager
def merged_profile(logdir: str = "/tmp/paddle_tpu_xprof"):
    """Capture host RecordEvents AND a device trace in one scope; yields
    a MergedProfile filled on exit.

        with profiler.merged_profile() as prof:
            train_steps()
        print(prof)                      # one sorted host+device table
        prof.export_chrome_trace("t.json")   # one merged timeline
    """
    import jax

    global _enabled
    prof = MergedProfile()
    with _events_lock:
        prev_events = list(_events)
        _events.clear()
    _enabled = True
    jax.profiler.start_trace(logdir)
    try:
        yield prof
    finally:
        jax.profiler.stop_trace()
        _enabled = False
        with _events_lock:
            prof.host_events = list(_events)
            _events.clear()
            _events.extend(prev_events)
        try:
            prof.device_events = _parse_device_trace(logdir)
        except Exception:
            prof.device_events = []
