"""DataFeeder: reader tuples -> feed dict (reference:
python/paddle/fluid/data_feeder.py DataFeeder.feed — converts a batch of
per-sample tuples to LoDTensors per data var; v2's `feeding` dict).

TPU-native: dense vars become stacked numpy arrays; lod_level>0 vars
become RaggedPair (padded data + lengths), the framework's static-shape
LoD representation. Padding length defaults to the longest sequence in
the batch, bucketed up to `pad_multiple` to limit XLA recompilation."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .core.lod import LoDTensor, RaggedPair


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None,
                 pad_multiple: int = 16, sub_pad_multiple: int = 4,
                 max_lens: Optional[Dict[str, int]] = None,
                 freeze: bool = False):
        self.feed_vars = list(feed_list)
        self.pad_multiple = pad_multiple
        # lod_level=2 sub-sequence axis bucketing (1 disables; keeps
        # compile signatures stable when sentence counts vary)
        self.sub_pad_multiple = max(1, int(sub_pad_multiple))
        self.max_lens = max_lens or {}
        # freeze=True returns read-only owning arrays, which the executor
        # caches device-side by identity — useful when the same batch is fed
        # repeatedly (eval sets, benchmarks). Off by default so callers may
        # mutate fed arrays in place.
        self.freeze = freeze

    def feed(self, batch: Sequence[Sequence]) -> Dict[str, object]:
        """batch: iterable of per-sample tuples aligned with feed_list."""
        out: Dict[str, object] = {}
        for i, var in enumerate(self.feed_vars):
            name = var if isinstance(var, str) else var.name
            lod_level = 0 if isinstance(var, str) else (var.lod_level or 0)
            dtype = "float32" if isinstance(var, str) else var.dtype
            column = [sample[i] for sample in batch]
            if lod_level >= 3:
                out[name] = self._tree(name, column, dtype, var, lod_level)
            elif lod_level == 2:
                out[name] = self._nested(name, column, dtype, var)
            elif lod_level > 0:
                out[name] = self._ragged(name, column, dtype, var)
            else:
                arr = np.asarray(column, dtype=np.dtype(dtype))
                shape = None if isinstance(var, str) else var.shape
                if shape is not None and len(shape) >= 1 and arr.ndim == 1:
                    arr = arr.reshape(len(column), *[
                        d for d in shape[1:] if d and d > 0] or [1])
                if self.freeze:
                    # the executor only caches frozen OWNING arrays; reshape
                    # yields views (owndata=False), so materialize first
                    if not arr.flags.owndata:
                        arr = arr.copy()
                    arr.flags.writeable = False
                out[name] = arr
        return out

    def feed_device(self, batch: Sequence[Sequence]) -> Dict[str, object]:
        """`feed()` plus host->device upload: every value is converted to
        its in-graph device form (`core.executor._to_device_value`, so
        frozen owning arrays still route through the device-side feed
        cache). This is the form the FeedPrefetcher parks — uploading
        batch N+1 while batch N computes — and `Executor.run` accepts it
        unchanged (device conversion is idempotent)."""
        from .core.executor import device_feed
        return device_feed(self.feed(batch))

    @staticmethod
    def _feat_dims(var):
        if not isinstance(var, str) and var.shape:
            # declared [-1?, feat...]: per-step feature dims after batch
            return [d for d in var.shape[1:] if d and d > 0]
        return None

    @staticmethod
    def _to_step_array(seq, np_dtype, feat):
        """One flat-or-shaped sequence -> [steps, *feat] (the shared
        flat-token reshape convention of the level-1 and level-2 paths)."""
        a = np.asarray(seq, np_dtype)
        if feat and a.ndim == 1:
            a = a.reshape(len(a) // int(np.prod(feat)), *feat) \
                if np.prod(feat) > 1 else a.reshape(len(a), *feat)
        elif a.ndim == 1:
            a = a.reshape(len(a), 1)
        return a

    def _nested(self, name, column, dtype, var):
        """lod_level=2 var: each sample is a list of sub-sequences
        (paragraph -> sentences -> tokens); -> RaggedNested via the
        2-level LoDTensor conversion. Applies the same flat-token
        reshape convention, max_lens cap (token level), and
        pad_multiple bucketing as the level-1 path."""
        from .core.lod import RaggedNested
        np_dtype = np.dtype(dtype)
        feat = self._feat_dims(var)
        max_tok = self.max_lens.get(name)
        nested = []
        longest_tok = 1
        longest_sub = 1
        for sample in column:
            subs = []
            for seq in sample:
                a = self._to_step_array(seq, np_dtype, feat)
                if max_tok is not None:
                    a = a[:max_tok]  # hard cap truncates (bucketing)
                subs.append(a)
                longest_tok = max(longest_tok, a.shape[0])
            nested.append(subs)
            longest_sub = max(longest_sub, len(subs))
        m = self.pad_multiple
        pad_tok = max_tok if max_tok is not None else \
            ((longest_tok + m - 1) // m) * m
        # the sub-sequence axis buckets too so batches with varying
        # sentence counts reuse compile signatures
        m2 = self.sub_pad_multiple
        pad_sub = ((longest_sub + m2 - 1) // m2) * m2
        data, sub_l, tok_l = LoDTensor.from_nested_sequences(
            nested, feat_shape=feat, dtype=np_dtype).to_nested_padded(
                max_sub=pad_sub, max_tok=pad_tok)
        return RaggedNested(data, sub_l, tok_l)

    def _tree(self, name, column, dtype, var, depth):
        """lod_level>=3 var: each sample is depth-(k-1) nested lists of
        token sequences -> RaggedTree via the depth-k LoDTensor
        conversion (reference: arbitrary-depth LoD,
        lod_tensor.h:55-107). Applies the flat-token reshape at leaves,
        the max_lens cap on the token level, and pad_multiple bucketing
        on the token dim."""
        from .core.lod import RaggedTree
        np_dtype = np.dtype(dtype)
        feat = self._feat_dims(var)
        max_tok = self.max_lens.get(name)

        def conv(node, level):
            if level == depth - 1:
                a = self._to_step_array(node, np_dtype, feat)
                return a if max_tok is None else a[:max_tok]
            return [conv(c, level + 1) for c in node]

        nested = [conv(sample, 0) for sample in column]
        lt = LoDTensor.from_depth_sequences(
            nested, depth, feat_shape=tuple(feat or ()), dtype=np_dtype)
        # bucket the token dim so varying batch contents reuse compile
        # signatures; group-count dims pad to the batch max
        m = self.pad_multiple
        tok_max = int(np.max(np.diff(lt.lod[-1]))) if len(lt.lod[-1]) > 1 \
            else 1
        max_dims = [None] * (depth - 1) + [((tok_max + m - 1) // m) * m]
        data, lengths = lt.to_tree_padded(max_dims=max_dims)
        return RaggedTree(data, tuple(lengths))

    def _ragged(self, name, column, dtype, var):
        np_dtype = np.dtype(dtype)
        feat = self._feat_dims(var)
        arrs = [self._to_step_array(seq, np_dtype, feat)
                for seq in column]
        max_len = self.max_lens.get(name)
        if max_len is None:
            longest = max((a.shape[0] for a in arrs), default=1)
            m = self.pad_multiple
            max_len = ((longest + m - 1) // m) * m
        else:
            # a hard cap truncates (the standard bucketing behavior);
            # to_padded would otherwise fail on longer sequences
            arrs = [a[:max_len] for a in arrs]
        lod = LoDTensor.from_sequences(arrs, feat_shape=feat,
                                       dtype=np_dtype)
        padded, lengths = lod.to_padded(max_len=max_len)
        return RaggedPair(padded, lengths)
