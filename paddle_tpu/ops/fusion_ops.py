"""Fused mega-ops produced by the rewrite layer's subgraph outlining
(analysis/rewrite.py).

These ops exist so a matched multi-op subgraph becomes ONE op in the
IR: one row in the cost model, one unit for the verifier, and one
dispatch point for a hand kernel. Gradients come from the generic
``__vjp__`` grad op (core/backward.py) — every compute rule here is
differentiable JAX, so the outlined backward is exact.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .math_ops import _mxu_matmul


@register_op("se_block")
def _se_block(ctx):
    """Squeeze-excitation channel gate as one op: global average pool
    -> bottleneck FC (relu) -> expand FC (sigmoid) -> per-channel gate.

    X: [n, c, h, w]; W1: [c, r]; B1: [r]; W2: [r, c]; B2: [c].
    Mirrors the composed layer chain (models/resnet.py
    squeeze_excitation) the rewrite layer outlines into this op; the
    pooled reduction accumulates in f32 exactly like pool2d's avg path
    so bf16 activations lose no mantissa.
    """
    x = ctx.input("X")
    w1, b1 = ctx.input("W1"), ctx.input("B1")
    w2, b2 = ctx.input("W2"), ctx.input("B2")
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    pooled = (jnp.sum(xf, axis=(2, 3)) /
              (x.shape[2] * x.shape[3])).astype(x.dtype)  # [n, c]
    h1 = _mxu_matmul(pooled, w1)
    if b1 is not None:
        h1 = h1 + b1.reshape(1, -1)
    h1 = jax.nn.relu(h1)
    g = _mxu_matmul(h1, w2)
    if b2 is not None:
        g = g + b2.reshape(1, -1)
    g = jax.nn.sigmoid(g)
    ctx.set_output("Out", x * g[:, :, None, None])
