"""Detection ops: SSD pipeline primitives.

Reference parity: paddle/fluid/operators/{prior_box_op.cc, box_coder_op.cc,
iou_similarity_op.cc, bipartite_match_op.cc, target_assign_op.cc,
mine_hard_examples_op.cc, multiclass_nms_op.cc}. TPU-native design: every
op is static-shape — NMS returns a fixed-capacity [N, keep_top_k] result
with a validity count instead of the reference's variable-length LoD
output, and bipartite matching runs as a bounded greedy lax.while-free
argmax loop (#columns iterations, fully unrolled by XLA).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


def expand_aspect_ratios(ars, flip):
    """Reference ExpandAspectRatios (prior_box_op.h:23-40): 1.0 first,
    then each new ratio (+ its reciprocal when flip), deduplicated.
    Shared with layers.detection.multi_box_head so head channel counts
    always match the op's prior count."""
    out = [1.0]
    for a in ars:
        if any(abs(a - e) < 1e-6 for e in out):
            continue
        out.append(float(a))
        if flip:
            out.append(1.0 / float(a))
    return out


def _greedy_match(dist, steps, min_valid):
    """Greedy bipartite core shared by the bipartite_match op and the
    fused ssd_loss: repeatedly take the globally-largest entry above
    `min_valid`, retire its row and column. Bounded `steps` iterations —
    static for XLA."""
    m = dist.shape[1]

    def body(_, state):
        d, row_of_col, dist_of_col = state
        flat = jnp.argmax(d)
        i, j = flat // m, flat % m
        best = d[i, j]
        found = best > min_valid
        row_of_col = jnp.where(found, row_of_col.at[j].set(i), row_of_col)
        dist_of_col = jnp.where(found, dist_of_col.at[j].set(best),
                                dist_of_col)
        d = jnp.where(found, d.at[i, :].set(-jnp.inf), d)
        d = jnp.where(found, d.at[:, j].set(-jnp.inf), d)
        return d, row_of_col, dist_of_col

    row0 = jnp.full((m,), -1, jnp.int32)
    dist0 = jnp.zeros((m,), dist.dtype)
    _, row, dist_out = jax.lax.fori_loop(0, steps, body,
                                         (dist, row0, dist0))
    return row, dist_out


@register_op("prior_box", no_grad_slots=["Input", "Image"])
def _prior_box(ctx):
    """SSD prior (anchor) boxes for one feature map (prior_box_op.cc).
    Outputs Boxes [H, W, num_priors, 4] (normalized xmin,ymin,xmax,ymax)
    and Variances broadcast to the same shape."""
    feat = ctx.input("Input")    # [N, C, H, W]
    image = ctx.input("Image")   # [N, C, IH, IW]
    min_sizes = [float(s) for s in ctx.attr("min_sizes")]
    max_sizes = [float(s) for s in ctx.attr("max_sizes", []) or []]
    ars = [float(a) for a in ctx.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in ctx.attr("variances",
                                            [0.1, 0.1, 0.2, 0.2])]
    flip = ctx.attr("flip", False)
    clip = ctx.attr("clip", False)
    step_w = ctx.attr("step_w", 0.0)
    step_h = ctx.attr("step_h", 0.0)
    offset = ctx.attr("offset", 0.5)

    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w if step_w > 0 else iw / w
    sh = step_h if step_h > 0 else ih / h

    out_ars = expand_aspect_ratios(ars, flip)

    # reference pairs max_sizes[i] with min_sizes[i]: per min size, one
    # prior per aspect ratio, then one square sqrt(min*max) prior
    # (prior_box_op.h:107-129; num_priors = |ars|*|min| + |max|)
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("max_sizes must pair 1:1 with min_sizes")
    widths, heights = [], []
    for i, ms in enumerate(min_sizes):
        for a in out_ars:
            widths.append(ms * np.sqrt(a))
            heights.append(ms / np.sqrt(a))
        if max_sizes:
            s = np.sqrt(ms * max_sizes[i])
            widths.append(s)
            heights.append(s)
    widths = jnp.asarray(widths, jnp.float32)
    heights = jnp.asarray(heights, jnp.float32)
    num_priors = widths.shape[0]

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)               # [h, w]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    half_w = widths.reshape(1, 1, -1) / 2.0
    half_h = heights.reshape(1, 1, -1) / 2.0
    boxes = jnp.stack([(cxg - half_w) / iw, (cyg - half_h) / ih,
                       (cxg + half_w) / iw, (cyg + half_h) / ih], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, num_priors, 4))
    ctx.set_output("Boxes", boxes)
    ctx.set_output("Variances", var)


def _pairwise_iou(x, y):
    """IoU between box sets x [N, 4] and y [M, 4] -> [N, M]."""
    area = lambda b: jnp.maximum(b[:, 2] - b[:, 0], 0) * \
        jnp.maximum(b[:, 3] - b[:, 1], 0)
    ix1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area(x)[:, None] + area(y)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", no_grad_slots=["X", "Y"])
def _iou_similarity(ctx):
    """Pairwise IoU between two box sets (iou_similarity_op.cc):
    X [N, 4], Y [M, 4] -> [N, M]."""
    ctx.set_output("Out", _pairwise_iou(ctx.input("X"), ctx.input("Y")))


@register_op("box_coder", no_grad_slots=["PriorBox", "PriorBoxVar"])
def _box_coder(ctx):
    """Encode/decode target boxes against priors (box_coder_op.cc)."""
    prior = ctx.input("PriorBox")       # [M, 4] xmin,ymin,xmax,ymax
    pvar = ctx.input("PriorBoxVar")     # [M, 4] or None
    target = ctx.input("TargetBox")
    code_type = ctx.attr("code_type", "encode_center_size")
    normalized = ctx.attr("box_normalized", True)
    off = 0.0 if normalized else 1.0

    pw = prior[:, 2] - prior[:, 0] + off
    ph = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    if pvar is None:
        pvar = jnp.ones_like(prior)

    if code_type.lower() in ("encode_center_size", "encode"):
        # target [N, 4] -> out [N, M, 4]
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None, :]) / pvar[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None, :]) / pvar[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    else:  # decode_center_size
        # target [N, M, 4] deltas -> boxes [N, M, 4]
        t = target
        cx = pvar[None, :, 0] * t[..., 0] * pw[None, :] + pcx[None, :]
        cy = pvar[None, :, 1] * t[..., 1] * ph[None, :] + pcy[None, :]
        bw = jnp.exp(pvar[None, :, 2] * t[..., 2]) * pw[None, :]
        bh = jnp.exp(pvar[None, :, 3] * t[..., 3]) * ph[None, :]
        out = jnp.stack([cx - bw / 2, cy - bh / 2,
                         cx + bw / 2 - off, cy + bh / 2 - off], axis=-1)
    ctx.set_output("OutputBox", out)


@register_op("bipartite_match", no_grad_slots=["DistMat"])
def _bipartite_match(ctx):
    """Greedy bipartite matching (bipartite_match_op.cc): repeatedly take
    the globally-largest entry, retire its row+col. Bounded loop of
    min(N, M) steps — static for XLA."""
    dist = ctx.input("DistMat")  # [N, M] similarity (rows = gt, cols=prior)
    n, m = dist.shape
    row_of_col, dist_of_col = _greedy_match(dist, min(n, m), -jnp.inf)
    match_type = ctx.attr("match_type", "bipartite")
    if match_type == "per_prediction":
        thr = ctx.attr("dist_threshold", 0.5)
        best_row = jnp.argmax(ctx.input("DistMat"), axis=0).astype(jnp.int32)
        best_val = jnp.max(ctx.input("DistMat"), axis=0)
        extra = (row_of_col < 0) & (best_val > thr)
        row_of_col = jnp.where(extra, best_row, row_of_col)
        dist_of_col = jnp.where(extra, best_val, dist_of_col)
    ctx.set_output("ColToRowMatchIndices", row_of_col[None, :])
    ctx.set_output("ColToRowMatchDist", dist_of_col[None, :])


@register_op("target_assign", no_grad_slots=["X", "MatchIndices",
                                             "NegIndices"])
def _target_assign(ctx):
    """Assign per-prior regression/classification targets from matched gt
    (target_assign_op.cc): out[j] = X[match[j]] where matched, else
    mismatch_value; weight 1 where matched (or negative), else 0."""
    x = ctx.input("X")                    # [P, K] per-gt targets
    match = ctx.input("MatchIndices")     # [1, M] row index per prior
    mismatch_value = ctx.attr("mismatch_value", 0)
    m = match.shape[-1]
    match = match.reshape(-1)
    matched = match >= 0
    safe = jnp.clip(match, 0, x.shape[0] - 1)
    if x.ndim == 3:
        # reference (target_assign_op.h) gathers per-prior columns:
        # out[j] = X[match[j], j, :]
        gathered = x[safe, jnp.arange(m)]
    else:
        gathered = x[safe]
    out = jnp.where(matched[:, None], gathered,
                    jnp.full((m, gathered.shape[-1]), mismatch_value,
                             x.dtype))
    wt = matched.astype(jnp.float32)[:, None]
    neg = ctx.input("NegIndices")
    if neg is not None:
        # NegIndices is -1-padded (mine_hard_examples); a raw scatter
        # would wrap -1 to the last prior, so count only valid hits
        neg = neg.reshape(-1).astype(jnp.int32)
        valid = (neg >= 0).astype(jnp.float32)
        hits = jnp.zeros((m,), jnp.float32).at[
            jnp.clip(neg, 0, m - 1)].add(valid)
        wt = jnp.maximum(wt, (hits > 0).astype(jnp.float32)[:, None])
    ctx.set_output("Out", out[None])
    ctx.set_output("OutWeight", wt[None])


@register_op("mine_hard_examples", no_grad_slots=["ClsLoss", "MatchIndices",
                                                  "MatchDist"])
def _mine_hard_examples(ctx):
    """Hard-negative mining (mine_hard_examples_op.cc): pick the
    highest-loss unmatched priors, neg:pos <= neg_pos_ratio. Static-shape
    form: NegIndices is [M] with -1 padding + UpdatedMatchIndices."""
    cls_loss = ctx.input("ClsLoss")         # [1, M] or [M]
    match = ctx.input("MatchIndices").reshape(-1)
    loss = cls_loss.reshape(-1)
    m = loss.shape[0]
    ratio = ctx.attr("neg_pos_ratio", 3.0)
    num_pos = jnp.sum((match >= 0).astype(jnp.int32))
    num_neg = jnp.minimum((num_pos * ratio).astype(jnp.int32),
                          m - num_pos)
    neg_loss = jnp.where(match >= 0, -jnp.inf, loss)
    order = jnp.argsort(-neg_loss)          # highest loss first
    ranks = jnp.arange(m)
    neg_idx = jnp.where(ranks < num_neg, order, -1).astype(jnp.int32)
    ctx.set_output("NegIndices", neg_idx[None])
    ctx.set_output("UpdatedMatchIndices", match[None])


@register_op("multiclass_nms", no_grad_slots=["BBoxes", "Scores"])
def _multiclass_nms(ctx):
    """Multi-class NMS (multiclass_nms_op.cc), TPU static-shape form:
    returns Out [N, keep_top_k, 6] = (label, score, x1, y1, x2, y2) with
    score -1 padding, plus NumDetections [N]."""
    bboxes = ctx.input("BBoxes")   # [N, M, 4]
    scores = ctx.input("Scores")   # [N, C, M]
    score_threshold = ctx.attr("score_threshold", 0.0)
    nms_threshold = ctx.attr("nms_threshold", 0.3)
    nms_top_k = int(ctx.attr("nms_top_k", 64))
    keep_top_k = int(ctx.attr("keep_top_k", 64))
    background_label = ctx.attr("background_label", 0)

    def one_class(boxes, cls_scores):
        # reference allows -1 = "keep all" for nms_top_k/keep_top_k
        k = boxes.shape[0] if nms_top_k <= 0 else min(nms_top_k,
                                                      boxes.shape[0])
        top_scores, top_idx = jax.lax.top_k(cls_scores, k)
        top_boxes = boxes[top_idx]
        ious = _pairwise_iou(top_boxes, top_boxes)
        # greedy suppression: keep i if no higher-scoring kept j overlaps
        def body(i, keep):
            overlap = (ious[i] > nms_threshold) & keep & \
                (jnp.arange(k) < i)
            return keep.at[i].set(~jnp.any(overlap) & keep[i])
        keep0 = top_scores > score_threshold
        keep = jax.lax.fori_loop(0, k, body, keep0)
        return top_scores, top_boxes, keep

    def one_image(boxes, img_scores):
        all_s, all_b, all_l, all_k = [], [], [], []
        for c in range(img_scores.shape[0]):
            if c == background_label:
                continue
            s, b, kmask = one_class(boxes, img_scores[c])
            all_s.append(jnp.where(kmask, s, -1.0))
            all_b.append(b)
            all_l.append(jnp.full(s.shape, c, jnp.float32))
            all_k.append(kmask)
        s = jnp.concatenate(all_s)
        b = jnp.concatenate(all_b, axis=0)
        l = jnp.concatenate(all_l)
        kk = s.shape[0] if keep_top_k <= 0 else min(keep_top_k, s.shape[0])
        top_s, idx = jax.lax.top_k(s, kk)
        out = jnp.concatenate([l[idx][:, None], top_s[:, None],
                               b[idx]], axis=1)
        num = jnp.sum((top_s > 0).astype(jnp.int32))
        # pad invalid rows with score -1 (already -1 from the mask)
        return out, num

    outs, nums = jax.vmap(one_image)(bboxes, scores)
    ctx.set_output("Out", outs)
    ctx.set_output("NumDetections", nums)


def _encode_boxes(gt, prior, pvar):
    """Center-size encode gt [M, 4] (already gathered per prior) against
    priors [M, 4] (box_coder encode semantics, normalized boxes)."""
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    tw = gt[:, 2] - gt[:, 0]
    th = gt[:, 3] - gt[:, 1]
    tcx = gt[:, 0] + tw / 2
    tcy = gt[:, 1] + th / 2
    ox = (tcx - pcx) / pw / pvar[:, 0]
    oy = (tcy - pcy) / ph / pvar[:, 1]
    ow = jnp.log(jnp.maximum(tw / pw, 1e-10)) / pvar[:, 2]
    oh = jnp.log(jnp.maximum(th / ph, 1e-10)) / pvar[:, 3]
    return jnp.stack([ox, oy, ow, oh], axis=-1)


@register_op("ssd_loss", no_grad_slots=["GtBox", "GtLabel", "PriorBox",
                                        "PriorBoxVar"])
def _ssd_loss(ctx):
    """Fused SSD multibox loss (reference: detection.py ssd_loss:349 —
    which chains iou_similarity, bipartite_match, target_assign,
    mine_hard_examples, box_coder, smooth_l1 as separate LoD ops per
    batch). TPU-native form: the whole pipeline is one vmapped static-
    shape rule, so XLA fuses matching, mining, and both losses into the
    training step. Ground truth arrives padded [B, G, 4] / [B, G] with
    label -1 marking absent rows (the dense replacement for LoD gt).
    Output: per-image loss [B], normalized by max(num_pos, 1) when
    `normalize`."""
    loc = ctx.input("Location")        # [B, M, 4]
    conf = ctx.input("Confidence")     # [B, M, C]
    gt_box = ctx.input("GtBox")        # [B, G, 4]
    gt_label = ctx.input("GtLabel")    # [B, G] int, -1 pad
    prior = ctx.input("PriorBox")      # [M, 4]
    pvar = ctx.input("PriorBoxVar")
    if pvar is None:
        pvar = jnp.ones_like(prior)
    bg = int(ctx.attr("background_label", 0))
    overlap_t = ctx.attr("overlap_threshold", 0.5)
    ratio = ctx.attr("neg_pos_ratio", 3.0)
    neg_overlap = ctx.attr("neg_overlap", 0.5)
    loc_w = ctx.attr("loc_loss_weight", 1.0)
    conf_w = ctx.attr("conf_loss_weight", 1.0)
    match_type = ctx.attr("match_type", "per_prediction")
    normalize = ctx.attr("normalize", True)
    m = prior.shape[0]

    if gt_label.ndim == 3 and gt_label.shape[-1] == 1:
        gt_label = gt_label[..., 0]
    gt_label = gt_label.astype(jnp.int32)

    def one_image(loc_i, conf_i, gtb_i, gtl_i):
        valid = gtl_i >= 0                                     # [G]
        sim = _pairwise_iou(gtb_i, prior)                      # [G, M]
        sim = jnp.where(valid[:, None], sim, -1.0)
        # min_valid 0.0: padded gt rows (sim forced to -1) never match
        match, match_dist = _greedy_match(sim, sim.shape[0], 0.0)
        if match_type == "per_prediction":
            best_row = jnp.argmax(sim, axis=0).astype(jnp.int32)
            best_val = jnp.max(sim, axis=0)
            extra = (match < 0) & (best_val > overlap_t)
            match = jnp.where(extra, best_row, match)
            match_dist = jnp.where(extra, best_val, match_dist)
        matched = match >= 0                                   # [M]
        safe = jnp.clip(match, 0, gtb_i.shape[0] - 1)

        # conf loss per prior against current targets (for mining)
        tgt_label = jnp.where(matched, gtl_i[safe], bg)
        logp = jax.nn.log_softmax(conf_i, axis=-1)
        ce = -jnp.take_along_axis(logp, tgt_label[:, None],
                                  axis=1)[:, 0]                # [M]

        # max_negative mining: top-loss unmatched priors whose best
        # overlap is under neg_overlap
        num_pos = matched.sum()
        neg_cand = (~matched) & (jnp.max(sim, axis=0) < neg_overlap)
        num_neg = jnp.minimum((num_pos * ratio).astype(jnp.int32),
                              neg_cand.sum().astype(jnp.int32))
        neg_loss = jnp.where(neg_cand, ce, -jnp.inf)
        order = jnp.argsort(-neg_loss)
        is_neg = jnp.zeros((m,), bool).at[order].set(
            jnp.arange(m) < num_neg)
        is_neg = is_neg & neg_cand

        # localization loss on positives (smooth l1 on encoded deltas)
        enc = _encode_boxes(gtb_i[safe], prior, pvar)          # [M, 4]
        diff = loc_i - enc
        ad = jnp.abs(diff)
        sl1 = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5).sum(-1)
        loc_loss = (sl1 * matched).sum()

        conf_loss = (ce * (matched | is_neg)).sum()
        total = conf_w * conf_loss + loc_w * loc_loss
        if normalize:
            total = total / jnp.maximum(num_pos.astype(total.dtype), 1.0)
        return total

    loss = jax.vmap(one_image)(loc, conf, gt_box, gt_label)
    ctx.set_output("Loss", loss[:, None])
