"""Linear-chain CRF ops: forward-algorithm likelihood and Viterbi decode.

Capability parity with the reference's CRF kernels (reference:
paddle/fluid/operators/linear_chain_crf_op.{h,cc} — forward algorithm over
LoD sequences with a [num_tags+2, num_tags] transition matrix whose rows
0/1 are the start/end weights — and crf_decoding_op.h Viterbi decode).
TPU-native design: the time recursion is one jax.lax.scan over the padded
batch with validity masking (no per-sequence loops), so XLA compiles a
single fused loop; the gradient of linear_chain_crf comes from the generic
vjp fallback (the whole forward is differentiable JAX), where the
reference hand-derives the backward kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import RaggedPair
from .sequence_ops import _as_ragged, register_op_SEQ


def _crf_components(transition):
    # Rows 0 and 1 carry start/end weights (reference transition layout,
    # linear_chain_crf_op.h).
    return transition[0], transition[1], transition[2:]


def _nll(emission, lengths, label, transition):
    """Negative log-likelihood per sequence. emission [B,T,D], label [B,T]."""
    start, stop, trans = _crf_components(transition)
    B, T, D = emission.shape
    t_idx = jnp.arange(T)
    valid = t_idx[None, :] < lengths[:, None]          # [B,T]

    # log Z by the forward algorithm.
    alpha0 = start[None, :] + emission[:, 0]           # [B,D]

    def fwd(alpha, xs):
        em_t, valid_t = xs
        new = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None], axis=1) + em_t
        return jnp.where(valid_t[:, None], new, alpha), None

    if T > 1:
        xs = (jnp.swapaxes(emission[:, 1:], 0, 1),
              jnp.swapaxes(valid[:, 1:], 0, 1))
        alpha, _ = jax.lax.scan(fwd, alpha0, xs)
    else:
        alpha = alpha0
    log_z = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=-1)

    # Gold-path score (vectorized; no recursion needed).
    em_gold = jnp.take_along_axis(emission, label[..., None],
                                  axis=2).squeeze(-1)  # [B,T]
    score = start[label[:, 0]] + jnp.sum(
        jnp.where(valid, em_gold, 0.0), axis=1)
    if T > 1:
        trans_gold = trans[label[:, :-1], label[:, 1:]]   # [B,T-1]
        score = score + jnp.sum(
            jnp.where(valid[:, 1:], trans_gold, 0.0), axis=1)
    last = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(label, last[:, None], axis=1)[:, 0]
    score = score + stop[last_tag]

    return log_z - score


@register_op_SEQ("linear_chain_crf", no_grad_slots=["Label"])
def _linear_chain_crf(ctx):
    em = _as_ragged(ctx.input("Emission"))
    label = _as_ragged(ctx.input("Label"))
    transition = ctx.input("Transition")
    lab = label.data
    if lab.ndim == 3:
        lab = lab.squeeze(-1)
    nll = _nll(em.data, em.lengths, lab, transition)
    ctx.set_output("LogLikelihood", nll[:, None])
    # Reference also emits normalized intermediates for its hand-written
    # backward (EmissionExps/TransitionExps/Alpha); autodiff makes them
    # unnecessary but the slots stay wired for API parity.
    ctx.set_output("Alpha", em.data)
    ctx.set_output("EmissionExps", em.data)
    ctx.set_output("TransitionExps", transition)


@register_op_SEQ("crf_decoding", no_grad_slots=["Emission", "Transition",
                                                "Label"])
def _crf_decoding(ctx):
    em = _as_ragged(ctx.input("Emission"))
    transition = ctx.input("Transition")
    start, stop, trans = _crf_components(transition)
    emission, lengths = em.data, em.lengths
    B, T, D = emission.shape
    valid = jnp.arange(T)[None, :] < lengths[:, None]

    # Viterbi forward: track best scores and backpointers.
    delta0 = start[None, :] + emission[:, 0]

    def fwd(delta, xs):
        em_t, valid_t = xs
        cand = delta[:, :, None] + trans[None]           # [B,D_prev,D]
        best_prev = jnp.argmax(cand, axis=1)             # [B,D]
        new = jnp.max(cand, axis=1) + em_t
        delta_out = jnp.where(valid_t[:, None], new, delta)
        return delta_out, best_prev

    if T > 1:
        xs = (jnp.swapaxes(emission[:, 1:], 0, 1),
              jnp.swapaxes(valid[:, 1:], 0, 1))
        delta, back = jax.lax.scan(fwd, delta0, xs)      # back [T-1,B,D]
    else:
        delta = delta0
        back = jnp.zeros((0, B, D), jnp.int32)

    # Sequences end at length-1: take argmax of delta+stop there, then walk
    # backpointers in reverse, freezing the tag for t >= length.
    last_tag = jnp.argmax(delta + stop[None, :], axis=-1)  # [B]

    def bwd(tag, xs):
        back_t, t = xs                                    # back_t [B,D]
        prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
        # Position t is "inside" sequence b iff t+1 <= length-1.
        inside = (t + 1) <= (lengths - 1)
        new_tag = jnp.where(inside, prev, tag)
        return new_tag, new_tag

    ts = jnp.arange(T - 1)
    _, path_rev = jax.lax.scan(bwd, last_tag, (back, ts), reverse=True)
    path = jnp.concatenate([path_rev, last_tag[None]], axis=0) if T > 1 \
        else last_tag[None]
    path = jnp.swapaxes(path, 0, 1)                       # [B,T]
    path = jnp.where(valid, path, 0).astype(jnp.int64)

    label = ctx.input("Label")
    if label is not None:
        lab = _as_ragged(label).data
        if lab.ndim == 3:
            lab = lab.squeeze(-1)
        # With a gold Label input, the op emits per-position correctness
        # (reference crf_decoding_op.h behavior).
        out = (path == lab).astype(jnp.int64) * valid
        ctx.set_output("ViterbiPath", RaggedPair(out[..., None], lengths))
    else:
        ctx.set_output("ViterbiPath", RaggedPair(path[..., None], lengths))
