"""Variable-length sequence ops over ragged batches, and scan-based RNNs.

The reference implements these over LoDTensors with CPU/CUDA kernels that
reorder ragged batches (sequence2batch, reference:
paddle/fluid/operators/math/sequence2batch.h, lstm_op.cc, gru_op.cc,
sequence_pool_op.cc, sequence_softmax_op.cc, sequence_expand_op.cc,
sequence_conv_op.cc, row_conv_op.cc). The TPU-native design: ragged data is
(padded [n, maxlen, ...], lengths) — see core/lod.py — masked compute over
dense tiles keeps the MXU busy, and recurrences are jax.lax.scan so XLA
compiles one fused loop body instead of per-timestep kernel launches.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..core.lod import RaggedNested, RaggedPair, RaggedTree
from functools import partial

from ..core.registry import register_op

# Every op in this module consumes/produces RaggedPair values natively.
register_op_SEQ = partial(register_op, ragged_aware=True)


def _as_ragged(x) -> RaggedPair:
    if isinstance(x, RaggedPair):
        return x
    if isinstance(x, RaggedNested):
        raise ValueError(
            "this sequence op works on level-1 ragged input but got a "
            "2-level (nested) ragged value — reduce the token level first "
            "(sequence_pool / sequence_last_step) or flatten it with "
            "nested_sequence_flatten")
    # Dense [n, t, ...] with all lengths = t.
    lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
    return RaggedPair(x, lengths)


def _pool_padded(x: RaggedPair, ptype: str):
    """Pool the time axis of a level-1 ragged batch -> dense [n, *feat]."""
    data, lengths = x.data, x.lengths
    mask = x.mask()
    for _ in range(data.ndim - 2):
        mask = mask[..., None]
    maskf = mask.astype(data.dtype)
    if ptype == "SUM":
        out = jnp.sum(data * maskf, axis=1)
    elif ptype == "AVERAGE":
        denom = jnp.maximum(lengths, 1).astype(data.dtype)
        denom = denom.reshape((-1,) + (1,) * (data.ndim - 2))
        out = jnp.sum(data * maskf, axis=1) / denom
    elif ptype == "SQRT":
        denom = jnp.sqrt(jnp.maximum(lengths, 1).astype(data.dtype))
        denom = denom.reshape((-1,) + (1,) * (data.ndim - 2))
        out = jnp.sum(data * maskf, axis=1) / denom
    elif ptype == "MAX":
        neg = jnp.finfo(data.dtype).min
        out = jnp.max(jnp.where(mask, data, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = data[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return out


def _pool_nested(x: RaggedNested, ptype: str) -> RaggedPair:
    """Pool the innermost (token) level of a 2-level ragged batch; the
    result keeps the outer level (reference LoD semantics: pooling one
    level of a 2-level LoDTensor yields a 1-level LoDTensor)."""
    flat = x.flatten()
    out_flat = _pool_padded(flat, ptype)
    n, s = x.data.shape[:2]
    out = out_flat.reshape((n, s) + out_flat.shape[1:])
    return RaggedPair(out, x.sub_lengths)


@register_op_SEQ("sequence_pool")
def _sequence_pool(ctx):
    x = ctx.input("X")
    ptype = ctx.attr("pooltype", "AVERAGE").upper()
    if isinstance(x, RaggedNested):
        ctx.set_output("Out", _pool_nested(x, ptype))
        return
    ctx.set_output("Out", _pool_padded(_as_ragged(x), ptype))


@register_op_SEQ("nested_sequence_flatten")
def _nested_sequence_flatten(ctx):
    """Nested ragged -> one level shallower, over a batch of n*max_sub
    roots (padding slots have length 0). 2-level input yields a level-1
    ragged batch the RNN/sequence ops consume directly; a depth-k
    RaggedTree yields depth k-1 (apply repeatedly to peel an
    arbitrary-depth LoD — lod_tensor.h:55-107). The inner level of the
    reference's nested RecurrentGradientMachine loop becomes one masked
    batch."""
    x = ctx.input("X")
    if not isinstance(x, (RaggedNested, RaggedTree)):
        raise ValueError("nested_sequence_flatten needs a nested ragged "
                         "input (feed a LoDTensor with >= 2 LoD levels)")
    ctx.set_output("Out", x.flatten())


@register_op_SEQ("nested_sequence_pack", no_grad_slots=["Ref"])
def _nested_sequence_pack(ctx):
    """Dense per-sub-sequence rows [n*max_sub, *feat] (e.g. the inner
    encoder's last states) -> level-1 ragged [n, max_sub, *feat] with the
    outer lengths of Ref (2-level ragged or deeper RaggedTree). Inverse
    of nested_sequence_flatten after the inner levels are reduced away."""
    x = ctx.input("X")
    ref = ctx.input("Ref")
    if not isinstance(ref, (RaggedNested, RaggedTree)):
        raise ValueError("nested_sequence_pack needs a nested ragged Ref")
    if isinstance(x, (RaggedPair, RaggedNested, RaggedTree)):
        raise ValueError(
            "nested_sequence_pack expects DENSE per-sub-sequence rows "
            "[n*max_sub, *feat]; got a ragged value whose inner levels "
            "are still present — reduce them first (sequence_last_step / "
            "sequence_pool)")
    n, s = ref.data.shape[:2]
    outer = ref.sub_lengths if isinstance(ref, RaggedNested) \
        else ref.lengths[0]
    out = x.reshape((n, s) + x.shape[1:])
    ctx.set_output("Out", RaggedPair(out, outer))


@register_op_SEQ("sequence_softmax")
def _sequence_softmax(ctx):
    x = _as_ragged(ctx.input("X"))
    mask = x.mask()
    logits = jnp.where(mask, x.data.squeeze(-1) if x.data.ndim == 3
                       and x.data.shape[-1] == 1 else x.data, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=1)
    probs = jnp.where(mask, probs, 0.0)
    if x.data.ndim == 3 and x.data.shape[-1] == 1:
        probs = probs[..., None]
    ctx.set_output("Out", RaggedPair(probs, x.lengths))


@register_op_SEQ("sequence_expand", no_grad_slots=["Y"])
def _sequence_expand(ctx):
    """Repeat each row of X per the ragged structure of Y
    (reference: sequence_expand_op.cc, level-0 broadcast form)."""
    x = ctx.input("X")          # dense [n, ...]
    y = _as_ragged(ctx.input("Y"))
    xd = x.data if isinstance(x, RaggedPair) else x
    maxlen = y.data.shape[1]
    out = jnp.repeat(xd[:, None], maxlen, axis=1)
    ctx.set_output("Out", RaggedPair(out, y.lengths))


@register_op_SEQ("sequence_concat")
def _sequence_concat(ctx):
    xs = [_as_ragged(v) for v in ctx.inputs("X")]
    # Concatenate along the time axis, compacting each row's valid prefix.
    total_max = sum(x.data.shape[1] for x in xs)
    n = xs[0].data.shape[0]
    feat = xs[0].data.shape[2:]
    out = jnp.zeros((n, total_max) + feat, xs[0].data.dtype)
    lengths = sum((x.lengths for x in xs[1:]), xs[0].lengths)
    pos = jnp.zeros((n,), jnp.int32)
    t_idx = jnp.arange(total_max, dtype=jnp.int32)
    for x in xs:
        src_t = jnp.arange(x.data.shape[1], dtype=jnp.int32)
        # dest positions for this piece: pos[i] + t for t < len_i
        dest = pos[:, None] + src_t[None, :]
        valid = src_t[None, :] < x.lengths[:, None]
        onehot = (dest[:, :, None] == t_idx[None, None, :]) & valid[:, :, None]
        contrib = jnp.einsum("nst,ns...->nt...", onehot.astype(x.data.dtype),
                             x.data)
        out = out + contrib
        pos = pos + x.lengths
    ctx.set_output("Out", RaggedPair(out, lengths))


@register_op_SEQ("sequence_reshape")
def _sequence_reshape(ctx):
    x = _as_ragged(ctx.input("X"))
    new_dim = ctx.attr("new_dim")
    n, t = x.data.shape[:2]
    d = x.data.shape[2] if x.data.ndim > 2 else 1
    factor = (t * d) // new_dim if new_dim else t
    out = x.data.reshape(n, (t * d) // new_dim, new_dim)
    new_len = (x.lengths * d) // new_dim
    ctx.set_output("Out", RaggedPair(out, new_len))


@register_op_SEQ("sequence_slice", no_grad_slots=["Offset", "Length"])
def _sequence_slice(ctx):
    x = _as_ragged(ctx.input("X"))
    offset = ctx.input("Offset").reshape(-1).astype(jnp.int32)
    length = ctx.input("Length").reshape(-1).astype(jnp.int32)
    maxlen = x.data.shape[1]
    t = jnp.arange(maxlen, dtype=jnp.int32)
    src = offset[:, None] + t[None, :]
    src = jnp.minimum(src, maxlen - 1)
    out = jnp.take_along_axis(
        x.data, src.reshape(src.shape + (1,) * (x.data.ndim - 2)), axis=1)
    mask = (t[None, :] < length[:, None])
    maskx = mask.reshape(mask.shape + (1,) * (x.data.ndim - 2))
    ctx.set_output("Out", RaggedPair(out * maskx.astype(out.dtype), length))


@register_op_SEQ("sequence_erase", no_grad_slots=["X"])
def _sequence_erase(ctx):
    x = _as_ragged(ctx.input("X"))
    tokens = jnp.asarray(ctx.attr("tokens", []), jnp.int32)
    data = x.data
    keep = jnp.ones(data.shape[:2], bool)
    for tok in ctx.attr("tokens", []):
        keep &= (data.squeeze(-1) if data.ndim == 3 else data) != tok
    keep &= x.mask()
    # compact kept tokens to the left (stable)
    order = jnp.argsort(~keep, axis=1, stable=True)
    gathered = jnp.take_along_axis(
        data, order.reshape(order.shape + (1,) * (data.ndim - 2)), axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    t = jnp.arange(data.shape[1], dtype=jnp.int32)
    mask = (t[None, :] < new_len[:, None])
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    ctx.set_output("Out", RaggedPair(gathered * mask.astype(data.dtype),
                                     new_len))


@register_op_SEQ("sequence_conv")
def _sequence_conv(ctx):
    """Context-window projection over each sequence
    (reference: sequence_conv_op.cc / ContextProjection function)."""
    x = _as_ragged(ctx.input("X"))
    w = ctx.input("Filter")  # [ctx_len * d, out_d]
    ctx_len = ctx.attr("contextLength", 3)
    ctx_start = ctx.attr("contextStart", -(ctx_len // 2))
    data = x.data  # [n, t, d]
    n, t, d = data.shape
    cols = []
    for i in range(ctx_len):
        shift = ctx_start + i
        rolled = jnp.roll(data, -shift, axis=1)
        tt = jnp.arange(t)
        valid = (tt + shift >= 0) & (tt + shift < t)
        cols.append(jnp.where(valid[None, :, None], rolled, 0.0))
    ctxmat = jnp.concatenate(cols, axis=-1)  # [n, t, ctx_len*d]
    out = jnp.einsum("ntc,co->nto", ctxmat, w)
    mask = x.mask()[..., None].astype(out.dtype)
    ctx.set_output("Out", RaggedPair(out * mask, x.lengths))


@register_op_SEQ("row_conv")
def _row_conv(ctx):
    x = _as_ragged(ctx.input("X"))
    w = ctx.input("Filter")  # [future_ctx, d]
    data = x.data
    k = w.shape[0]
    outs = jnp.zeros_like(data)
    t = data.shape[1]
    for i in range(k):
        rolled = jnp.roll(data, -i, axis=1)
        tt = jnp.arange(t)
        valid = (tt + i < t)
        outs = outs + jnp.where(valid[None, :, None], rolled, 0.0) * w[i][None,
                                                                         None]
    mask = x.mask()[..., None].astype(data.dtype)
    ctx.set_output("Out", RaggedPair(outs * mask, x.lengths))


# -- recurrent nets ---------------------------------------------------------

def _rnn_unroll():
    """Scan unroll factor, read at trace time (PADDLE_TPU_RNN_UNROLL,
    1 disables). Unrolling amortizes loop overhead across the small
    per-step recurrent matmuls; A/B on real TPU: unroll=4 ~ +30%
    tokens/s on the LSTM-LM bench (unroll=8 regressed)."""
    raw = os.environ.get("PADDLE_TPU_RNN_UNROLL", "4")
    try:
        return max(int(raw), 1)
    except ValueError:
        if raw.strip().lower() in ("off", "false", "no", "none",
                                   "disabled", ""):
            return 1
        raise ValueError(
            f"PADDLE_TPU_RNN_UNROLL={raw!r}: expected an integer or a "
            "disable word (off/false/no/none/disabled)")


def _masked_scan_rnn(step, xs, init_states, lengths):
    """Run `step` over time axis 1 of xs, freezing state past each row's
    length. step(carry, x_t) -> (carry, out_t); carry is a tuple."""
    maxlen = xs.shape[1]
    tpos = jnp.arange(maxlen, dtype=jnp.int32)

    def body(carry, inp):
        t, x_t = inp
        new_carry, out_t = step(carry, x_t)
        is_tuple = isinstance(out_t, tuple)
        outs = out_t if is_tuple else (out_t,)
        alive0 = (t < lengths)

        def mask(o):
            a = alive0.reshape((-1,) + (1,) * (o.ndim - 1))
            return o * a.astype(o.dtype)

        sel = lambda n, o: jnp.where(
            alive0.reshape((-1,) + (1,) * (n.ndim - 1)), n, o)
        carry = tuple(sel(n, o) for n, o in zip(new_carry, carry))
        masked = tuple(mask(o) for o in outs)
        return carry, (masked if is_tuple else masked[0])

    xs_t = jnp.moveaxis(xs, 1, 0)  # [t, n, ...]
    carry, outs = jax.lax.scan(body, init_states, (tpos, xs_t),
                               unroll=_rnn_unroll())
    if isinstance(outs, tuple):
        return carry, tuple(jnp.moveaxis(o, 0, 1) for o in outs)
    return carry, jnp.moveaxis(outs, 0, 1)


_ACT = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": jax.nn.relu,
        "identity": lambda x: x}


@register_op_SEQ("lstm")
def _lstm(ctx):
    """Dynamic LSTM over ragged input (reference: lstm_op.cc).

    Input: ragged [n, t, 4h] (already projected by a mul op, as in the
    reference), Weight [h, 4h] recurrent weights, Bias [1, 4h] (+ peephole
    terms unsupported). Gate order i, c, f, o matches the reference
    (operators/math/detail/lstm_kernel.h usage in lstm_op).
    """
    x = _as_ragged(ctx.input("Input"))
    w = ctx.input("Weight")
    b = ctx.input("Bias")
    h_dim = w.shape[0]
    n = x.data.shape[0]
    gate_act = _ACT[ctx.attr("gate_activation", "sigmoid")]
    cell_act = _ACT[ctx.attr("cell_activation", "tanh")]
    cand_act = _ACT[ctx.attr("candidate_activation", "tanh")]
    is_reverse = ctx.attr("is_reverse", False)

    data = x.data
    if is_reverse:
        # reverse each sequence's valid prefix
        t = data.shape[1]
        idx = (x.lengths[:, None] - 1 - jnp.arange(t)[None, :]) % t
        data = jnp.take_along_axis(data, idx[..., None], axis=1)

    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    h0 = h0 if h0 is not None else jnp.zeros((n, h_dim), data.dtype)
    c0 = c0 if c0 is not None else jnp.zeros((n, h_dim), data.dtype)

    # Peephole weights: reference packs them in Bias as [1, 7h] when
    # use_peepholes (lstm_op.cc: W_ic, W_fc, W_oc after the 4h gate bias).
    use_peepholes = ctx.attr("use_peepholes", False) and b is not None \
        and b.reshape(-1).shape[0] >= 7 * h_dim
    if use_peepholes:
        bflat = b.reshape(-1)
        w_ic = bflat[4 * h_dim:5 * h_dim].reshape(1, -1)
        w_fc = bflat[5 * h_dim:6 * h_dim].reshape(1, -1)
        w_oc = bflat[6 * h_dim:7 * h_dim].reshape(1, -1)

    # Hot path: the Pallas fused kernel keeps (h, c) in VMEM across all
    # timesteps (the reference's hl_cuda_lstm.cu analog) — ~13% faster
    # fwd+bwd than the unrolled scan on chip. Standard gates only;
    # PADDLE_TPU_PALLAS_LSTM=0 disables.
    from .pallas import pallas_dispatch
    enabled, interp = pallas_dispatch("PADDLE_TPU_PALLAS_LSTM", "1",
                                      attr=ctx.attr("__pallas__"))
    eligible = (
        not use_peepholes
        and ctx.attr("gate_activation", "sigmoid") == "sigmoid"
        and ctx.attr("cell_activation", "tanh") == "tanh"
        and ctx.attr("candidate_activation", "tanh") == "tanh")
    if enabled and eligible:
        from .pallas.fused_lstm import fused_lstm
        bias = b.reshape(-1)[:4 * h_dim] if b is not None else \
            jnp.zeros((4 * h_dim,), data.dtype)
        h_tm, c_tm, h_last, c_last = fused_lstm(
            jnp.moveaxis(data, 1, 0), w, bias, h0, c0, x.lengths, interp)
        hidden = jnp.moveaxis(h_tm, 0, 1)
        cells = jnp.moveaxis(c_tm, 0, 1)
    else:
        def step(carry, x_t):
            h_prev, c_prev = carry
            gates = x_t + h_prev @ w
            if b is not None:
                gates = gates + b.reshape(1, -1)[:, :4 * h_dim]
            i, c_hat, f, o = jnp.split(gates, 4, axis=-1)
            if use_peepholes:
                i = i + w_ic * c_prev
                f = f + w_fc * c_prev
            i = gate_act(i)
            f = gate_act(f)
            c = f * c_prev + i * cand_act(c_hat)
            if use_peepholes:
                o = o + w_oc * c
            o = gate_act(o)
            h = o * cell_act(c)
            return (h, c), (h, c)

        (h_last, c_last), (hidden, cells) = _masked_scan_rnn(
            step, data, (h0, c0), x.lengths)
    if is_reverse:
        t = hidden.shape[1]
        idx = (x.lengths[:, None] - 1 - jnp.arange(t)[None, :]) % t
        hidden = jnp.take_along_axis(hidden, idx[..., None], axis=1)
        cells = jnp.take_along_axis(cells, idx[..., None], axis=1)
    ctx.set_output("Hidden", RaggedPair(hidden, x.lengths))
    ctx.set_output("Cell", RaggedPair(cells, x.lengths))
    ctx.set_output("LastH", h_last)
    ctx.set_output("LastC", c_last)


@register_op_SEQ("gru")
def _gru(ctx):
    """Dynamic GRU over ragged input (reference: gru_op.cc).
    Input ragged [n, t, 3h] pre-projected; Weight packs [h, 2h] update/reset
    and [h, h] candidate, as in the reference layout."""
    x = _as_ragged(ctx.input("Input"))
    w = ctx.input("Weight")  # [h, 3h]
    b = ctx.input("Bias")
    h_dim = w.shape[0]
    n = x.data.shape[0]
    gate_act = _ACT[ctx.attr("gate_activation", "sigmoid")]
    cand_act = _ACT[ctx.attr("activation", "tanh")]
    w_ur = w[:, :2 * h_dim]
    w_c = w[:, 2 * h_dim:]

    h0 = ctx.input("H0")
    h0 = h0 if h0 is not None else jnp.zeros((n, h_dim), x.data.dtype)

    data = x.data
    is_reverse = ctx.attr("is_reverse", False)
    if is_reverse:
        # reverse each sequence's valid prefix (as the lstm op does)
        t = data.shape[1]
        ridx = (x.lengths[:, None] - 1 - jnp.arange(t)[None, :]) % t
        data = jnp.take_along_axis(data, ridx[..., None], axis=1)

    # default ON: measured ~1.8x over the scan path on v5e (20-layer
    # stacked GRU, b64 t100 h512, marginal-cost protocol, 2 runs each)
    from .pallas import pallas_dispatch
    enabled, interp = pallas_dispatch("PADDLE_TPU_PALLAS_GRU", "1",
                                      attr=ctx.attr("__pallas__"))
    eligible = (ctx.attr("gate_activation", "sigmoid") == "sigmoid"
                and ctx.attr("activation", "tanh") == "tanh")
    if enabled and eligible:
        from .pallas.fused_gru import fused_gru
        gdata = data if b is None else data + b.reshape(1, 1, -1)
        h_tm, h_last = fused_gru(
            jnp.moveaxis(gdata, 1, 0), w, h0, x.lengths, interp)
        hidden = jnp.moveaxis(h_tm, 0, 1)
    else:
        def step(carry, x_t):
            (h_prev,) = carry
            if b is not None:
                x_t = x_t + b.reshape(1, -1)
            xu, xr, xc = jnp.split(x_t, 3, axis=-1)
            ur = h_prev @ w_ur
            hu, hr = jnp.split(ur, 2, axis=-1)
            u = gate_act(xu + hu)
            r = gate_act(xr + hr)
            c = cand_act(xc + (r * h_prev) @ w_c)
            h = u * h_prev + (1 - u) * c
            return (h,), h

        (h_last,), hidden = _masked_scan_rnn(step, data, (h0,),
                                             x.lengths)
    if is_reverse:
        t = hidden.shape[1]
        ridx = (x.lengths[:, None] - 1 - jnp.arange(t)[None, :]) % t
        hidden = jnp.take_along_axis(hidden, ridx[..., None], axis=1)
    ctx.set_output("Hidden", RaggedPair(hidden, x.lengths))
    ctx.set_output("LastH", h_last)


@register_op_SEQ("sequence_mask", no_grad_slots=["X"])
def _sequence_mask(ctx):
    lengths = ctx.input("X").reshape(-1)
    maxlen = ctx.attr("maxlen", -1)
    if maxlen <= 0:
        raise ValueError("sequence_mask on TPU needs a static maxlen attr")
    pos = jnp.arange(maxlen, dtype=lengths.dtype)
    ctx.set_output("Y", (pos[None, :] < lengths[:, None]).astype(jnp.float32))


@register_op_SEQ("sequence_pad")
def _sequence_pad(ctx):
    x = _as_ragged(ctx.input("X"))
    ctx.set_output("Out", x.data)
    ctx.set_output("Length", x.lengths.astype(jnp.int64))


@register_op_SEQ("sequence_unpad", no_grad_slots=["Length"])
def _sequence_unpad(ctx):
    x = ctx.input("X")
    lengths = ctx.input("Length").reshape(-1).astype(jnp.int32)
    ctx.set_output("Out", RaggedPair(x, lengths))


@register_op_SEQ("sequence_last_step")
def _sequence_last_step(ctx):
    x = ctx.input("X")
    if isinstance(x, RaggedNested):
        ctx.set_output("Out", _pool_nested(x, "LAST"))
        return
    ctx.set_output("Out", _pool_padded(_as_ragged(x), "LAST"))


@register_op_SEQ("sequence_first_step")
def _sequence_first_step(ctx):
    x = ctx.input("X")
    if isinstance(x, RaggedNested):
        ctx.set_output("Out", _pool_nested(x, "FIRST"))
        return
    ctx.set_output("Out", _pool_padded(_as_ragged(x), "FIRST"))


# -- CTC (reference: warpctc_op.cc wraps the warp-ctc CUDA lib;
# ctc_align_op.cc / Python ctc_greedy_decoder) ------------------------------

NEG_INF = -1e30


def _ctc_loss_single_batch(logits, logit_lens, labels, label_lens, blank):
    """CTC negative log-likelihood via the standard alpha recursion in log
    space, vectorized over the batch and scanned over time — one fused XLA
    loop instead of the reference's per-sample CUDA kernels.

    logits: [B, T, C] raw (pre-softmax); labels: int32 [B, L] padded.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    B, T, C = logp.shape
    L = labels.shape[1]
    U = 2 * L + 1

    # Extended label sequence with interleaved blanks: [B, U]
    ext = jnp.full((B, U), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    # allow the s-2 skip where ext[s] is a real label != ext[s-2]
    skip_ok = jnp.zeros((B, U), bool)
    skip_ok = skip_ok.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    # states beyond 2*label_len are invalid
    spos = jnp.arange(U)[None, :]
    state_valid = spos <= 2 * label_lens[:, None]

    emit0 = jnp.take_along_axis(logp[:, 0], ext, axis=1)  # [B, U]
    alpha0 = jnp.where((spos <= 1) & state_valid, emit0, NEG_INF)

    def step(alpha, t):
        emit = jnp.take_along_axis(logp[:, t], ext, axis=1)
        stay = alpha
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), NEG_INF), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), NEG_INF), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(skip_ok, prev2, NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2) + emit
        merged = jnp.where(state_valid, merged, NEG_INF)
        # frozen past each sequence's end: carry alpha unchanged
        alive = (t < logit_lens)[:, None]
        return jnp.where(alive, merged, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = 2 * label_lens          # final blank state
    end2 = jnp.maximum(2 * label_lens - 1, 0)  # final label state
    a1 = jnp.take_along_axis(alpha, end1[:, None], axis=1)[:, 0]
    a2 = jnp.take_along_axis(alpha, end2[:, None], axis=1)[:, 0]
    a2 = jnp.where(label_lens > 0, a2, NEG_INF)
    return -jnp.logaddexp(a1, a2)


@register_op_SEQ("warpctc", no_grad_slots=["Label"])
def _warpctc(ctx):
    """CTC loss over ragged logits/labels (reference: warpctc_op.cc).
    Gradients flow through the scan via autodiff — exact, unlike the
    reference's hand-written backward."""
    logits = _as_ragged(ctx.input("Logits"))
    label = _as_ragged(ctx.input("Label"))
    blank = ctx.attr("blank", 0)
    norm_by_times = ctx.attr("norm_by_times", False)
    lab = label.data
    if lab.ndim == 3 and lab.shape[-1] == 1:
        lab = lab[..., 0]
    nll = _ctc_loss_single_batch(logits.data, logits.lengths, lab,
                                 label.lengths, blank)
    if norm_by_times:
        nll = nll / jnp.maximum(logits.lengths, 1).astype(nll.dtype)
    ctx.set_output("Loss", nll[:, None].astype(logits.data.dtype))


@register_op_SEQ("ctc_greedy_decoder", no_grad_slots=["Input"])
def _ctc_greedy_decoder(ctx):
    """Best-path decode: argmax per frame, merge repeats, drop blanks
    (reference: Python ctc_greedy_decoder + ctc_align_op.cc). Static-shape
    compaction via cumsum positions + scatter."""
    x = _as_ragged(ctx.input("Input"))  # [B, T, C] probs or logits
    blank = ctx.attr("blank", 0)
    best = jnp.argmax(x.data, axis=-1).astype(jnp.int32)   # [B, T]
    B, T = best.shape
    mask = x.mask()
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32),
                            best[:, :-1]], axis=1)
    keep = (best != blank) & (best != prev) & mask
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1   # target slot
    out_lens = keep.astype(jnp.int32).sum(axis=1)
    # scatter kept tokens into a [B, T] buffer (padded with zeros)
    buf = jnp.zeros((B, T + 1), jnp.int32)
    scatter_pos = jnp.where(keep, pos, T)                  # T = trash slot
    buf = buf.at[jnp.arange(B)[:, None], scatter_pos].set(best)
    ctx.set_output("Out", RaggedPair(buf[:, :T, None], out_lens))


# -- single-step RNN cells (reference: lstm_unit_op.cc, gru_unit_op.cc,
# lstmp_op.cc) --------------------------------------------------------------

@register_op("lstm_unit")
def _lstm_unit(ctx):
    """One LSTM step on pre-projected gates (reference: lstm_unit_op.cc).
    X: [n, 4d] packed i,f,o,g? — the reference packs i, g(c_hat), f, o as
    in lstm_op; C_prev: [n, d]. forget_bias added to f pre-sigmoid."""
    x = ctx.input("X")
    c_prev = ctx.input("C_prev")
    fb = ctx.attr("forget_bias", 0.0)
    d = c_prev.shape[-1]
    i, g, f, o = (x[:, :d], x[:, d:2 * d], x[:, 2 * d:3 * d], x[:, 3 * d:])
    c = jax.nn.sigmoid(f + fb) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    ctx.set_output("C", c)
    ctx.set_output("H", h)


@register_op("gru_unit")
def _gru_unit(ctx):
    """One GRU step (reference: gru_unit_op.cc). Input: [n, 3d] projected
    x contributions; HiddenPrev [n, d]; Weight [d, 3d]; Bias [1, 3d]."""
    x = ctx.input("Input")
    h_prev = ctx.input("HiddenPrev")
    w = ctx.input("Weight")
    b = ctx.input("Bias")
    d = h_prev.shape[-1]
    if b is not None:
        x = x + b.reshape(1, -1)
    xu, xr, xc = x[:, :d], x[:, d:2 * d], x[:, 2 * d:]
    hu = h_prev @ w[:, :d]
    hr = h_prev @ w[:, d:2 * d]
    u = jax.nn.sigmoid(xu + hu)
    r = jax.nn.sigmoid(xr + hr)
    c = jnp.tanh(xc + (r * h_prev) @ w[:, 2 * d:])
    h = u * h_prev + (1.0 - u) * c
    ctx.set_output("Gate", jnp.concatenate([u, r, c], axis=-1))
    ctx.set_output("ResetHiddenPrev", r * h_prev)
    ctx.set_output("Hidden", h)


@register_op_SEQ("lstmp")
def _lstmp(ctx):
    """LSTM with recurrent projection (reference: lstmp_op.cc): cell size
    d, projected hidden size p; recurrence runs on the projection."""
    x = _as_ragged(ctx.input("Input"))       # [n, t, 4d] pre-projected
    w = ctx.input("Weight")                  # [p, 4d]
    w_proj = ctx.input("ProjWeight")         # [d, p]
    b = ctx.input("Bias")
    d = w_proj.shape[0]
    p = w_proj.shape[1]
    n = x.data.shape[0]
    gate_act = _ACT[ctx.attr("gate_activation", "sigmoid")]
    cell_act = _ACT[ctx.attr("cell_activation", "tanh")]
    cand_act = _ACT[ctx.attr("candidate_activation", "tanh")]
    proj_act = _ACT[ctx.attr("proj_activation", "tanh")]

    h0 = ctx.input("H0")
    c0 = ctx.input("C0")
    r0 = jnp.zeros((n, p), x.data.dtype) if h0 is None else h0 @ w_proj \
        if h0.shape[-1] == d else h0
    c0 = c0 if c0 is not None else jnp.zeros((n, d), x.data.dtype)

    use_peepholes = ctx.attr("use_peepholes", False) and b is not None \
        and b.reshape(-1).shape[0] >= 7 * d
    if use_peepholes:
        bflat = b.reshape(-1)
        w_ic = bflat[4 * d:5 * d].reshape(1, -1)
        w_fc = bflat[5 * d:6 * d].reshape(1, -1)
        w_oc = bflat[6 * d:7 * d].reshape(1, -1)

    def step(carry, x_t):
        r_prev, c_prev = carry
        gates = x_t + r_prev @ w
        if b is not None:
            gates = gates + b.reshape(1, -1)[:, :4 * d]
        i, c_hat, f, o = jnp.split(gates, 4, axis=-1)
        if use_peepholes:
            i = i + w_ic * c_prev
            f = f + w_fc * c_prev
        c = gate_act(f) * c_prev + gate_act(i) * cand_act(c_hat)
        if use_peepholes:
            o = o + w_oc * c
        h = gate_act(o) * cell_act(c)
        r = proj_act(h @ w_proj)
        return (r, c), (r, c)

    (r_last, c_last), (proj, cells) = _masked_scan_rnn(
        step, x.data, (r0, c0), x.lengths)
    ctx.set_output("Projection", RaggedPair(proj, x.lengths))
    ctx.set_output("Cell", RaggedPair(cells, x.lengths))
    ctx.set_output("LastH", r_last)
    ctx.set_output("LastC", c_last)


@register_op_SEQ("ctc_align", no_grad_slots=["Input"])
def _ctc_align(ctx):
    """Merge repeated tokens (optional) then drop blanks (reference:
    ctc_align_op.cc). Static-shape compaction as in ctc_greedy_decoder."""
    x = _as_ragged(ctx.input("Input"))      # [B, T, 1] or [B, T] token ids
    blank = ctx.attr("blank", 0)
    merge = ctx.attr("merge_repeated", True)
    ids = x.data
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    ids = ids.astype(jnp.int32)
    B, T = ids.shape
    mask = x.mask()
    keep = (ids != blank) & mask
    if merge:
        prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32),
                                ids[:, :-1]], axis=1)
        keep = keep & (ids != prev)
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out_lens = keep.astype(jnp.int32).sum(axis=1)
    buf = jnp.zeros((B, T + 1), jnp.int32)
    scatter_pos = jnp.where(keep, pos, T)
    buf = buf.at[jnp.arange(B)[:, None], scatter_pos].set(ids)
    ctx.set_output("Output", RaggedPair(buf[:, :T, None], out_lens))


@register_op_SEQ("sequence_reverse")
def _sequence_reverse(ctx):
    """Reverse each sequence's valid prefix, padding stays in place
    (reference: sequence_reverse_op.h). Powers reverse=True recurrences
    built on the masked-scan DynamicRNN."""
    x = _as_ragged(ctx.input("X"))
    t = jnp.arange(x.data.shape[1], dtype=jnp.int32)
    lens = x.lengths.astype(jnp.int32)
    src = jnp.where(t[None, :] < lens[:, None],
                    lens[:, None] - 1 - t[None, :], t[None, :])
    out = jnp.take_along_axis(
        x.data, src.reshape(src.shape + (1,) * (x.data.ndim - 2)),
        axis=1)
    ctx.set_output("Y", RaggedPair(out, x.lengths))


@register_op_SEQ("multihead_seq_attention")
def _multihead_seq_attention(ctx):
    """Multi-head self/cross attention over RAGGED sequences (the v2
    networks.multi_head_attention composition, reference:
    trainer_config_helpers/networks.py:1580 — realized as one fused
    ragged op so padding is masked exactly; the modern dense-tensor
    path is ops 'scaled_dot_product_attention')."""
    q = _as_ragged(ctx.input("Q"))
    k = _as_ragged(ctx.input("K"))
    v = _as_ragged(ctx.input("V"))
    wq, wk = ctx.input("WQ"), ctx.input("WK")
    wv, wo = ctx.input("WV"), ctx.input("WO")
    heads = ctx.attr("num_heads", 1)
    qp = jnp.einsum("btd,de->bte", q.data, wq)
    kp = jnp.einsum("btd,de->bte", k.data, wk)
    vp = jnp.einsum("btd,de->bte", v.data, wv)
    b, t, d = qp.shape
    dh = d // heads

    def split(x):
        return x.reshape(b, x.shape[1], heads, dh).transpose(0, 2, 1, 3)

    qs, ks, vs = split(qp), split(kp), split(vp)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qs, ks) / jnp.sqrt(
        jnp.asarray(dh, qp.dtype))
    scores = jnp.where(k.mask()[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vs) \
        .transpose(0, 2, 1, 3).reshape(b, t, d)
    out = jnp.einsum("btd,de->bte", out, wo)
    out = out * q.mask()[..., None].astype(out.dtype)
    ctx.set_output("Out", RaggedPair(out, q.lengths))
