"""KV-cache update ops for incremental decode (serving/generation).

The cache is persistable scope state shaped [slots, heads, max_seq, d]:
an op here reads the cache var and writes its output back to the SAME
var name, which makes the executor classify it read-write state and
donate it to the jitted step (core/executor.py donate_argnums) — the
update is an in-place XLA dynamic-update-slice, not a copy of the whole
cache per token. This is exactly the optimizer-op ParamOut contract;
the serving engine never fetches the cache, so donation is safe even
under sync dispatch.

Both rules are pure differentiable JAX, but generation never runs a
backward pass — the index slots are marked no-grad so an accidental
minimize() over a decode graph fails on the float paths only.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _cache_passthrough_infer(block_desc, op):
    """Out mirrors the Cache operand: both ops are in-place
    dynamic-update-slices, so shape/dtype pass straight through. The
    generic abstract trace cannot run them (integer index operands have
    no declared feed values at build time); without this rule the
    memory planner would see a shape-coverage gap exactly on the
    cache-resident buffers it most needs to count."""
    names = op.input("Cache")
    outs = op.output("Out")
    if not names or not outs:
        return {}
    v = block_desc.find_var_recursive(names[0])
    if v is None or v.shape is None:
        return {}
    return {outs[0]: {"shape": list(v.shape), "dtype": v.dtype,
                      "lod_level": 0}}


@register_op("kv_cache_write", no_grad_slots=["Slot"],
             infer_shape=_cache_passthrough_infer)
def _kv_cache_write(ctx):
    """Prefill path: write one request's full-prompt K or V rows into
    its cache slot.

    Cache: [slots, h, max_seq, d]; New: [1, h, S, d] (S <= max_seq);
    Slot: [1] int — the in-flight batch slot index. Rows [0, S) of the
    slot are overwritten; rows beyond S keep whatever the previous
    occupant left (masked out by the decode-step attention mask).
    """
    cache = ctx.input("Cache")
    new = ctx.input("New").astype(cache.dtype)
    slot = ctx.input("Slot").reshape(()).astype(jnp.int32)
    ctx.set_output("Out", jax.lax.dynamic_update_slice(
        cache, new, (slot, 0, 0, 0)))


@register_op("kv_cache_append", no_grad_slots=["Pos"],
             infer_shape=_cache_passthrough_infer)
def _kv_cache_append(ctx):
    """Decode path: append one token's K or V row per slot, at each
    slot's own position.

    Cache: [slots, h, max_seq, d]; New: [slots, h, 1, d]; Pos: [slots]
    int — per-slot write position. Inactive slots point Pos at 0; the
    garbage row is overwritten by that slot's next prefill and is never
    attended to meanwhile (the additive mask covers only live rows).
    """
    cache = ctx.input("Cache")
    new = ctx.input("New").astype(cache.dtype)
    pos = ctx.input("Pos").astype(jnp.int32)
    ctx.set_output("Out", jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0)))(
            cache, new, pos))
