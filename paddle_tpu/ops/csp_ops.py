"""In-graph CSP channel ops (reference: channel_create/send/recv/close
ops, paddle/fluid/operators/channel_*.cc + framework/channel.h:33, used
by go/select programs).

TPU-native form: device programs are pure, so channel STATE lives on the
host (the same `concurrency.Channel` objects the Python API uses); the
in-graph ops bridge to it with `jax.experimental.io_callback(ordered=True)`
so sends/recvs keep program order inside one executed program and
interoperate with host-side `go()` producers/consumers. Gradients do not
flow through channels (the reference's channel ops are not differentiable
either); recv needs a static shape/dtype attr, XLA's static-shape regime.

Deadlock note: a recv on an empty channel BLOCKS the executed program
(as the reference's ChannelReceive blocks its thread); pair in-graph
recvs with host-side `go()` senders or buffered channels, and set
`timeout` to fail fast instead.
"""
from __future__ import annotations

import functools
import threading
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..concurrency import Channel, ChannelClosed
from ..core.registry import register_op
from .core_ops import jnp_dtype

# host channel registry: id -> Channel (in-graph ops reference channels
# by integer id carried as a scalar tensor). Unregistered ids leave a
# tombstone so a late op on a swept channel still reads as "closed"
# rather than "never existed"; ids are monotonic so tombstones are just
# "allocated but absent".
_channels: Dict[int, Channel] = {}
_lock = threading.Lock()
_next_id = [1]


def register_channel(ch: Channel) -> int:
    """Expose an existing host Channel to in-graph ops; returns its id."""
    with _lock:
        cid = _next_id[0]
        _next_id[0] += 1
        _channels[cid] = ch
    return cid


def get_channel(cid: int) -> Channel:
    cid = int(cid)
    with _lock:
        ch = _channels.get(cid)
        allocated = 0 < cid < _next_id[0]
    if ch is None:
        if allocated:
            raise ChannelClosed(
                f"channel id {cid} was closed and drained (its host "
                "object has been released)")
        raise KeyError(f"unknown channel id {cid} (create it with "
                       "channel_create or register_channel)")
    return ch


def _unregister(cid: int):
    with _lock:
        _channels.pop(int(cid), None)


def _gc_dead_channels():
    """Drop closed, drained channels from the registry. channel_create
    runs its callback on every program execution, so without this sweep
    a program that closes with buffered items nobody drains would grow
    the registry by one Channel per run. (A channel that is never closed
    at all stays registered — the host cannot see device-side id refs,
    so close is the lifetime signal, as in the reference's
    channel_close_op.)"""
    with _lock:
        dead = [cid for cid, ch in _channels.items()
                if ch.closed and ch.drained()]
        for cid in dead:
            _channels.pop(cid, None)


def _host_create(capacity):
    _gc_dead_channels()
    return np.int32(register_channel(Channel(int(capacity))))


def _host_send(cid, value, *, timeout):
    ch = get_channel(int(cid))
    t = float(timeout)
    ok = ch.send(np.asarray(value), timeout=None if t < 0 else t)
    if not ok:
        raise TimeoutError(f"channel_send timed out after {t}s")
    return np.int32(1)


def _host_recv(cid, *, timeout, shape, dtype):
    ch = get_channel(int(cid))
    t = float(timeout)
    value, ok = ch.recv(timeout=None if t < 0 else t)
    if not ok:
        if ch.closed:
            # closed AND drained: this channel can never produce again —
            # drop it from the registry so looped programs don't leak
            _unregister(cid)
            raise ChannelClosed("channel_recv on a closed, drained "
                                "channel")
        raise TimeoutError(f"channel_recv timed out after {t}s")
    arr = np.asarray(value).astype(dtype, copy=False)
    if arr.shape != shape:
        raise ValueError(f"channel_recv expected shape {shape}, got "
                         f"{arr.shape}")
    return arr


def _host_close(cid):
    ch = get_channel(int(cid))
    ch.close()
    # unregister once nothing is left to drain (a close with buffered
    # items keeps the id alive until a recv drains it)
    if ch.drained():
        _unregister(cid)
    return np.int32(1)


@register_op("channel_create", stateful=True)
def _channel_create(ctx):
    capacity = int(ctx.attr("capacity", 0))
    if capacity < 1:
        # an unbuffered in-graph channel deadlocks by construction:
        # ordered callbacks serialize, so a blocking rendezvous send can
        # never meet its receiver within one program. Host-side
        # unbuffered channels still work via register_channel + go().
        raise ValueError(
            "in-graph channel_create needs capacity >= 1 (unbuffered "
            "rendezvous cannot complete inside one ordered program); "
            "for unbuffered host channels use concurrency.Channel + "
            "ops.csp_ops.register_channel")
    cid = jax.experimental.io_callback(
        functools.partial(_host_create, capacity),
        jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    ctx.set_output("Out", cid)


@register_op("channel_send", stateful=True, no_grad_slots=["Channel", "X"])
def _channel_send(ctx):
    cid = ctx.input("Channel")
    x = ctx.input("X")
    timeout = float(ctx.attr("timeout", -1.0))
    status = jax.experimental.io_callback(
        functools.partial(_host_send, timeout=timeout),
        jax.ShapeDtypeStruct((), jnp.int32), cid, x, ordered=True)
    ctx.set_output("Status", status)


@register_op("channel_recv", stateful=True, no_grad_slots=["Channel"])
def _channel_recv(ctx):
    cid = ctx.input("Channel")
    shape = tuple(int(d) for d in ctx.attr("shape"))
    if any(d < 0 for d in shape):
        raise ValueError(
            f"channel_recv needs a fully static shape (got {shape}); "
            "the batch dim cannot be -1 under XLA")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    timeout = float(ctx.attr("timeout", -1.0))
    out = jax.experimental.io_callback(
        functools.partial(_host_recv, timeout=timeout, shape=shape,
                          dtype=np.dtype(dtype).name),
        jax.ShapeDtypeStruct(shape, dtype), cid, ordered=True)
    ctx.set_output("Out", out)


@register_op("channel_close", stateful=True, no_grad_slots=["Channel"])
def _channel_close(ctx):
    cid = ctx.input("Channel")
    status = jax.experimental.io_callback(
        _host_close, jax.ShapeDtypeStruct((), jnp.int32), cid,
        ordered=True)
    ctx.set_output("Status", status)


def _host_select(cids, *send_vals, kinds, timeout, recv_specs):
    """Host arbitration for the in-graph select (reference:
    select_op.cc — pick one ready case, Go semantics). Blocks until a
    case fires (timeout < 0) or raises TimeoutError. Returns the fired
    case index plus one buffer per recv case (zeros for cases that did
    not fire)."""
    import time as _time
    from ..concurrency import select as host_select

    cids = np.asarray(cids).reshape(-1)
    send_vals = list(send_vals)
    recv_out = [np.zeros(shape, dtype) for shape, dtype in recv_specs]
    recv_slot = {}   # case index -> recv buffer position
    for i, kind in enumerate(kinds):
        if kind == "recv":
            recv_slot[i] = len(recv_slot)
    fired_value = {}
    fired_ok = {}    # case index -> did the recv deliver a real value?
    si = 0

    def make_recv_cb(i):
        def cb(v, ok):
            fired_ok[i] = bool(ok)
            if ok:
                fired_value[i] = np.asarray(v)
        return cb

    cases = []
    for i, kind in enumerate(kinds):
        ch = get_channel(int(cids[i]))
        if kind == "recv":
            cases.append(("recv", ch, make_recv_cb(i)))
        else:
            cases.append(("send", ch, (np.asarray(send_vals[si]), None)))
            si += 1

    t = float(timeout)
    if t < 0:
        idx = host_select(cases)          # block until one case fires
    else:
        deadline = _time.monotonic() + t
        sentinel = []
        while True:
            idx = host_select(cases, default=lambda: sentinel.append(1))
            if idx >= 0:
                break
            if _time.monotonic() >= deadline:
                raise TimeoutError(f"select timed out after {t}s")
            _time.sleep(0.001)

    if idx in recv_slot and idx in fired_value:
        buf = fired_value[idx]
        slot = recv_slot[idx]
        want = recv_out[slot]
        recv_out[slot] = buf.astype(want.dtype, copy=False).reshape(
            want.shape)
    # ok flag per recv case: 1 iff that case fired AND delivered a real
    # value — a recv that fired because its channel CLOSED reads 0, so
    # callers can tell a genuine zero value from a closed-channel zero
    # (the reference select / host concurrency.select expose the same ok)
    ok_vec = np.zeros(len(recv_slot), np.int32)
    if idx in recv_slot:
        ok_vec[recv_slot[idx]] = np.int32(1 if fired_ok.get(idx) else 0)
    if recv_slot:
        return (np.int32(idx), ok_vec) + tuple(recv_out)
    return (np.int32(idx),)


@register_op("select", stateful=True,
             no_grad_slots=["Channels", "SendX"])
def _select(ctx):
    """In-graph multi-way select over channels (reference:
    select_op.cc — graph-level select with one sub-scope per case; Go
    semantics: pick a ready case at random, block until one is). Host
    arbitration rides the same ordered io_callback bridge as
    channel_send/recv, so a select's choice keeps program order with
    surrounding channel ops and interoperates with host go() threads.

    Outputs: CaseIndex (int32 scalar — downstream control flow branches
    on it with IfElse/cond/switch), RecvOk (int32 [n_recv]: 1 at the
    fired recv's slot iff it delivered a real value, 0 when it fired on
    a closed channel), and one Out per recv case (the received value
    when that case fired, zeros otherwise)."""
    cids = ctx.inputs("Channels")
    send_vals = ctx.inputs("SendX") or []
    kinds = list(ctx.attr("kinds"))
    timeout = float(ctx.attr("timeout", -1.0))
    recv_shapes = ctx.attr("recv_shapes", []) or []
    recv_dtypes = ctx.attr("recv_dtypes", []) or []
    recv_specs = [(tuple(int(d) for d in s), np.dtype(dt).name)
                  for s, dt in zip(recv_shapes, recv_dtypes)]
    for shape, _ in recv_specs:
        if any(d < 0 for d in shape):
            raise ValueError(
                f"select recv cases need fully static shapes, got "
                f"{shape}")
    if len(kinds) != len(cids):
        raise ValueError(f"select got {len(cids)} channels for "
                         f"{len(kinds)} case kinds")

    out_shapes = (jax.ShapeDtypeStruct((), jnp.int32),)
    if recv_specs:
        out_shapes += (jax.ShapeDtypeStruct((len(recv_specs),), jnp.int32),)
    out_shapes += tuple(jax.ShapeDtypeStruct(shape, jnp_dtype(dt))
                        for shape, dt in recv_specs)
    cid_vec = jnp.stack([jnp.asarray(c, jnp.int32).reshape(())
                         for c in cids])
    res = jax.experimental.io_callback(
        functools.partial(_host_select, kinds=tuple(kinds),
                          timeout=timeout, recv_specs=tuple(recv_specs)),
        out_shapes, cid_vec, *send_vals, ordered=True)
    ctx.set_output("CaseIndex", res[0])
    if recv_specs:
        ctx.set_output("RecvOk", res[1])   # no-op if not wired
        ctx.set_outputs("Out", list(res[2:]))
