"""In-graph CSP channel ops (reference: channel_create/send/recv/close
ops, paddle/fluid/operators/channel_*.cc + framework/channel.h:33, used
by go/select programs).

TPU-native form: device programs are pure, so channel STATE lives on the
host (the same `concurrency.Channel` objects the Python API uses); the
in-graph ops bridge to it with `jax.experimental.io_callback(ordered=True)`
so sends/recvs keep program order inside one executed program and
interoperate with host-side `go()` producers/consumers. Gradients do not
flow through channels (the reference's channel ops are not differentiable
either); recv needs a static shape/dtype attr, XLA's static-shape regime.

Deadlock note: a recv on an empty channel BLOCKS the executed program
(as the reference's ChannelReceive blocks its thread); pair in-graph
recvs with host-side `go()` senders or buffered channels, and set
`timeout` to fail fast instead.
"""
from __future__ import annotations

import functools
import threading
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..concurrency import Channel, ChannelClosed
from ..core.registry import register_op
from .core_ops import jnp_dtype

# host channel registry: id -> Channel (in-graph ops reference channels
# by integer id carried as a scalar tensor). Unregistered ids leave a
# tombstone so a late op on a swept channel still reads as "closed"
# rather than "never existed"; ids are monotonic so tombstones are just
# "allocated but absent".
_channels: Dict[int, Channel] = {}
_lock = threading.Lock()
_next_id = [1]


def register_channel(ch: Channel) -> int:
    """Expose an existing host Channel to in-graph ops; returns its id."""
    with _lock:
        cid = _next_id[0]
        _next_id[0] += 1
        _channels[cid] = ch
    return cid


def get_channel(cid: int) -> Channel:
    cid = int(cid)
    with _lock:
        ch = _channels.get(cid)
        allocated = 0 < cid < _next_id[0]
    if ch is None:
        if allocated:
            raise ChannelClosed(
                f"channel id {cid} was closed and drained (its host "
                "object has been released)")
        raise KeyError(f"unknown channel id {cid} (create it with "
                       "channel_create or register_channel)")
    return ch


def _unregister(cid: int):
    with _lock:
        _channels.pop(int(cid), None)


def _gc_dead_channels():
    """Drop closed, drained channels from the registry. channel_create
    runs its callback on every program execution, so without this sweep
    a program that closes with buffered items nobody drains would grow
    the registry by one Channel per run. (A channel that is never closed
    at all stays registered — the host cannot see device-side id refs,
    so close is the lifetime signal, as in the reference's
    channel_close_op.)"""
    with _lock:
        dead = [cid for cid, ch in _channels.items()
                if ch.closed and ch.drained()]
        for cid in dead:
            _channels.pop(cid, None)


def _host_create(capacity):
    _gc_dead_channels()
    return np.int32(register_channel(Channel(int(capacity))))


def _host_send(cid, value, *, timeout):
    ch = get_channel(int(cid))
    t = float(timeout)
    ok = ch.send(np.asarray(value), timeout=None if t < 0 else t)
    if not ok:
        raise TimeoutError(f"channel_send timed out after {t}s")
    return np.int32(1)


def _host_recv(cid, *, timeout, shape, dtype):
    ch = get_channel(int(cid))
    t = float(timeout)
    value, ok = ch.recv(timeout=None if t < 0 else t)
    if not ok:
        if ch.closed:
            # closed AND drained: this channel can never produce again —
            # drop it from the registry so looped programs don't leak
            _unregister(cid)
            raise ChannelClosed("channel_recv on a closed, drained "
                                "channel")
        raise TimeoutError(f"channel_recv timed out after {t}s")
    arr = np.asarray(value).astype(dtype, copy=False)
    if arr.shape != shape:
        raise ValueError(f"channel_recv expected shape {shape}, got "
                         f"{arr.shape}")
    return arr


def _host_close(cid):
    ch = get_channel(int(cid))
    ch.close()
    # unregister once nothing is left to drain (a close with buffered
    # items keeps the id alive until a recv drains it)
    if ch.drained():
        _unregister(cid)
    return np.int32(1)


@register_op("channel_create", stateful=True)
def _channel_create(ctx):
    capacity = int(ctx.attr("capacity", 0))
    if capacity < 1:
        # an unbuffered in-graph channel deadlocks by construction:
        # ordered callbacks serialize, so a blocking rendezvous send can
        # never meet its receiver within one program. Host-side
        # unbuffered channels still work via register_channel + go().
        raise ValueError(
            "in-graph channel_create needs capacity >= 1 (unbuffered "
            "rendezvous cannot complete inside one ordered program); "
            "for unbuffered host channels use concurrency.Channel + "
            "ops.csp_ops.register_channel")
    cid = jax.experimental.io_callback(
        functools.partial(_host_create, capacity),
        jax.ShapeDtypeStruct((), jnp.int32), ordered=True)
    ctx.set_output("Out", cid)


@register_op("channel_send", stateful=True, no_grad_slots=["Channel", "X"])
def _channel_send(ctx):
    cid = ctx.input("Channel")
    x = ctx.input("X")
    timeout = float(ctx.attr("timeout", -1.0))
    status = jax.experimental.io_callback(
        functools.partial(_host_send, timeout=timeout),
        jax.ShapeDtypeStruct((), jnp.int32), cid, x, ordered=True)
    ctx.set_output("Status", status)


@register_op("channel_recv", stateful=True, no_grad_slots=["Channel"])
def _channel_recv(ctx):
    cid = ctx.input("Channel")
    shape = tuple(int(d) for d in ctx.attr("shape"))
    if any(d < 0 for d in shape):
        raise ValueError(
            f"channel_recv needs a fully static shape (got {shape}); "
            "the batch dim cannot be -1 under XLA")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    timeout = float(ctx.attr("timeout", -1.0))
    out = jax.experimental.io_callback(
        functools.partial(_host_recv, timeout=timeout, shape=shape,
                          dtype=np.dtype(dtype).name),
        jax.ShapeDtypeStruct(shape, dtype), cid, ordered=True)
    ctx.set_output("Out", out)


@register_op("channel_close", stateful=True, no_grad_slots=["Channel"])
def _channel_close(ctx):
    cid = ctx.input("Channel")
    status = jax.experimental.io_callback(
        _host_close, jax.ShapeDtypeStruct((), jnp.int32), cid,
        ordered=True)
    ctx.set_output("Status", status)
