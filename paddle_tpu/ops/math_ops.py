"""Math ops: elementwise (with reference broadcast semantics), matmul,
reductions, activations, comparisons, clipping, norms.

Reference parity: paddle/fluid/operators/elementwise_op_function.h (axis
broadcast), matmul_op.cc, mul_op.cc (flatten-to-2D matmul), reduce_op.cc,
activation_op.cc, clip_op.cc, softmax_op.cc, topk. All rules are pure
jax.numpy, so the MXU sees large fused matmuls and XLA fuses the rest.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..amp import amp_cast, amp_enabled
from ..core.registry import register_op
from .core_ops import jnp_dtype


def _mxu_matmul(x, y):
    """matmul that engages the MXU in one pass under AMP: bf16 operands,
    float32 accumulation, and a bf16 RESULT so activations thread
    end-to-end at half width (the f32->bf16 rounding happens in the
    matmul epilogue, fused — see MFU_BREAKDOWN.md)."""
    out_dtype = jnp.promote_types(x.dtype, y.dtype)
    x, y = amp_cast(x, y)
    if x.dtype == jnp.bfloat16 == y.dtype and out_dtype == jnp.float32:
        out_dtype = jnp.bfloat16
        pref = jnp.float32
    else:
        pref = None
    return jnp.matmul(x, y, preferred_element_type=pref).astype(out_dtype)


def _broadcast_y(x, y, axis: int):
    """Reference elementwise broadcast: align y's dims starting at `axis`
    of x (elementwise_op_function.h). axis=-1 means trailing alignment."""
    xnd, ynd = x.ndim, y.ndim
    if xnd == ynd:
        return y
    if axis == -1 or axis is None:
        axis = xnd - ynd
    shape = [1] * axis + list(y.shape) + [1] * (xnd - axis - ynd)
    return y.reshape(shape)


def _register_elementwise(name, fn):
    @register_op(name)
    def _op(ctx, _fn=fn):
        x = ctx.input("X")
        y = ctx.input("Y")
        y = _broadcast_y(x, y, ctx.attr("axis", -1))
        # Under AMP, bf16 wins mixed bf16/f32 elementwise ops (a f32
        # bias/scale param would otherwise silently promote the whole
        # activation stream back to f32 width).
        if amp_enabled() and {getattr(x, "dtype", None),
                              getattr(y, "dtype", None)} == \
                {jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32)}:
            x, y = (a.astype(jnp.bfloat16) for a in (x, y))
        ctx.set_output("Out", _fn(x, y))


_register_elementwise("elementwise_add", lambda x, y: x + y)
_register_elementwise("elementwise_sub", lambda x, y: x - y)
_register_elementwise("elementwise_mul", lambda x, y: x * y)
_register_elementwise("elementwise_div", lambda x, y: x / y)
_register_elementwise("elementwise_pow", lambda x, y: jnp.power(x, y))
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_mod", jnp.mod)
_register_elementwise("elementwise_floordiv", jnp.floor_divide)


@register_op("mul")
def _mul(ctx):
    """The reference's `mul` op: flatten X to 2-D at x_num_col_dims, Y at
    y_num_col_dims, matmul, restore shape (mul_op.cc)."""
    x = ctx.input("X")
    y = ctx.input("Y")
    xn = ctx.attr("x_num_col_dims", 1)
    yn = ctx.attr("y_num_col_dims", 1)
    x2 = x.reshape((_prod(x.shape[:xn]), _prod(x.shape[xn:])))
    y2 = y.reshape((_prod(y.shape[:yn]), _prod(y.shape[yn:])))
    out = _mxu_matmul(x2, y2)
    out_shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    ctx.set_output("Out", out.reshape(out_shape))


def _prod(dims):
    p = 1
    for d in dims:
        p *= int(d)
    return p


@register_op("matmul")
def _matmul(ctx):
    x = ctx.input("X")
    y = ctx.input("Y")
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = _mxu_matmul(x, y)
    alpha = ctx.attr("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_output("Out", out)


@register_op("dot")
def _dot(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    ctx.set_output("Out", jnp.sum(x * y, axis=-1, keepdims=True))


# -- reductions -------------------------------------------------------------

def _register_reduce(name, fn):
    @register_op(name)
    def _op(ctx, _fn=fn):
        x = ctx.input("X")
        dim = ctx.attr("dim", None)
        keep = ctx.attr("keep_dim", False)
        if ctx.attr("reduce_all", False) or dim is None:
            axis = None
        else:
            axis = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        out = _fn(x, axis=axis, keepdims=keep)
        if axis is None and not keep:
            out = out.reshape(())
        ctx.set_output("Out", out)


_register_reduce("reduce_sum", jnp.sum)
_register_reduce("reduce_mean", jnp.mean)
_register_reduce("reduce_max", jnp.max)
_register_reduce("reduce_min", jnp.min)
_register_reduce("reduce_prod", jnp.prod)


@register_op("mean")
def _mean(ctx):
    ctx.set_output("Out", jnp.mean(ctx.input("X")))


# -- activations ------------------------------------------------------------

def _register_act(name, fn):
    @register_op(name)
    def _op(ctx, _fn=fn):
        ctx.set_output("Out", _fn(ctx.input("X")))


_register_act("relu", jax.nn.relu)
_register_act("relu6", lambda x: jnp.clip(x, 0.0, 6.0))
_register_act("sigmoid", jax.nn.sigmoid)
_register_act("logsigmoid", jax.nn.log_sigmoid)
_register_act("tanh", jnp.tanh)
_register_act("tanh_shrink", lambda x: x - jnp.tanh(x))
_register_act("softsign", lambda x: x / (1 + jnp.abs(x)))
_register_act("sqrt", jnp.sqrt)
_register_act("rsqrt", jax.lax.rsqrt)
_register_act("abs", jnp.abs)
_register_act("ceil", jnp.ceil)
_register_act("floor", jnp.floor)
_register_act("round", jnp.round)
_register_act("reciprocal", lambda x: 1.0 / x)
_register_act("square", jnp.square)
_register_act("exp", jnp.exp)
_register_act("log", jnp.log)
_register_act("gelu", jax.nn.gelu)
_register_act("sin", jnp.sin)
_register_act("cos", jnp.cos)
_register_act("sign", jnp.sign)


@register_op("softplus")
def _softplus(ctx):
    ctx.set_output("Out", jax.nn.softplus(ctx.input("X")))


@register_op("leaky_relu")
def _leaky_relu(ctx):
    alpha = ctx.attr("alpha", 0.02)
    x = ctx.input("X")
    ctx.set_output("Out", jnp.where(x >= 0, x, alpha * x))


@register_op("elu")
def _elu(ctx):
    ctx.set_output("Out", jax.nn.elu(ctx.input("X"), ctx.attr("alpha", 1.0)))


@register_op("pow")
def _pow(ctx):
    ctx.set_output("Out", jnp.power(ctx.input("X"), ctx.attr("factor", 1.0)))


@register_op("hard_sigmoid")
def _hard_sigmoid(ctx):
    slope = ctx.attr("slope", 0.2)
    offset = ctx.attr("offset", 0.5)
    ctx.set_output("Out", jnp.clip(slope * ctx.input("X") + offset, 0.0, 1.0))


@register_op("swish")
def _swish(ctx):
    beta = ctx.attr("beta", 1.0)
    x = ctx.input("X")
    ctx.set_output("Out", x * jax.nn.sigmoid(beta * x))


@register_op("soft_relu")
def _soft_relu(ctx):
    t = ctx.attr("threshold", 40.0)
    x = jnp.clip(ctx.input("X"), -t, t)
    ctx.set_output("Out", jnp.log(1 + jnp.exp(x)))


@register_op("clip")
def _clip(ctx):
    ctx.set_output("Out", jnp.clip(ctx.input("X"), ctx.attr("min", -1.0),
                                   ctx.attr("max", 1.0)))


@register_op("clip_by_norm")
def _clip_by_norm(ctx):
    x = ctx.input("X")
    max_norm = ctx.attr("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    ctx.set_output("Out", x * scale)


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx):
    ctx.set_output("Out", jnp.sum(jnp.square(ctx.input("X"))).reshape(()))


@register_op("l2_normalize")
def _l2_normalize(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    eps = ctx.attr("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    ctx.set_output("Out", x / jnp.maximum(norm, eps))
    ctx.set_output("Norm", norm)


# -- softmax family ---------------------------------------------------------

@register_op("softmax")
def _softmax(ctx):
    x = ctx.input("X")
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    out = jax.nn.softmax(xf, axis=ctx.attr("axis", -1))
    ctx.set_output("Out", out.astype(x.dtype))


@register_op("log_softmax")
def _log_softmax(ctx):
    x = ctx.input("X")
    xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
    out = jax.nn.log_softmax(xf, axis=ctx.attr("axis", -1))
    ctx.set_output("Out", out.astype(x.dtype))


# -- comparisons / logical --------------------------------------------------

def _register_cmp(name, fn):
    @register_op(name, no_grad_slots=["X", "Y"])
    def _op(ctx, _fn=fn):
        x, y = ctx.input("X"), ctx.input("Y")
        if y is not None:
            y = _broadcast_y(x, y, ctx.attr("axis", -1))
        ctx.set_output("Out", _fn(x, y))


_register_cmp("equal", lambda x, y: x == y)
_register_cmp("not_equal", lambda x, y: x != y)
_register_cmp("less_than", lambda x, y: x < y)
_register_cmp("less_equal", lambda x, y: x <= y)
_register_cmp("greater_than", lambda x, y: x > y)
_register_cmp("greater_equal", lambda x, y: x >= y)

_register_cmp("logical_and", jnp.logical_and)
_register_cmp("logical_or", jnp.logical_or)
_register_cmp("logical_xor", jnp.logical_xor)


@register_op("logical_not", no_grad_slots=["X"])
def _logical_not(ctx):
    ctx.set_output("Out", jnp.logical_not(ctx.input("X")))


@register_op("isfinite", no_grad_slots=["X"])
def _isfinite(ctx):
    ctx.set_output("Out", jnp.all(jnp.isfinite(ctx.input("X"))).reshape(()))


# -- misc math --------------------------------------------------------------

@register_op("top_k", no_grad_slots=["X"])
def _top_k(ctx):
    x = ctx.input("X")
    k = ctx.attr("k", 1)
    vals, idxs = jax.lax.top_k(x, k)
    ctx.set_output("Out", vals)
    ctx.set_output("Indices", idxs.astype(jnp.int64))


@register_op("arg_max", no_grad_slots=["X"])
def _arg_max(ctx):
    ctx.set_output("Out", jnp.argmax(ctx.input("X"),
                                     axis=ctx.attr("axis", -1)).astype(jnp.int64))


@register_op("arg_min", no_grad_slots=["X"])
def _arg_min(ctx):
    ctx.set_output("Out", jnp.argmin(ctx.input("X"),
                                     axis=ctx.attr("axis", -1)).astype(jnp.int64))


@register_op("cumsum")
def _cumsum(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", -1)
    reverse = ctx.attr("reverse", False)
    exclusive = ctx.attr("exclusive", False)
    work = jnp.flip(x, axis) if reverse else x
    out = jnp.cumsum(work, axis=axis)
    if exclusive:
        # shift forward along axis: out[i] = sum of strictly-earlier elems
        pad = [(0, 0)] * x.ndim
        pad[axis % x.ndim] = (1, 0)
        out = jnp.pad(out, pad)[tuple(
            slice(0, s) if i == (axis % x.ndim) else slice(None)
            for i, s in enumerate(x.shape))]
    if reverse:
        out = jnp.flip(out, axis)
    ctx.set_output("Out", out)


@register_op("maxout")
def _maxout(ctx):
    x = ctx.input("X")  # NCHW
    groups = ctx.attr("groups")
    n, c, h, w = x.shape
    ctx.set_output("Out", x.reshape(n, c // groups, groups, h, w).max(axis=2))


@register_op("cos_sim")
def _cos_sim(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / (xn * yn + 1e-12)
    ctx.set_output("Out", out)
    ctx.set_output("XNorm", xn)
    ctx.set_output("YNorm", yn)


# -- remaining activation surface (reference: activation_op.cc) -------------

_register_act("stanh", lambda x: 1.7159 * jnp.tanh(0.66667 * x))


@register_op("brelu")
def _brelu(ctx):
    x = ctx.input("X")
    t_min = ctx.attr("t_min", 0.0)
    t_max = ctx.attr("t_max", 24.0)
    ctx.set_output("Out", jnp.clip(x, t_min, t_max))


@register_op("hard_shrink")
def _hard_shrink(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 0.5)
    ctx.set_output("Out", jnp.where(jnp.abs(x) > t, x, 0.0))


@register_op("softshrink")
def _softshrink(ctx):
    x = ctx.input("X")
    lam = ctx.attr("lambda", 0.5)
    ctx.set_output("Out", jnp.where(x > lam, x - lam,
                                    jnp.where(x < -lam, x + lam, 0.0)))


@register_op("thresholded_relu")
def _thresholded_relu(ctx):
    x = ctx.input("X")
    t = ctx.attr("threshold", 1.0)
    ctx.set_output("Out", jnp.where(x > t, x, 0.0))


@register_op("prelu")
def _prelu(ctx):
    """PReLU with learned slope (reference: prelu_op.cc — 'all' mode
    shares one alpha; 'channel' mode one per channel dim 1)."""
    x = ctx.input("X")
    alpha = ctx.input("Alpha")
    mode = ctx.attr("mode", "all")
    if mode == "channel" and x.ndim >= 2:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:
        alpha = alpha.reshape((1,) * x.ndim)
    ctx.set_output("Out", jnp.where(x > 0, x, alpha * x))


@register_op("label_smooth", no_grad_slots=["PriorDist"])
def _label_smooth(ctx):
    """(1-eps)*label + eps*prior (uniform when no prior);
    reference: label_smooth_op.cc."""
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 0.0)
    prior = ctx.input("PriorDist")
    if prior is None:
        prior = 1.0 / x.shape[-1]
    ctx.set_output("Out", (1.0 - eps) * x + eps * prior)


# -- remaining losses (reference: *_loss_op.cc) -----------------------------

@register_op("modified_huber_loss", no_grad_slots=["Y"])
def _modified_huber_loss(ctx):
    """Classification Huber loss on y in {0,1} (reference:
    modified_huber_loss_op.cc): z = 2y-1; yv = z*pred;
    loss = (1-yv)^2 clipped quadratic for yv >= -1 else -4*yv."""
    x = ctx.input("X")
    y = ctx.input("Y").astype(x.dtype)
    yv = (2.0 * y - 1.0) * x
    loss = jnp.where(yv < -1.0, -4.0 * yv,
                     jnp.square(jnp.maximum(0.0, 1.0 - yv)))
    ctx.set_output("IntermediateVal", yv)
    ctx.set_output("Out", loss)


@register_op("rank_loss", no_grad_slots=["Label"])
def _rank_loss(ctx):
    """Pairwise ranking loss (reference: rank_loss_op.cc):
    C = -label*(l-r) + log(1+exp(l-r))."""
    label = ctx.input("Label")
    left = ctx.input("Left")
    right = ctx.input("Right")
    d = left - right
    ctx.set_output("Out", -label * d + jnp.logaddexp(0.0, d))


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    diff = x - y.reshape(y.shape if y.shape[0] == x.shape[0]
                         else (1,) + tuple(y.shape[1:]))
    ctx.set_output("sub_result", diff)
    ctx.set_output("Out", jnp.sum(jnp.square(diff), axis=-1, keepdims=True))


@register_op("l1_norm")
def _l1_norm(ctx):
    ctx.set_output("Out", jnp.sum(jnp.abs(ctx.input("X"))))


@register_op("norm")
def _norm(ctx):
    """L2-normalize along channel dim 1 with learned scale (reference:
    norm_op.cc — out = scale_c * x / ||x||_2 over channels)."""
    x = ctx.input("X")
    scale = ctx.input("Scale")
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + 1e-10)
    scale = scale.reshape((1, -1) + (1,) * (x.ndim - 2))
    ctx.set_output("Out", scale * x / norm)


# -- misc parity ops --------------------------------------------------------

@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ctx):
    """out[:, k] = x @ W_k @ y^T diag + bias (reference:
    bilinear_tensor_product_op.cc)."""
    x = ctx.input("X")          # [n, dx]
    y = ctx.input("Y")          # [n, dy]
    w = ctx.input("Weight")     # [k, dx, dy]
    out = jnp.einsum("nd,kde,ne->nk", x, w, y)
    bias = ctx.input("Bias")
    if bias is not None:
        out = out + bias.reshape(1, -1)
    ctx.set_output("Out", out)


@register_op("conv_shift")
def _conv_shift(ctx):
    """Circular 1-D correlation (reference: conv_shift_op.cc): out[i,j] =
    sum_k x[i, (j+k-m//2) mod n] * y[i,k] with y width m (odd)."""
    x = ctx.input("X")  # [b, n]
    y = ctx.input("Y")  # [b, m], m odd, m <= n
    b, n = x.shape
    m = y.shape[1]
    half = m // 2
    idx = (jnp.arange(n)[:, None] + jnp.arange(m)[None, :] - half) % n
    ctx.set_output("Out", jnp.einsum("bnm,bm->bn", x[:, idx], y))


@register_op("is_empty", no_grad_slots=["X"])
def _is_empty(ctx):
    import numpy as _np
    x = ctx.input("X")
    size = int(_np.prod(x.shape)) if x.shape else 0
    ctx.set_output("Out", jnp.asarray(size == 0))


@register_op("minus")
def _minus(ctx):
    """Out = X - Y (reference: minus_op.cc)."""
    ctx.set_output("Out", ctx.input("X") - ctx.input("Y"))
