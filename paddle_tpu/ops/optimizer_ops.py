"""Optimizer ops: one op per parameter update, writing ParamOut (and
accumulator outs) back to persistable state — the executor threads them
functionally with buffer donation, so updates stay on-device in place.

Reference parity: paddle/fluid/operators/{sgd_op.cc, momentum_op.cc,
adam_op.cc, adagrad_op.cc, adamax_op.cc, adadelta_op.cc, rmsprop_op.cc,
decayed_adagrad_op.cc, ftrl_op.cc, lars_momentum...}. All rules are pure
jnp; optimizer math runs fused into the training step's XLA program.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op


def _no_grads(*slots):
    return list(slots)


# ---------------------------------------------------------------------------
# sparse (row-wise lazy) update rules — the SelectedRows path
# ---------------------------------------------------------------------------
#: hyperparameter defaults per sparse rule, matching the dense ops above
SPARSE_HYPER_DEFAULTS = {
    "sgd": {},
    "adagrad": {"epsilon": 1e-6},
    "adam": {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
}


def sparse_row_update(kind, p_rows, slot_rows, g, lr, hyper,
                      b1p=None, b2p=None):
    """One optimizer step restricted to the TOUCHED rows — formulas are
    the exact expressions of the dense ops above (`_sgd`, `_adagrad`,
    `_adam`), applied to gathered row blocks so the sparse path is
    bit-identical to the dense single-chip optimizer on those rows.
    ``slot_rows`` is a tuple of gathered accumulator row blocks in the
    order the dense op reads them; returns (new_p_rows, new_slot_rows).

    Lazy semantics (reference SelectedRows / sparse adam): rows NOT in
    the update never decay — for adam that means a row touched only
    intermittently diverges from the dense rule, which decays moments
    every step (documented in KNOWN_GAPS "Sharded embedding
    boundaries"). Rows touched every step match bitwise.
    """
    lr = lr.reshape(()).astype(p_rows.dtype)
    if kind == "sgd":
        return p_rows - lr * g, ()
    if kind == "adagrad":
        (m,) = slot_rows
        eps = hyper.get("epsilon", 1e-6)
        m_out = m + jnp.square(g)
        return p_rows - lr * g / (jnp.sqrt(m_out) + eps), (m_out,)
    if kind == "adam":
        m1, m2 = slot_rows
        b1 = hyper.get("beta1", 0.9)
        b2 = hyper.get("beta2", 0.999)
        eps = hyper.get("epsilon", 1e-8)
        b1p = b1p.reshape(())
        b2p = b2p.reshape(())
        m1_out = b1 * m1 + (1 - b1) * g
        m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        p_out = p_rows - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
        return p_out, (m1_out, m2_out)
    raise ValueError(f"no sparse update rule for optimizer {kind!r}; "
                     f"have {sorted(SPARSE_HYPER_DEFAULTS)}")


def _sparse_scatter(ctx, kind, slot_in_out):
    """Shared body of the sparse_* ops: gather the touched rows of
    Param (+ slots), run `sparse_row_update`, scatter the results back.
    Ids outside [0, vocab) — negative, the dedup fill sentinel, a
    padding row routed to the sentinel — are DROPPED: their rows (and
    slot rows: lazy semantics) are left untouched."""
    p = ctx.input("Param")
    g = ctx.input("Grad")              # [U, D] deduped row gradients
    ids = ctx.input("Ids")             # [U] unique row ids
    lr = ctx.input("LearningRate")
    vocab = p.shape[0]
    hit = (ids >= 0) & (ids < vocab)
    safe = jnp.clip(ids, 0, vocab - 1)
    p_rows = jnp.take(p, safe, axis=0)
    slot_rows = tuple(jnp.take(ctx.input(s), safe, axis=0)
                      for s, _o in slot_in_out)
    hyper = {k: ctx.attr(k, v)
             for k, v in SPARSE_HYPER_DEFAULTS[kind].items()}
    b1p = ctx.input("Beta1Pow") if kind == "adam" else None
    b2p = ctx.input("Beta2Pow") if kind == "adam" else None
    new_p, new_slots = sparse_row_update(kind, p_rows, slot_rows, g, lr,
                                         hyper, b1p, b2p)
    tgt = jnp.where(hit, ids, vocab)   # out-of-bounds target -> dropped
    ctx.set_output("ParamOut", p.at[tgt].set(new_p, mode="drop"))
    for (s_in, s_out), ns in zip(slot_in_out, new_slots):
        ctx.set_output(s_out,
                       ctx.input(s_in).at[tgt].set(ns, mode="drop"))
    if kind == "adam":
        b1 = ctx.attr("beta1", 0.9)
        b2 = ctx.attr("beta2", 0.999)
        ctx.set_output("Beta1PowOut", ctx.input("Beta1Pow") * b1)
        ctx.set_output("Beta2PowOut", ctx.input("Beta2Pow") * b2)


@register_op("sparse_sgd", no_grad_slots=["Param", "Grad", "Ids",
                                          "LearningRate"])
def _sparse_sgd(ctx):
    """Row-wise SGD over unique touched rows (reference: sgd_op.h
    SelectedRows branch)."""
    _sparse_scatter(ctx, "sgd", ())


@register_op("sparse_adagrad", no_grad_slots=["Param", "Grad", "Ids",
                                              "Moment", "LearningRate"])
def _sparse_adagrad(ctx):
    """Row-wise Adagrad: touched rows' moment accumulates, untouched
    rows' moment is untouched (reference: adagrad_op.cc SelectedRows
    branch)."""
    _sparse_scatter(ctx, "adagrad", (("Moment", "MomentOut"),))


@register_op("sparse_adam", no_grad_slots=[
    "Param", "Grad", "Ids", "Moment1", "Moment2", "LearningRate",
    "Beta1Pow", "Beta2Pow"])
def _sparse_adam(ctx):
    """Row-wise lazy Adam (reference: adam_op.h SelectedRows branch,
    lazy_mode): moments decay only on touched rows; the beta powers
    advance globally once per step."""
    _sparse_scatter(ctx, "adam", (("Moment1", "Moment1Out"),
                                  ("Moment2", "Moment2Out")))


@register_op("sgd", no_grad_slots=["Param", "Grad", "LearningRate"])
def _sgd(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    lr = ctx.input("LearningRate")
    ctx.set_output("ParamOut", p - lr.reshape(()).astype(p.dtype) * g)


@register_op("momentum",
             no_grad_slots=["Param", "Grad", "Velocity", "LearningRate"])
def _momentum(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    v = ctx.input("Velocity")
    lr = ctx.input("LearningRate").reshape(())
    mu = ctx.attr("mu", 0.9)
    v_out = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    ctx.set_output("ParamOut", p_out)
    ctx.set_output("VelocityOut", v_out)


@register_op("adam", no_grad_slots=[
    "Param", "Grad", "Moment1", "Moment2", "LearningRate",
    "Beta1Pow", "Beta2Pow"])
def _adam(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    m1 = ctx.input("Moment1")
    m2 = ctx.input("Moment2")
    lr = ctx.input("LearningRate").reshape(())
    b1p = ctx.input("Beta1Pow").reshape(())
    b2p = ctx.input("Beta2Pow").reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    ctx.set_output("ParamOut", p_out)
    ctx.set_output("Moment1Out", m1_out)
    ctx.set_output("Moment2Out", m2_out)
    # preserve the accumulator's [1] shape: state written must match
    # state read or the var can't chain through a scan carry
    # (Executor.run(iterations=K))
    ctx.set_output("Beta1PowOut", ctx.input("Beta1Pow") * b1)
    ctx.set_output("Beta2PowOut", ctx.input("Beta2Pow") * b2)


@register_op("adagrad", no_grad_slots=["Param", "Grad", "Moment",
                                       "LearningRate"])
def _adagrad(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    eps = ctx.attr("epsilon", 1e-6)
    m_out = m + jnp.square(g)
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_out) + eps))
    ctx.set_output("MomentOut", m_out)


@register_op("adamax", no_grad_slots=["Param", "Grad", "Moment", "InfNorm",
                                      "LearningRate", "Beta1Pow"])
def _adamax(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    m = ctx.input("Moment")
    u = ctx.input("InfNorm")
    lr = ctx.input("LearningRate").reshape(())
    b1p = ctx.input("Beta1Pow").reshape(())
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    # epsilon goes INSIDE the max, on the decayed-norm side
    # (reference adamax_op.h: grad.abs().cwiseMax(beta2*inf_norm + eps))
    u_out = jnp.maximum(jnp.abs(g), b2 * u + eps)
    p_out = p - (lr / (1 - b1p)) * m_out / u_out
    ctx.set_output("ParamOut", p_out)
    ctx.set_output("MomentOut", m_out)
    ctx.set_output("InfNormOut", u_out)


@register_op("adadelta", no_grad_slots=["Param", "Grad", "AvgSquaredGrad",
                                        "AvgSquaredUpdate"])
def _adadelta(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    sg = ctx.input("AvgSquaredGrad")
    su = ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    sg_out = rho * sg + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((su + eps) / (sg_out + eps)) * g
    su_out = rho * su + (1 - rho) * jnp.square(update)
    ctx.set_output("ParamOut", p + update)
    ctx.set_output("AvgSquaredGradOut", sg_out)
    ctx.set_output("AvgSquaredUpdateOut", su_out)


@register_op("rmsprop", no_grad_slots=["Param", "Grad", "Moment",
                                       "MeanSquare", "LearningRate"])
def _rmsprop(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    mom = ctx.input("Moment")
    ms = ctx.input("MeanSquare")
    lr = ctx.input("LearningRate").reshape(())
    rho = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    mu = ctx.attr("momentum", 0.0)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    mom_out = mu * mom + lr * g / jnp.sqrt(ms_out + eps)
    ctx.set_output("ParamOut", p - mom_out)
    ctx.set_output("MomentOut", mom_out)
    ctx.set_output("MeanSquareOut", ms_out)


@register_op("decayed_adagrad", no_grad_slots=["Param", "Grad", "Moment",
                                               "LearningRate"])
def _decayed_adagrad(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    ctx.set_output("ParamOut", p - lr * g / (jnp.sqrt(m_out) + eps))
    ctx.set_output("MomentOut", m_out)


@register_op("ftrl", no_grad_slots=["Param", "Grad", "SquaredAccumulator",
                                    "LinearAccumulator", "LearningRate"])
def _ftrl(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    sq = ctx.input("SquaredAccumulator")
    lin = ctx.input("LinearAccumulator")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    power = ctx.attr("lr_power", -0.5)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    lin_out = lin + g - sigma * p
    quad = jnp.power(new_sq, -power) / lr + 2 * l2
    pre = jnp.clip(lin_out, -l1, l1) - lin_out
    p_out = jnp.where(jnp.abs(lin_out) > l1, pre / quad, jnp.zeros_like(p))
    ctx.set_output("ParamOut", p_out)
    ctx.set_output("SquaredAccumOut", new_sq)
    ctx.set_output("LinearAccumOut", lin_out)


@register_op("lars_momentum", no_grad_slots=["Param", "Grad", "Velocity",
                                             "LearningRate"])
def _lars_momentum(ctx):
    p = ctx.input("Param")
    g = ctx.input("Grad")
    v = ctx.input("Velocity")
    lr = ctx.input("LearningRate").reshape(())
    mu = ctx.attr("mu", 0.9)
    coeff = ctx.attr("lars_coeff", 0.001)
    decay = ctx.attr("lars_weight_decay", 0.0005)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = lr * coeff * p_norm / (g_norm + decay * p_norm + 1e-12)
    v_out = mu * v + local_lr * (g + decay * p)
    ctx.set_output("ParamOut", p - v_out)
    ctx.set_output("VelocityOut", v_out)


@register_op("proximal_gd", no_grad_slots=["Param", "Grad", "LearningRate"])
def _proximal_gd(ctx):
    """Proximal gradient descent with L1/L2 regularization (reference:
    proximal_gd_op.cc): prox_param = param - lr*grad, then soft-threshold."""
    p = ctx.input("Param")
    g = ctx.input("Grad")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    prox = p - lr * g
    if l1 > 0:
        out = (jnp.sign(prox) *
               jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)) / (1.0 + lr * l2)
    else:
        out = prox / (1.0 + lr * l2)
    ctx.set_output("ParamOut", out)


@register_op("proximal_adagrad", no_grad_slots=["Param", "Grad", "Moment",
                                                "LearningRate"])
def _proximal_adagrad(ctx):
    """Proximal Adagrad (reference: proximal_adagrad_op.cc)."""
    p = ctx.input("Param")
    g = ctx.input("Grad")
    m = ctx.input("Moment")
    lr = ctx.input("LearningRate").reshape(())
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    m_out = m + g * g
    prox = p - lr * g / jnp.sqrt(m_out)
    # the shrink thresholds scale by the BASE lr, not the per-element
    # effective lr (reference proximal_adagrad_op.h: lr*l1, 1+lr*l2)
    if l1 > 0:
        out = (jnp.sign(prox) *
               jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)) / (1.0 + lr * l2)
    else:
        out = prox / (1.0 + lr * l2)
    ctx.set_output("ParamOut", out)
    ctx.set_output("MomentOut", m_out)


@register_op("average_accumulates", no_grad_slots=[
    "param", "in_sum_1", "in_sum_2", "in_sum_3", "in_num_accumulates",
    "in_old_num_accumulates", "in_num_updates"])
def _average_accumulates(ctx):
    """Sliding-window parameter sum for ModelAverage (reference:
    average_accumulates_op.h). Three-tier sums: sum_1 per-step, rolled
    into sum_2 every 16384 updates, both folded into sum_3 when the
    window [min_avg_window, min(max_avg_window, num_updates*rate)]
    closes. The reference's roll/close branches become jnp.where —
    shapes stay static so the whole update fuses into the step program."""
    p = ctx.input("param")
    s1 = ctx.input("in_sum_1")
    s2 = ctx.input("in_sum_2")
    s3 = ctx.input("in_sum_3")
    num_acc = ctx.input("in_num_accumulates").reshape(()).astype(jnp.int32)
    old_num = ctx.input("in_old_num_accumulates").reshape(()) \
        .astype(jnp.int32)
    num_upd = ctx.input("in_num_updates").reshape(()).astype(jnp.int32)
    rate = ctx.attr("average_window", 0.0)
    min_w = ctx.attr("min_average_window", 10000)
    max_w = ctx.attr("max_average_window", 10000)
    k_max = 16384  # kMaxNumAccumulates

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    o1 = s1 + p
    o2 = s2
    # precision roll. The reference's in/out tensors alias the same
    # accumulator, so its "in_sum_1" reads are post-update values —
    # mirror that sequencing here.
    roll = (num_upd % k_max) == 0
    o2 = jnp.where(roll, o2 + o1, o2)
    o1 = jnp.where(roll, jnp.zeros_like(o1), o1)
    # window close: discard the old sum
    close = (num_acc >= min_w) & \
        (num_acc.astype(jnp.float32) >=
         jnp.minimum(jnp.float32(max_w),
                     num_upd.astype(jnp.float32) * rate))
    o3 = jnp.where(close, o1 + o2, s3)
    o1 = jnp.where(close, jnp.zeros_like(o1), o1)
    o2 = jnp.where(close, jnp.zeros_like(o2), o2)
    old_num = jnp.where(close, num_acc, old_num)
    num_acc = jnp.where(close, jnp.int32(0), num_acc)

    ctx.set_output("out_sum_1", o1)
    ctx.set_output("out_sum_2", o2)
    ctx.set_output("out_sum_3", o3)
    ctx.set_output("out_num_accumulates", num_acc.reshape(1))
    ctx.set_output("out_old_num_accumulates", old_num.reshape(1))
    ctx.set_output("out_num_updates", num_upd.reshape(1))
