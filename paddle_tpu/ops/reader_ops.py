"""In-graph file readers (reference: operators/reader/* —
create_recordio_file_reader, read_file, and the shuffle/double-buffer/
multi-pass decorator readers, surfaced as fluid.layers.io functions).

TPU-native form mirrors the CSP channel design: reader STATE lives on
the host (an iterator over feed dicts, e.g. the records
recordio_writer.convert_reader_to_recordio_file wrote); the in-graph
`read_file` op pulls the next batch through an ordered
`jax.experimental.io_callback`, so reads keep program order and the
batch enters the compiled program as statically-shaped tensors.
Exhaustion raises StopIteration on the host, surfacing as an error
from Executor.run — the reference's reader EOF contract; wrap with a
multi-pass reader for epoch loops.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from .core_ops import jnp_dtype

_readers: Dict[int, "_HostReader"] = {}
_lock = threading.Lock()
_next_id = [1]


class _HostReader:
    """A restartable host iterator of feed dicts."""

    def __init__(self, make_iter: Callable):
        self.make_iter = make_iter
        self._it = None

    def next(self):
        if self._it is None:
            self._it = iter(self.make_iter())
        try:
            return next(self._it)
        except StopIteration:
            self._it = None      # next read starts a fresh pass
            raise

    def reset(self):
        self._it = None


def register_reader(make_iter: Callable) -> int:
    with _lock:
        rid = _next_id[0]
        _next_id[0] += 1
        _readers[rid] = _HostReader(make_iter)
    return rid


def unregister_reader(rid: int) -> None:
    with _lock:
        _readers.pop(int(rid), None)


def reset_readers() -> None:
    """Drop every registered host reader. Reader registrations are
    program-scoped build-time state (unlike channels, whose lifetime
    signal is close); framework.reset_default_programs calls this so a
    long-lived session rebuilding programs does not accumulate reader
    closures and live iterators."""
    with _lock:
        _readers.clear()


def get_reader(rid: int) -> _HostReader:
    with _lock:
        r = _readers.get(int(rid))
    if r is None:
        raise KeyError(f"unknown reader id {rid}")
    return r


def _host_read(rid, *, names, shapes, dtypes):
    feed = get_reader(int(rid)).next()
    out = []
    for name, shape, dtype in zip(names, shapes, dtypes):
        if name not in feed:
            raise KeyError(
                f"read_file: record has no var {name!r}; record keys: "
                f"{sorted(feed)}")
        arr = np.asarray(feed[name]).astype(dtype, copy=False)
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(
                f"read_file: var {name!r} has shape {arr.shape}, "
                f"reader declared {tuple(shape)}")
        out.append(arr)
    return tuple(out)


@register_op("read_file", stateful=True, no_grad_slots=["Reader"])
def _read_file(ctx):
    import functools

    rid = ctx.input("Reader")
    names = tuple(ctx.attr("var_names"))
    shapes = tuple(tuple(int(d) for d in s) for s in ctx.attr("shapes"))
    # canonicalize (int64 -> int32 without x64): io_callback result
    # dtypes must match what the program can hold
    dtypes = tuple(np.dtype(jax.dtypes.canonicalize_dtype(
        jnp_dtype(d))).name for d in ctx.attr("dtypes"))
    out_shapes = tuple(jax.ShapeDtypeStruct(s, jnp_dtype(d))
                       for s, d in zip(shapes, dtypes))
    res = jax.experimental.io_callback(
        functools.partial(_host_read, names=names, shapes=shapes,
                          dtypes=dtypes),
        out_shapes, jnp.asarray(rid, jnp.int32), ordered=True)
    ctx.set_outputs("Out", list(res))
