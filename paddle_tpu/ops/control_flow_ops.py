"""Control-flow ops: sub-blocks lowered to lax.scan / while_loop / cond.

Reference parity: paddle/fluid/operators/{while_op.cc:35, recurrent_op.cc:222,
conditional_block_op.cc, tensor_array_read_write_op.cc}. The reference runs
sub-blocks with nested Executors and per-step scopes; here a sub-block is
traced into the parent's XLA computation as a structured-control-flow region,
so the whole loop compiles to one fused TPU program (grad flows through via
jax.vjp of the scan/while, replacing the reference's WhileGrad/RecurrentGrad
step-scope machinery).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from functools import partial

from ..core.registry import register_op

register_op_CF = partial(register_op, ragged_aware=True)


def _trace_sub(ctx, block_idx, env):
    from ..core.executor import trace_block
    prog = ctx.extra["program"]
    return trace_block(prog.blocks[block_idx], env, ctx.extra)


@register_op_CF("static_rnn")
def _static_rnn(ctx):
    """Scan over leading time axis of each step input."""
    xs = ctx.inputs("X")                 # each [T, ...]
    mem_init = ctx.inputs("MemInit")
    step_in = ctx.attr("step_in_names")
    mem_pre = ctx.attr("mem_pre_names")
    mem_new = ctx.attr("mem_new_names")
    out_names = ctx.attr("out_names")
    blk_idx = ctx.attr("sub_block_idx")
    outer = dict(ctx.env)

    def body(carry, x_t):
        env = dict(outer)
        env.update(zip(mem_pre, carry))
        env.update(zip(step_in, x_t))
        env = _trace_sub(ctx, blk_idx, env)
        new_carry = tuple(env[n] for n in mem_new)
        outs = tuple(env[n] for n in out_names)
        return new_carry, outs

    carry0 = tuple(mem_init)
    _, stacked = jax.lax.scan(body, carry0, tuple(xs))
    ctx.set_outputs("Out", list(stacked))


@register_op_CF("while")
def _while(ctx):
    cond_name = ctx.attr("cond_name")
    carried = ctx.attr("carried_names")
    blk_idx = ctx.attr("sub_block_idx")
    outer = dict(ctx.env)
    cond0 = ctx.input("Cond")
    init = tuple(outer[n] for n in carried)

    def cond_fn(state):
        return state[0].reshape(())

    def body_fn(state):
        vals = state[1:]
        env = dict(outer)
        env.update(zip(carried, vals))
        env = _trace_sub(ctx, blk_idx, env)
        return (env[cond_name].reshape(()).astype(jnp.bool_),) + \
            tuple(env[n] for n in carried)

    final = jax.lax.while_loop(
        cond_fn, body_fn, (cond0.reshape(()).astype(jnp.bool_),) + init)
    ctx.set_outputs("Out", list(final[1:]))


@register_op_CF("cond")
def _cond(ctx):
    pred = ctx.input("Pred")
    outer = dict(ctx.env)

    def make_branch(blk_idx, out_name):
        def branch(_):
            env = dict(outer)
            env = _trace_sub(ctx, blk_idx, env)
            return env[out_name]
        return branch

    out = jax.lax.cond(pred.reshape(()).astype(jnp.bool_),
                       make_branch(ctx.attr("true_block_idx"),
                                   ctx.attr("true_out")),
                       make_branch(ctx.attr("false_block_idx"),
                                   ctx.attr("false_out")),
                       operand=None)
    ctx.set_output("Out", out)


# -- tensor arrays (dense fixed-capacity form) ------------------------------

@register_op_CF("array_write", no_grad_slots=["I"])
def _array_write(ctx):
    x = ctx.input("X")
    i = ctx.input("I").reshape(()).astype(jnp.int32)
    arr = ctx.input("Array")
    if arr is None:
        cap = ctx.attr("capacity", 128)
        arr = jnp.zeros((cap,) + tuple(x.shape), x.dtype)
    out = jax.lax.dynamic_update_index_in_dim(arr, x, i, 0)
    ctx.set_output("Out", out)


@register_op_CF("array_read", no_grad_slots=["I"])
def _array_read(ctx):
    arr = ctx.input("Array")
    i = ctx.input("I").reshape(()).astype(jnp.int32)
    ctx.set_output("Out", jax.lax.dynamic_index_in_dim(arr, i, 0,
                                                       keepdims=False))


@register_op_CF("array_length", no_grad_slots=["Array"])
def _array_length(ctx):
    arr = ctx.input("Array")
    ctx.set_output("Out", jnp.asarray(arr.shape[0], jnp.int64))
