"""Control-flow ops: sub-blocks lowered to lax.scan / while_loop / cond.

Reference parity: paddle/fluid/operators/{while_op.cc:35, recurrent_op.cc:222,
conditional_block_op.cc, tensor_array_read_write_op.cc}. The reference runs
sub-blocks with nested Executors and per-step scopes; here a sub-block is
traced into the parent's XLA computation as a structured-control-flow region,
so the whole loop compiles to one fused TPU program (grad flows through via
jax.vjp of the scan/while, replacing the reference's WhileGrad/RecurrentGrad
step-scope machinery).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from functools import partial

from ..core.registry import OpRegistry, register_op

register_op_CF = partial(register_op, ragged_aware=True)


def _trace_sub(ctx, block_idx, env):
    from ..core.executor import trace_block
    prog = ctx.extra["program"]
    return trace_block(prog.blocks[block_idx], env, ctx.extra)


def nested_dynamic_wids(program, blk_idx):
    """while_ids of every unbounded (dynamic_bound) While nested
    anywhere under block `blk_idx`, in deterministic program order.
    Static program structure — safe to bake into carry shapes."""
    out = []

    def visit(bi):
        for op in program.blocks[bi].ops:
            if op.type == "while" and op.attrs.get("dynamic_bound") and \
                    int(op.attrs.get("max_steps", 0) or 0) <= 0:
                out.append(op.attrs.get("while_id"))
            for attr in ("sub_block_idx", "true_block_idx",
                         "false_block_idx"):
                idx = op.attrs.get(attr)
                if isinstance(idx, int):
                    visit(idx)

    visit(blk_idx)
    return out


def union_nested_wids(program, blk_idxs):
    """Deduped union of nested_dynamic_wids over several blocks, in
    block order — THE ordering contract between an op's declared
    nested_while_ids attr, its NestedSteps outputs, and the executor's
    zip of the two. Every layer/op that wires nested trip counts goes
    through this one function."""
    wids = []
    for b in blk_idxs:
        for w in nested_dynamic_wids(program, b):
            if w not in wids:
                wids.append(w)
    return wids


def _collect_reports(ctx, trace_fn):
    """Run `trace_fn()` with a fresh nested-steps report dict in
    ctx.extra; returns (trace result, {wid: steps tracer}) reported by
    dynamic Whiles lowered inside it. The probe-and-replay WhileGrad
    measures NESTED loops this way: each level max-accumulates its
    children's per-iteration trip counts in its own carry (reference
    analog: while_op.cc:96 step scopes nest freely)."""
    extra = ctx.extra
    saved = extra.get("nested_steps_report")
    extra["nested_steps_report"] = {}
    try:
        result = trace_fn()
        rep = extra["nested_steps_report"]
    finally:
        extra["nested_steps_report"] = saved
    return result, rep


def _publish_report(ctx, entries):
    """Report {wid: steps} to an enclosing collector, if any."""
    rep = ctx.extra.get("nested_steps_report")
    if rep is not None:
        rep.update(entries)


def _zero_steps():
    return jnp.zeros((), jnp.int32)


@register_op_CF("static_rnn")
def _static_rnn(ctx):
    """Scan over leading time axis of each step input."""
    xs = ctx.inputs("X")                 # each [T, ...]
    mem_init = ctx.inputs("MemInit")
    step_in = ctx.attr("step_in_names")
    mem_pre = ctx.attr("mem_pre_names")
    mem_new = ctx.attr("mem_new_names")
    out_names = ctx.attr("out_names")
    blk_idx = ctx.attr("sub_block_idx")
    outer = dict(ctx.env)
    nested = nested_dynamic_wids(ctx.extra["program"], blk_idx)

    def body(state, x_t):
        carry, maxes = state

        def trace():
            env = dict(outer)
            env.update(zip(mem_pre, carry))
            env.update(zip(step_in, x_t))
            return _trace_sub(ctx, blk_idx, env)

        env, rep = _collect_reports(ctx, trace)
        maxes = tuple(jnp.maximum(m, rep.get(w, _zero_steps()))
                      for w, m in zip(nested, maxes))
        new_carry = tuple(env[n] for n in mem_new)
        outs = tuple(env[n] for n in out_names)
        return (new_carry, maxes), outs

    state0 = (tuple(mem_init), tuple(_zero_steps() for _ in nested))
    (_, maxes), stacked = jax.lax.scan(body, state0, tuple(xs))
    ctx.set_outputs("Out", list(stacked))
    ctx.set_outputs("NestedSteps", list(maxes))
    _publish_report(ctx, dict(zip(nested, maxes)))


@register_op_CF("while")
def _while(ctx):
    """While loop. Two lowerings:

    - default: lax.while_loop — dynamic trip count, minimal compute,
      but NOT reverse-differentiable (XLA has no rule for it);
    - with a positive `max_steps` attr: a bounded lax.scan that runs
      max_steps iterations with an active mask (finished state passes
      through) — same result for loops that terminate within the bound,
      and fully differentiable, the TPU-native WhileGrad
      (reference: while_op.cc:96 step-scope replay)."""
    cond_name = ctx.attr("cond_name")
    carried = ctx.attr("carried_names")
    blk_idx = ctx.attr("sub_block_idx")
    max_steps = int(ctx.attr("max_steps", 0) or 0)
    # Unbounded loop under the executor's probe-and-replay WhileGrad:
    # the executor measured this loop's trip count with a forward probe
    # and injects a (bucketed) static bound — the loop then lowers to
    # the differentiable masked scan instead of lax.while_loop
    # (reference analog: while_op.cc:96 step-scope replay).
    if max_steps <= 0:
        bounds = (ctx.extra or {}).get("while_bounds") or {}
        wid = ctx.attr("while_id")
        if wid in bounds:
            max_steps = int(bounds[wid])
    outer = dict(ctx.env)
    cond0 = ctx.input("Cond")
    init = tuple(outer[n] for n in carried)
    wid = ctx.attr("while_id")
    # dynamic Whiles nested anywhere below: their per-iteration trip
    # counts are max-accumulated through this loop's carry so the
    # executor's probe can read one static bound per nesting level
    nested = nested_dynamic_wids(ctx.extra["program"], blk_idx)

    def body_env(vals):
        env = dict(outer)
        env.update(zip(carried, vals))
        env = _trace_sub(ctx, blk_idx, env)
        return (env[cond_name].reshape(()).astype(jnp.bool_),
                tuple(env[n] for n in carried))

    def body_with_reports(vals, maxes):
        (new_cond, new_vals), rep = _collect_reports(
            ctx, lambda: body_env(vals))
        new_maxes = tuple(jnp.maximum(m, rep.get(w, _zero_steps()))
                          for w, m in zip(nested, maxes))
        return new_cond, new_vals, new_maxes

    maxes0 = tuple(_zero_steps() for _ in nested)

    if max_steps > 0:
        def scan_body(state, _):
            active, count, maxes, vals = state
            new_cond, new_vals, new_maxes = body_with_reports(vals, maxes)
            # carries may be pytrees (e.g. RaggedPair): select per leaf
            kept = tuple(
                jax.tree_util.tree_map(
                    lambda a, b: jnp.where(active, a, b), n, o)
                for n, o in zip(new_vals, vals))
            new_maxes = tuple(jnp.where(active, nm, m)
                              for nm, m in zip(new_maxes, maxes))
            count = count + active.astype(jnp.int32)
            return (active & new_cond, count, new_maxes, kept), None

        state0 = (cond0.reshape(()).astype(jnp.bool_),
                  jnp.zeros((), jnp.int32), maxes0, init)
        (still_active, count, maxes, final_vals), _ = jax.lax.scan(
            scan_body, state0, None, length=max_steps)
        ctx.set_outputs("Out", list(final_vals))
        # still true after max_steps iterations => the loop was truncated
        # (silent-truncation hazard of the bounded lowering); surfaced as
        # an optional output the layer wires to `<name>.exhausted`
        ctx.set_output("Exhausted", still_active)
        ctx.set_output("Steps", count)
        ctx.set_outputs("NestedSteps", list(maxes))
        _publish_report(ctx, dict(zip(nested, maxes)))
        return

    def cond_fn(state):
        return state[0].reshape(())

    def body_fn(state):
        maxes = state[2:2 + len(nested)]
        new_cond, new_vals, new_maxes = body_with_reports(
            state[2 + len(nested):], maxes)
        return (new_cond, state[1] + 1) + new_maxes + new_vals

    final = jax.lax.while_loop(
        cond_fn, body_fn,
        (cond0.reshape(()).astype(jnp.bool_), jnp.zeros((), jnp.int32))
        + maxes0 + init)
    steps = final[1]
    maxes = final[2:2 + len(nested)]
    ctx.set_outputs("Out", list(final[2 + len(nested):]))
    ctx.set_output("Steps", steps)
    ctx.set_outputs("NestedSteps", list(maxes))
    # visible to an enclosing collector: own trip count + children's
    _publish_report(ctx, {wid: steps, **dict(zip(nested, maxes))})


@register_op_CF("cond")
def _cond(ctx):
    pred = ctx.input("Pred")
    outer = dict(ctx.env)
    prog = ctx.extra["program"]
    tb = ctx.attr("true_block_idx")
    fb = ctx.attr("false_block_idx")
    # dynamic Whiles inside either branch report their trip counts as
    # extra lax.cond outputs — a tracer may not leak from a branch
    # trace into an enclosing collector directly (the untaken branch
    # contributes zeros, which can only under-report; the probe only
    # needs counts for what actually EXECUTED)
    wids = ctx.attr("nested_while_ids", None)
    if wids is None:   # op built without the layer: same union, same order
        wids = union_nested_wids(prog, (tb, fb))

    def make_branch(blk_idx, out_name):
        def branch(_):
            env, rep = _collect_reports(
                ctx, lambda: _trace_sub(ctx, blk_idx, dict(outer)))
            return (env[out_name],) + tuple(
                rep.get(w, _zero_steps()) for w in wids)
        return branch

    res = jax.lax.cond(pred.reshape(()).astype(jnp.bool_),
                       make_branch(tb, ctx.attr("true_out")),
                       make_branch(fb, ctx.attr("false_out")),
                       operand=None)
    ctx.set_output("Out", res[0])
    ctx.set_outputs("NestedSteps", list(res[1:]))
    _publish_report(ctx, dict(zip(wids, res[1:])))


# -- tensor arrays (dense fixed-capacity form) ------------------------------

@register_op_CF("array_write", no_grad_slots=["I"])
def _array_write(ctx):
    x = ctx.input("X")
    i = ctx.input("I").reshape(()).astype(jnp.int32)
    arr = ctx.input("Array")
    if arr is None:
        cap = ctx.attr("capacity", 128)
        arr = jnp.zeros((cap,) + tuple(x.shape), x.dtype)
    out = jax.lax.dynamic_update_index_in_dim(arr, x, i, 0)
    ctx.set_output("Out", out)


@register_op_CF("array_read", no_grad_slots=["I"])
def _array_read(ctx):
    arr = ctx.input("Array")
    i = ctx.input("I").reshape(()).astype(jnp.int32)
    ctx.set_output("Out", jax.lax.dynamic_index_in_dim(arr, i, 0,
                                                       keepdims=False))


@register_op_CF("array_length", no_grad_slots=["Array"])
def _array_length(ctx):
    arr = ctx.input("Array")
    ctx.set_output("Out", jnp.asarray(arr.shape[0], jnp.int64))


@register_op_CF("dynamic_rnn")
def _dynamic_rnn(ctx):
    """Ragged-batch RNN (reference: DynamicRNN control_flow.py:1354 +
    lod_rank_table/shrink_rnn_memory machinery). The reference shrinks
    the live batch as short sequences finish; here the batch stays dense
    [B, T, ...] and finished rows simply freeze their memory (masked
    carry) — the TPU-native equivalent of shrink_rnn_memory. Outputs are
    ragged (zero-masked past each row's length).

    Contract (as in the reference, which rejects mismatched LoD): all
    ragged step inputs share one set of lengths; the FIRST input's
    lengths drive the masking. Mismatched lengths cannot be detected
    inside the traced program and silently follow the first input."""
    from ..core.lod import RaggedPair

    xs_in = ctx.inputs("X")              # ragged step inputs
    mem_init = ctx.inputs("MemInit")
    step_in = ctx.attr("step_in_names")
    mem_pre = ctx.attr("mem_pre_names")
    mem_new = ctx.attr("mem_new_names")
    out_names = ctx.attr("out_names")
    blk_idx = ctx.attr("sub_block_idx")
    outer = dict(ctx.env)

    rags = []
    for x in xs_in:
        if isinstance(x, RaggedPair):
            rags.append(x)
        else:
            rags.append(RaggedPair(
                x, jnp.full((x.shape[0],), x.shape[1], jnp.int32)))
    lengths = rags[0].lengths
    t_max = rags[0].data.shape[1]
    # time-major step data for scan
    xs_tm = tuple(jnp.moveaxis(r.data, 1, 0) for r in rags)

    nested = nested_dynamic_wids(ctx.extra["program"], blk_idx)

    def body(state, inp):
        carry, maxes = state
        t, x_t = inp
        active = (t < lengths)           # [B]

        def trace():
            env = dict(outer)
            env.update(zip(mem_pre, carry))
            env.update(zip(step_in, x_t))
            return _trace_sub(ctx, blk_idx, env)

        env, rep = _collect_reports(ctx, trace)
        maxes = tuple(jnp.maximum(m, rep.get(w, _zero_steps()))
                      for w, m in zip(nested, maxes))
        new_carry = []
        for old, name in zip(carry, mem_new):
            new = env[name]
            m = active.reshape((-1,) + (1,) * (new.ndim - 1))
            new_carry.append(jnp.where(m, new, old))
        outs = []
        for n in out_names:
            o = env[n]
            m = active.reshape((-1,) + (1,) * (o.ndim - 1))
            outs.append(jnp.where(m, o, jnp.zeros_like(o)))
        return (tuple(new_carry), maxes), tuple(outs)

    ts = jnp.arange(t_max, dtype=jnp.int32)
    state0 = (tuple(mem_init), tuple(_zero_steps() for _ in nested))
    (final_mems, maxes), stacked = jax.lax.scan(body, state0, (ts, xs_tm))
    outs = [RaggedPair(jnp.moveaxis(s, 0, 1), lengths) for s in stacked]
    ctx.set_outputs("Out", outs)
    ctx.set_outputs("LastMem", list(final_mems))
    ctx.set_outputs("NestedSteps", list(maxes))
    _publish_report(ctx, dict(zip(nested, maxes)))


@register_op_CF("if_else")
def _if_else(ctx):
    """Row-wise two-branch select (reference: IfElse control_flow.py:1252
    over split_lod_tensor/merge_lod_tensor). The reference routes each
    row to one branch's sub-executor; dense TPU form traces BOTH
    branches over the full batch and merges rows by the condition —
    compute is duplicated but stays one fused XLA program (the standard
    accelerator trade)."""
    cond = ctx.input("Cond")
    outer = dict(ctx.env)
    true_outs = ctx.attr("true_out_names")
    false_outs = ctx.attr("false_out_names")
    prog = ctx.extra["program"]
    tb = ctx.attr("true_block_idx")
    fb = ctx.attr("false_block_idx")
    wids = ctx.attr("nested_while_ids", None)
    if wids is None:
        wids = union_nested_wids(prog, (tb, fb))

    env_t, rep_t = _collect_reports(
        ctx, lambda: _trace_sub(ctx, tb, dict(outer)))
    env_f, rep_f = _collect_reports(
        ctx, lambda: _trace_sub(ctx, fb, dict(outer)))
    c = cond.reshape(-1).astype(jnp.bool_)
    merged = []
    for tn, fn in zip(true_outs, false_outs):
        tv, fv = env_t[tn], env_f[fn]
        m = c.reshape((-1,) + (1,) * (tv.ndim - 1))
        merged.append(jnp.where(m, tv, fv))
    ctx.set_outputs("Out", merged)
    # both branches execute in the dense lowering: report the max
    maxes = tuple(jnp.maximum(rep_t.get(w, _zero_steps()),
                              rep_f.get(w, _zero_steps()))
                  for w in wids)
    ctx.set_outputs("NestedSteps", list(maxes))
    _publish_report(ctx, dict(zip(wids, maxes)))


@register_op_CF("pipeline")
def _pipeline(ctx):
    """Program-level GPipe pipeline (layers/control_flow.py
    PipelinedStack). With a mesh carrying the pipe axis: microbatched
    pipeline_apply (ppermute activation hops inside one scan, stage
    params sharded stage-per-device). Without one: sequential stage
    composition — identical math and gradients, so single-device
    Executors and the ParallelExecutor run the same program."""
    x = ctx.input("X")
    params = ctx.inputs("StageParams")
    names = ctx.attr("param_names")
    n_stages = ctx.attr("n_stages")
    n_micro = ctx.attr("n_micro", 1)
    axis = ctx.attr("axis", "pipe")
    blk_idx = ctx.attr("sub_block_idx")
    sin = ctx.attr("stage_in_name")
    sout = ctx.attr("stage_out_name")
    outer = dict(ctx.env)

    def stage_fn(pdict, a):
        env = dict(outer)
        env.update(pdict)
        env[sin] = a
        env = _trace_sub(ctx, blk_idx, env)
        return env[sout]

    mesh = ctx.extra.get("mesh")
    if mesh is not None and axis in mesh.axis_names:
        if mesh.shape[axis] != n_stages:
            raise ValueError(
                f"pipeline has n_stages={n_stages} but mesh axis "
                f"{axis!r} spans {mesh.shape[axis]} devices")
        from ..parallel.pipeline import (merge_microbatches, pipeline_apply,
                                         split_microbatches)
        micro = split_microbatches(x, n_micro)
        stacked = dict(zip(names, params))
        # combined DP x PP: if the mesh also carries a 'data' axis, keep
        # the microbatch dim sharded over it (each DP row pipelines its
        # own batch shard; GSPMD reshards replicated feeds as needed)
        batch_axis = "data" if "data" in mesh.axis_names else None
        out = pipeline_apply(stage_fn, stacked, micro, axis=axis, mesh=mesh,
                             batch_axis=batch_axis)
        out = merge_microbatches(out)
    else:
        a = x
        for i in range(n_stages):
            a = stage_fn({n: p[i] for n, p in zip(names, params)}, a)
        out = a
    ctx.set_output("Out", out)


@register_op_CF("go", stateful=True)
def _go(ctx):
    """In-graph go: launch the sub-block on a host thread when this op
    executes (reference: go_op.cc:29 — ExecuteOnThread of the sub-block
    against a child scope). Captured inputs are snapshotted through an
    ordered io_callback at the op's program position, then the body ops
    run EAGERLY (concrete jax values) on the spawned thread — so its
    channel ops interoperate with the program's own io_callback channel
    sends/recvs and with host concurrency.Channel users. Fire and
    forget: no outputs flow back (as in the reference)."""
    from ..concurrency import go as host_go
    from ..core.registry import run_op

    blk_idx = ctx.attr("sub_block_idx")
    captured = list(ctx.attr("captured_names", []) or [])
    vals = ctx.inputs("X") or []
    prog = ctx.extra["program"]
    block = prog.blocks[blk_idx]

    def _host_launch(*snap):
        import numpy as _np

        def body():
            env = {n: _np.asarray(v) for n, v in zip(captured, snap)}
            extra = {
                "program": prog,
                "step": jnp.zeros((), jnp.int32),
                "prng": lambda seed: jax.random.PRNGKey(seed),
            }
            for op in block.ops:
                env.update(run_op(op, env, extra))
        host_go(body)
        return _np.int32(1)

    status = jax.experimental.io_callback(
        _host_launch, jax.ShapeDtypeStruct((), jnp.int32), *vals,
        ordered=True)
    ctx.set_output("Status", status)


# ---------------------------------------------------------------------------
# Explicit shape-inference rules for the control-flow family.
#
# The generic build-time mechanism (framework.infer_op_outputs) abstractly
# evaluates an op's compute rule — but these ops trace their SUB-BLOCKS and
# need extra["program"] plus closure vars, so eval_shape cannot run them and
# they were the most common "no shape-inference coverage" gaps the static
# verifier found. The rules below derive output metadata structurally:
#
# - while:      Out re-writes already-declared parent carries; the
#               Exhausted/Steps/NestedSteps flags are scalars.
# - if_else:    Out[i] mirrors the true branch's i-th output var.
# - static_rnn: Out[i] = [T, *step_out_shape] (scan stacks the per-step
#               output over the leading time axis of X).
# - dynamic_rnn: Out[i] mirrors the sub-block step output (ragged,
#               lod_level 1); LastMem[i] mirrors the init memory.
#
# Rule contract (framework._infer_shapes): rule(block_desc, op) -> dict
# {name: {"shape", "dtype", "lod_level"}} filling only what the builder
# left undeclared.

def _scalar_specs(op, slots_dtypes):
    specs = {}
    for slot, dtype in slots_dtypes:
        for n in op.output(slot):
            specs[n] = {"shape": [], "dtype": dtype, "lod_level": 0}
    return specs


def _sub_var(block_desc, blk_idx, name):
    prog = block_desc.program
    if not isinstance(blk_idx, int) or not 0 <= blk_idx < len(prog.blocks):
        return None
    return prog.blocks[blk_idx].find_var_recursive(name)


def _while_infer(block_desc, op):
    return _scalar_specs(op, [("Exhausted", "bool"), ("Steps", "int32"),
                              ("NestedSteps", "int32")])


def _if_else_infer(block_desc, op):
    specs = _scalar_specs(op, [("NestedSteps", "int32")])
    tb = op.attrs.get("true_block_idx")
    for out, tn in zip(op.output("Out"),
                       op.attrs.get("true_out_names") or []):
        tv = _sub_var(block_desc, tb, tn)
        if tv is not None and tv.shape is not None:
            specs[out] = {"shape": list(tv.shape), "dtype": tv.dtype,
                          "lod_level": tv.lod_level}
    return specs


def _static_rnn_infer(block_desc, op):
    specs = _scalar_specs(op, [("NestedSteps", "int32")])
    t_dim = -1
    for xn in op.input("X"):
        xv = block_desc.find_var_recursive(xn)
        if xv is not None and xv.shape:
            t_dim = xv.shape[0]
            break
    blk_idx = op.attrs.get("sub_block_idx")
    for out, sn in zip(op.output("Out"),
                       op.attrs.get("out_names") or []):
        sv = _sub_var(block_desc, blk_idx, sn)
        if sv is not None and sv.shape is not None:
            specs[out] = {"shape": [t_dim] + list(sv.shape),
                          "dtype": sv.dtype, "lod_level": 0}
    return specs


def _dynamic_rnn_infer(block_desc, op):
    specs = _scalar_specs(op, [("NestedSteps", "int32")])
    blk_idx = op.attrs.get("sub_block_idx")
    for out, sn in zip(op.output("Out"),
                       op.attrs.get("out_names") or []):
        sv = _sub_var(block_desc, blk_idx, sn)
        if sv is not None and sv.shape is not None:
            specs[out] = {"shape": list(sv.shape), "dtype": sv.dtype,
                          "lod_level": 1}
    for out, mn in zip(op.output("LastMem"), op.input("MemInit")):
        mv = block_desc.find_var_recursive(mn)
        if mv is not None and mv.shape is not None:
            specs[out] = {"shape": list(mv.shape), "dtype": mv.dtype,
                          "lod_level": 0}
    return specs


for _t, _rule in (("while", _while_infer), ("if_else", _if_else_infer),
                  ("static_rnn", _static_rnn_infer),
                  ("dynamic_rnn", _dynamic_rnn_infer)):
    OpRegistry.get(_t).infer_shape = _rule
