"""Op library. Importing this package registers every op type.

TPU-native replacement for the reference op library
(paddle/fluid/operators/ — ~130 op types, see SURVEY.md N11-N14): each op
is a pure-JAX compute rule traced into the executor's XLA program.
"""
from . import core_ops  # noqa: F401
from . import math_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import crf_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import beam_search_ops  # noqa: F401

from ..core.registry import OpRegistry


def all_ops():
    return OpRegistry.all_ops()
from . import csp_ops  # noqa: F401
from . import reader_ops  # noqa: F401
from . import fusion_ops  # noqa: F401
from . import augment_ops  # noqa: F401
from . import cache_ops  # noqa: F401
