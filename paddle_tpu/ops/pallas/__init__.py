"""Pallas TPU kernels for hot ops.

The reference hand-writes CUDA for its fused hot ops (fused LSTM
paddle/cuda/src/hl_cuda_lstm.cu, top-k cuda/src/hl_top_k.cu, attention-era
compositions in nets.py). The TPU-native analogue is a small library of
Pallas kernels; everything else rides XLA fusion.

All kernels run in interpret mode on CPU (tests) and compiled on TPU.
"""
from .flash_attention import flash_attention

__all__ = ["flash_attention"]
