"""Pallas TPU kernels for hot ops.

The reference hand-writes CUDA for its fused hot ops (fused LSTM
paddle/cuda/src/hl_cuda_lstm.cu, top-k cuda/src/hl_top_k.cu, attention-era
compositions in nets.py). The TPU-native analogue is a small library of
Pallas kernels; everything else rides XLA fusion.

All kernels run in interpret mode on CPU (tests) and compiled on TPU.
"""
import os

import jax


def interpret_default() -> bool:
    """Interpret kernels off-TPU (tests); compile on real hardware."""
    return jax.default_backend() != "tpu"


def pallas_dispatch(knob_env: str, default: str, attr=None):
    """Shared policy for op-level kernel dispatch: returns
    (enabled, interpret). "1" enables on TPU only, "force" enables
    anywhere via interpret mode (test coverage), "0" disables.

    ``attr`` is a program-level override stamped onto the op by the
    rewrite layer's kernel_dispatch pass (analysis/rewrite.py): when
    present it replaces the env read, making the dispatch decision part
    of the IR instead of trace-time environment sniffing.
    """
    knob = attr if attr is not None else os.environ.get(knob_env, default)
    if knob == "force":
        return True, None          # None -> interpret_default() inside
    return (knob == "1" and jax.default_backend() == "tpu"), False


from .flash_attention import flash_attention  # noqa: E402

__all__ = ["flash_attention", "interpret_default", "pallas_dispatch"]
