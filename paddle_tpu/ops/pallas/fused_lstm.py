"""Fused LSTM time loop as Pallas TPU kernels (forward + backward).

The reference hand-fuses its LSTM hot loop in CUDA
(paddle/cuda/src/hl_cuda_lstm.cu; used by lstm_op's batched compute).
This is the TPU-native equivalent: one kernel runs ALL timesteps with the
recurrent state (h, c) resident in VMEM scratch and the recurrent weight
streamed once, so the per-step HBM traffic is just x_t in / h_t out —
instead of a lax.scan whose every step round-trips state through HBM.

Layout (matches ops/sequence_ops.py _lstm):
  x   [T, B, 4H]  pre-projected gates, time-major; gate order i,c_hat,f,o
  w   [H, 4H]     recurrent weights
  b   [4H]        gate bias (already includes any projection bias)
  h0, c0 [B, H]
  lengths [B]     ragged mask: rows freeze past their length and masked
                  outputs are zero, identical to _masked_scan_rnn.

Backward is a second kernel walking t in reverse, recomputing gate
activations from (x_t, h_{t-1}) — flash-style recompute, so only h_all
and c_all are saved, not the [T, B, 4H] gates.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from . import interpret_default as _interpret_default  # shared policy


def _fwd_kernel(len_ref, x_ref, w_ref, b_ref, h0_ref, c0_ref,
                h_all_ref, c_all_ref, h_scr, c_scr, *, hidden):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    h_prev = h_scr[...]
    c_prev = c_scr[...]
    gates = x_ref[0].astype(jnp.float32) + \
        jax.lax.dot_general(h_prev, w_ref[...].astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) + \
        b_ref[...].astype(jnp.float32)              # b: [1, 4H]
    i = jax.nn.sigmoid(gates[:, :hidden])
    cand = jnp.tanh(gates[:, hidden:2 * hidden])
    f = jax.nn.sigmoid(gates[:, 2 * hidden:3 * hidden])
    o = jax.nn.sigmoid(gates[:, 3 * hidden:])
    c_new = f * c_prev + i * cand
    h_new = o * jnp.tanh(c_new)

    alive = t < len_ref[...]                     # [B, 1]
    c_scr[...] = jnp.where(alive, c_new, c_prev)
    h_scr[...] = jnp.where(alive, h_new, h_prev)
    zeros = jnp.zeros_like(h_new)
    h_all_ref[0] = jnp.where(alive, h_new, zeros).astype(h_all_ref.dtype)
    c_all_ref[0] = jnp.where(alive, c_new, zeros).astype(c_all_ref.dtype)


def _bwd_kernel(len_ref, x_ref, w_ref, b_ref, h0_ref, c0_ref,
                h_all_ref, c_all_ref, dh_out_ref, dc_out_ref,
                dx_ref, dw_ref, db_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr, *, hidden, t_max):
    # dw/db accumulate IN their fp32 output buffers (constant block
    # mapping + sequential grid) instead of separate VMEM scratch — the
    # extra [H, 4H] scratch copy pushed large shapes over the 16MB
    # scoped-vmem limit (b64 h512 t64 in an 8-layer stack).
    k = pl.program_id(0)
    t = t_max - 1 - k

    @pl.when(k == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dc_scr[...] = jnp.zeros_like(dc_scr)
        dw_ref[...] = jnp.zeros_like(dw_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    # previous-step state: h_all/c_all blocks are indexed at t-1 via the
    # BlockSpec (clamped at 0); substitute h0/c0 when t == 0
    use_init = (t == 0)
    h_prev = jnp.where(use_init, h0_ref[...].astype(jnp.float32),
                       h_all_ref[0].astype(jnp.float32))
    c_prev = jnp.where(use_init, c0_ref[...].astype(jnp.float32),
                       c_all_ref[0].astype(jnp.float32))

    gates = x_ref[0].astype(jnp.float32) + \
        jax.lax.dot_general(h_prev, w_ref[...].astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) + \
        b_ref[...].astype(jnp.float32)
    i = jax.nn.sigmoid(gates[:, :hidden])
    cand = jnp.tanh(gates[:, hidden:2 * hidden])
    f = jax.nn.sigmoid(gates[:, 2 * hidden:3 * hidden])
    o = jax.nn.sigmoid(gates[:, 3 * hidden:])
    c = f * c_prev + i * cand
    tc = jnp.tanh(c)

    alive = t < len_ref[...]                     # [B, 1]
    dh = dh_out_ref[0].astype(jnp.float32) + dh_scr[...]
    dh = jnp.where(alive, dh, jnp.zeros_like(dh))
    dc = dh * o * (1.0 - tc * tc) + dc_scr[...] + \
        dc_out_ref[0].astype(jnp.float32)
    dc = jnp.where(alive, dc, dc_scr[...])

    do_pre = jnp.where(alive, dh * tc * o * (1.0 - o), 0.0)
    df_pre = jnp.where(alive, dc * c_prev * f * (1.0 - f), 0.0)
    di_pre = jnp.where(alive, dc * cand * i * (1.0 - i), 0.0)
    dch_pre = jnp.where(alive, dc * i * (1.0 - cand * cand), 0.0)
    dgates = jnp.concatenate([di_pre, dch_pre, df_pre, do_pre], axis=1)

    dx_ref[0] = dgates.astype(dx_ref.dtype)
    dw_ref[...] += jax.lax.dot_general(
        h_prev, dgates, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_ref[...] += jnp.sum(dgates, axis=0, keepdims=True)

    dh_prev = jax.lax.dot_general(
        dgates, w_ref[...].astype(jnp.float32),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    # frozen rows pass their carries through untouched
    dh_scr[...] = jnp.where(alive, dh_prev, dh_scr[...])
    dc_scr[...] = jnp.where(alive, dc * f, dc_scr[...])

    @pl.when(k == t_max - 1)
    def _final():
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_scr[...].astype(dc0_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def fused_lstm(x, w, b, h0, c0, lengths, interpret=None):
    """[T, B, 4H] pre-projected gates -> (h_all [T, B, H], c_all,
    h_last [B, H], c_last)."""
    out = _fused_lstm_fwd(x, w, b, h0, c0, lengths, interpret)
    return out[0]


def _run_fwd(x, w, b, h0, c0, lengths, interpret):
    if interpret is None:
        interpret = _interpret_default()
    t_max, bsz, g4 = x.shape
    hidden = g4 // 4
    kernel = functools.partial(_fwd_kernel, hidden=hidden)
    h_all, c_all = pl.pallas_call(
        kernel,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((bsz, 1), lambda t: (0, 0)),          # lengths
            pl.BlockSpec((1, bsz, g4), lambda t: (t, 0, 0)),   # x_t
            pl.BlockSpec((hidden, g4), lambda t: (0, 0)),      # w
            pl.BlockSpec((1, g4), lambda t: (0, 0)),           # b
            pl.BlockSpec((bsz, hidden), lambda t: (0, 0)),     # h0
            pl.BlockSpec((bsz, hidden), lambda t: (0, 0)),     # c0
        ],
        out_specs=[
            pl.BlockSpec((1, bsz, hidden), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, bsz, hidden), lambda t: (t, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_max, bsz, hidden), x.dtype),
            jax.ShapeDtypeStruct((t_max, bsz, hidden), x.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bsz, hidden), jnp.float32),
                        pltpu.VMEM((bsz, hidden), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(lengths.astype(jnp.int32).reshape(bsz, 1), x, w,
      b.reshape(1, g4), h0, c0)
    # last valid state per row; zero-length rows keep their initial
    # state (scan-path semantics)
    lens32 = lengths.astype(jnp.int32)
    idx = jnp.maximum(lens32 - 1, 0)
    h_last = jnp.take_along_axis(
        jnp.moveaxis(h_all, 0, 1), idx[:, None, None], axis=1)[:, 0]
    c_last = jnp.take_along_axis(
        jnp.moveaxis(c_all, 0, 1), idx[:, None, None], axis=1)[:, 0]
    empty = (lens32 == 0)[:, None]
    h_last = jnp.where(empty, h0.astype(h_last.dtype), h_last)
    c_last = jnp.where(empty, c0.astype(c_last.dtype), c_last)
    return (h_all, c_all, h_last, c_last)


def _fused_lstm_fwd(x, w, b, h0, c0, lengths, interpret):
    outs = _run_fwd(x, w, b, h0, c0, lengths, interpret)
    h_all, c_all, _, _ = outs
    return outs, (x, w, b, h0, c0, lengths, h_all, c_all)


def _fused_lstm_bwd(interpret, res, grads):
    x, w, b, h0, c0, lengths, h_all, c_all = res
    dh_all, dc_all, dh_last, dc_last = grads
    if interpret is None:
        interpret = _interpret_default()
    t_max, bsz, g4 = x.shape
    hidden = g4 // 4
    # fold the h_last/c_last cotangents back into the per-step streams
    idx = jnp.maximum(lengths.astype(jnp.int32) - 1, 0)
    dh_all = jnp.moveaxis(jnp.moveaxis(dh_all, 0, 1).at[
        jnp.arange(bsz), idx].add(dh_last), 1, 0)
    dc_all = jnp.moveaxis(jnp.moveaxis(dc_all, 0, 1).at[
        jnp.arange(bsz), idx].add(dc_last), 1, 0)

    kernel = functools.partial(_bwd_kernel, hidden=hidden, t_max=t_max)
    dx, dw, db, dh0, dc0 = pl.pallas_call(
        kernel,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((bsz, 1), lambda k: (0, 0)),
            pl.BlockSpec((1, bsz, g4), lambda k: (t_max - 1 - k, 0, 0)),
            pl.BlockSpec((hidden, g4), lambda k: (0, 0)),
            pl.BlockSpec((1, g4), lambda k: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda k: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda k: (0, 0)),
            # h_all/c_all indexed at t-1 (clamped to 0; t==0 substitutes
            # h0/c0 inside the kernel)
            pl.BlockSpec((1, bsz, hidden),
                         lambda k: (jnp.maximum(t_max - 2 - k, 0), 0, 0)),
            pl.BlockSpec((1, bsz, hidden),
                         lambda k: (jnp.maximum(t_max - 2 - k, 0), 0, 0)),
            pl.BlockSpec((1, bsz, hidden),
                         lambda k: (t_max - 1 - k, 0, 0)),
            pl.BlockSpec((1, bsz, hidden),
                         lambda k: (t_max - 1 - k, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bsz, g4), lambda k: (t_max - 1 - k, 0, 0)),
            pl.BlockSpec((hidden, g4), lambda k: (0, 0)),
            pl.BlockSpec((1, g4), lambda k: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda k: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda k: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_max, bsz, g4), x.dtype),
            # fp32 accumulators (cast to param dtype after the call) —
            # accumulating 4H-wide sums in bf16 would lose precision
            jax.ShapeDtypeStruct((hidden, g4), jnp.float32),
            jax.ShapeDtypeStruct((1, g4), jnp.float32),
            jax.ShapeDtypeStruct((bsz, hidden), h0.dtype),
            jax.ShapeDtypeStruct((bsz, hidden), c0.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bsz, hidden), jnp.float32),
                        pltpu.VMEM((bsz, hidden), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(lengths.astype(jnp.int32).reshape(bsz, 1), x, w,
      b.reshape(1, g4), h0, c0, h_all, c_all, dh_all, dc_all)
    return dx, dw.astype(w.dtype), db.reshape(g4).astype(b.dtype), \
        dh0, dc0, None


fused_lstm.defvjp(_fused_lstm_fwd, _fused_lstm_bwd)
