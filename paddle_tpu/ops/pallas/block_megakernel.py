"""Batch-tiled cross-layer bottleneck megakernel (round-4 campaign).

The round-3 roofline analysis (MFU_BREAKDOWN.md) showed the ResNet-50
train step pinned to the HBM roofline at ~40 GB/step vs a ~16 GB hand
ideal: every conv boundary writes its activation to HBM and the next
conv reads it back. Whole-block fusion was ruled out there because a
STAGE-wide activation (51-205 MB) cannot sit in VMEM — but that sizing
assumed whole-batch tiles. This kernel grids over the BATCH instead:
a tile of `tile` images' activations for one bottleneck block
(1x1 -> BN/relu -> 3x3 -> BN/relu -> 1x1 -> BN -> +residual -> relu)
lives entirely in VMEM (~10 MB at stage-2 shapes, tile=2), the block's
weights stay VMEM-resident across the sequential grid (constant-index
blocks are not refetched), and the only HBM traffic is x in, y out —
the hand-ideal byte count.

Spatial structure inside the flat [tile*H*W, C] layout: the 3x3 is
nine shifted matmuls; a tap (dy,dx) is a whole-array row rotation by
dy*W+dx (pltpu.roll on the f32 activation — Mosaic's rotate needs
32-bit data, the same constraint fused_conv.py hit) masked by the
per-pixel validity of (h+dy, w+dx). Rows that rotate across an image
boundary are exactly the rows the validity mask zeroes, so no halo
DMA and no pixel-pair geometry — the two things that made round 3's
spatially-tiled 3x3 ~5x slower than XLA's conv.

BatchNorm inside a batch tile is GHOST BN: statistics over the tile's
`tile*H*W` samples rather than the full batch (the standard ghost-BN
regularizer, here with ghost size = tile images). This is what makes
cross-layer fusion possible at all — full-batch stats would need a
cross-program barrier between every conv. Training-semantics parity is
a measured question (tests/test_block_megakernel.py convergence test),
not assumed.

Reference anchor: the hand-fusion precedent paddle/cuda/src/
hl_cuda_lstm.cu (reference optimizes ITS hot path with hand-fused
kernels; this is the TPU-shaped analog for the conv hot path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_default

EPS = 1e-5


def _ghost_coefs(h, p_ref, eps):
    """(a, b) [1, C] f32 from ghost stats of f32 [M, C]."""
    m = h.shape[0]
    mean = jnp.sum(h, axis=0, keepdims=True) / m
    var = jnp.sum(h * h, axis=0, keepdims=True) / m - mean * mean
    a = p_ref[0:1, :] * jax.lax.rsqrt(var + eps)
    return a, p_ref[1:2, :] - mean * a


def _bottleneck_kernel(x_ref, w1_ref, w3_ref, w2_ref, p1_ref, p2_ref,
                       p3_ref, out_ref, *, h_img, w_img, tile, eps):
    """VPU-lean variant (the first cut measured VPU-bound at 39% MXU,
    ~parity with XLA): BN1's affine+relu fuses into the tap masking
    pass (affine is per-lane, so it commutes with row rotation), the
    nine taps collapse into three K=3*Cm dots (one per dy), and the
    validity masks are built once from a single iota."""
    hw = h_img * w_img
    m = tile * hw
    x = x_ref[:]                                        # bf16 [M, Cin]
    cm = w1_ref.shape[1]
    dt = x_ref.dtype

    acc1 = jnp.dot(x, w1_ref[:], preferred_element_type=jnp.float32)
    a1, b1 = _ghost_coefs(acc1, p1_ref, eps)            # [1, Cm]
    a1t = jnp.concatenate([a1, a1, a1], axis=1)         # [1, 3Cm]
    b1t = jnp.concatenate([b1, b1, b1], axis=1)

    row = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
    p_local = row % hw
    hh = p_local // w_img
    ww = p_local % w_img
    w_ok = [ww - 1 >= 0, row >= 0, ww + 1 < w_img]      # dx = -1, 0, 1

    # w3_ref is tap-major [9, Cm, Cm], t = (dy+1)*3 + (dx+1); a dy-trio
    # reshapes to the [3Cm, Cm] right operand of one MXU dot
    acc2 = jnp.zeros((m, cm), jnp.float32)
    for dy in (-1, 0, 1):
        base = pltpu.roll(acc1, (-dy * w_img) % m, 0) if dy else acc1
        h_ok = (hh + dy >= 0) & (hh + dy < h_img)
        trio = jnp.concatenate(
            [base if dx == 0 else pltpu.roll(base, (-dx) % m, 0)
             for dx in (-1, 0, 1)], axis=1)             # [M, 3Cm]
        mask = jnp.concatenate(
            [jnp.broadcast_to(h_ok & wk, (m, cm)) for wk in w_ok],
            axis=1)
        # fused: BN1 affine + relu + boundary mask + bf16 cast
        tap = jnp.where(mask,
                        jnp.maximum(trio * a1t + b1t, 0.0), 0.0)
        wt = w3_ref[(dy + 1) * 3:(dy + 1) * 3 + 3].reshape(3 * cm, cm)
        acc2 = acc2 + jnp.dot(tap.astype(dt), wt,
                              preferred_element_type=jnp.float32)

    a2, b2 = _ghost_coefs(acc2, p2_ref, eps)
    h2 = jnp.maximum(acc2 * a2 + b2, 0.0).astype(dt)    # one fused pass

    acc3 = jnp.dot(h2, w2_ref[:], preferred_element_type=jnp.float32)
    a3, b3 = _ghost_coefs(acc3, p3_ref, eps)
    y = acc3 * a3 + b3 + x.astype(jnp.float32)
    out_ref[:] = jnp.maximum(y, 0.0).astype(out_ref.dtype)


def bottleneck_block(x, w1, w3, w2, bn1, bn2, bn3, h_img, w_img,
                     tile=2, eps=EPS, interpret=None):
    """Fused identity bottleneck block forward, ghost-BN training stats.

    x: [N, H*W, Cin] NHWC-flat bf16 (or f32 in interpret tests).
    w1 [Cin, Cm], w3 [9, Cm, Cm] (tap-major: t = (dy+1)*3 + dx+1),
    w2 [Cm, Cin]; bn1/bn2/bn3: [2, C] f32 rows (gamma, beta).
    Returns y [N, H*W, Cin] in x.dtype.
    """
    if interpret is None:
        interpret = interpret_default()
    n, hw, cin = x.shape
    assert hw == h_img * w_img, (hw, h_img, w_img)
    cm = w1.shape[1]
    assert n % tile == 0, (n, tile)
    assert cin % 128 == 0 and cm % 128 == 0, \
        "stage-1 (Cm=64) needs lane packing — not built; see fused_conv"
    m = tile * hw
    xf = x.reshape(n * hw, cin)
    kern = functools.partial(_bottleneck_kernel, h_img=h_img,
                             w_img=w_img, tile=tile, eps=eps)
    flops = 2 * n * hw * cm * (cin + 9 * cm + cin)
    out = pl.pallas_call(
        kern,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec((m, cin), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cin, cm), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((9, cm, cm), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((cm, cin), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, cm), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, cm), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, cin), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, cin), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n * hw, cin), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=2 * x.size * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(xf, w1, w3, w2,
      jnp.asarray(bn1, jnp.float32), jnp.asarray(bn2, jnp.float32),
      jnp.asarray(bn3, jnp.float32))
    return out.reshape(n, hw, cin)


def bottleneck_block_reference(x, w1, w3, w2, bn1, bn2, bn3, h_img,
                               w_img, tile=2, eps=EPS):
    """jnp oracle with IDENTICAL ghost-BN semantics (stats per
    tile-of-images group), for correctness tests and as the XLA-side
    arm of the same-semantics perf A/B."""
    n, hw, cin = x.shape
    cm = w1.shape[1]

    def ghost_bn(h, p, relu):
        # h [G, M, C] f32, stats over axis 1 within each group
        mean = h.mean(axis=1, keepdims=True)
        var = (h * h).mean(axis=1, keepdims=True) - mean * mean
        a = p[0] * jax.lax.rsqrt(var + eps)
        b = p[1] - mean * a
        y = h * a + b
        return jnp.maximum(y, 0.0) if relu else y

    g = n // tile
    xg = x.reshape(g, tile * hw, cin)
    h1 = ghost_bn(jnp.einsum("gmk,kn->gmn", xg, w1,
                             preferred_element_type=jnp.float32),
                  jnp.asarray(bn1, jnp.float32), True)
    img = h1.reshape(g * tile, h_img, w_img, cm)
    padded = jnp.pad(img, ((0, 0), (1, 1), (1, 1), (0, 0)))
    acc = jnp.zeros((g * tile, h_img, w_img, cm), jnp.float32)
    for t in range(9):
        dy, dx = t // 3, t % 3
        tap = padded[:, dy:dy + h_img, dx:dx + w_img, :]
        acc = acc + jnp.einsum(
            "bhwk,kn->bhwn", tap.astype(x.dtype), w3[t],
            preferred_element_type=jnp.float32)
    h2 = ghost_bn(acc.reshape(g, tile * hw, cm),
                  jnp.asarray(bn2, jnp.float32), True)
    y = ghost_bn(jnp.einsum("gmk,kn->gmn", h2.astype(x.dtype), w2,
                            preferred_element_type=jnp.float32),
                 jnp.asarray(bn3, jnp.float32), False)
    y = y + xg.astype(jnp.float32)
    return jnp.maximum(y, 0.0).astype(x.dtype).reshape(n, hw, cin)
