"""Pallas fused conv+BN kernels for the ResNet hot path.

The reference answers conv+BN cost with vendor-fused kernels
(reference: paddle/fluid/operators/conv_cudnn_op.cu.cc:1); the TPU-native
answer is Pallas kernels that fold BatchNorm's activation sweeps into the
convolutions that already touch the data:

- the conv kernel's EPILOGUE accumulates per-channel sum / sum-of-squares
  of its raw f32 accumulator output (BN statistics for free — the XLA
  path re-reads the conv output from HBM for them);
- the NEXT conv kernel's PROLOGUE applies the producer BN's per-channel
  affine (y = x*a + b) and ReLU while the input tile is in VMEM (the XLA
  path materializes the normalized activation as its own HBM pass).

Net effect: each activation buffer is written once (raw conv output) and
read once (next conv's input) — BN costs no extra HBM sweeps. Internal
layout is NHWC-flat ([N*H*W, C] row-major), the MXU-native shape for a
1x1 conv (a plain matmul) and for 3x3 as nine shifted matmuls.

All kernels run under interpret mode on CPU for tests (see
tests/test_fused_conv.py) and compiled on TPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import interpret_default


def _pick_block_m(m: int, vmem_budget_rows: int = 1024) -> int:
    """Largest divisor of m that is a multiple of 16 (bf16 sublane tile)
    and <= the row budget."""
    for cand in range(min(vmem_budget_rows, m), 15, -16):
        if m % cand == 0:
            return cand
    return m  # last resort: single block (m itself)


def _conv1x1_kernel(x_ref, w_ref, a_ref, b_ref, out_ref, stats_ref,
                    *, relu, stats, affine, out_dtype):
    """One [BM, K] x [K, N] tile: optional input affine+relu prologue,
    matmul, optional stats epilogue accumulated across the M grid."""
    x = x_ref[:]
    if affine:
        xf = x.astype(jnp.float32) * a_ref[:] + b_ref[:]
        if relu:
            xf = jnp.maximum(xf, 0.0)
        x = xf.astype(x_ref.dtype)
    elif relu:
        x = jnp.maximum(x, 0)
    out = jnp.dot(x, w_ref[:], preferred_element_type=jnp.float32)
    out_ref[:] = out.astype(out_dtype)
    if stats:
        @pl.when(pl.program_id(0) == 0)
        def _init():
            stats_ref[:] = jnp.zeros_like(stats_ref)
        stats_ref[0, :] += jnp.sum(out, axis=0)
        stats_ref[1, :] += jnp.sum(out * out, axis=0)


def conv1x1_bn_act(x, w, a=None, b=None, relu=False, stats=True,
                   block_m=None, interpret=None):
    """Fused pointwise conv on NHWC-flat input.

    x: [M, K] (M = N*H*W rows, K input channels), any float dtype.
    w: [K, N] weights.
    a, b: optional per-input-channel affine coefficients [K] f32 — the
        PRODUCER BatchNorm's normalize (a = scale*rsqrt(var+eps),
        b = bias - mean*a), applied (then ReLU if relu=True) to x in
        the prologue.
    Returns (out [M, N] in x.dtype, stats [2, N] f32) where stats rows
    are (sum, sum_of_squares) of the f32 conv output over M — exactly
    what the CONSUMER BatchNorm needs; stats is None if stats=False.
    """
    if interpret is None:
        interpret = interpret_default()
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    affine = a is not None
    if affine:
        a = jnp.asarray(a, jnp.float32).reshape(1, k)
        b = jnp.asarray(b, jnp.float32).reshape(1, k)
    else:
        # dummy tiny operands keep the kernel signature static
        a = jnp.zeros((1, 1), jnp.float32)
        b = jnp.zeros((1, 1), jnp.float32)
    bm = block_m or _pick_block_m(m)
    grid = (m // bm,)
    kernel = functools.partial(
        _conv1x1_kernel, relu=relu, stats=stats, affine=affine,
        out_dtype=x.dtype)
    out_shapes = [jax.ShapeDtypeStruct((m, n), x.dtype),
                  jax.ShapeDtypeStruct((2, n), jnp.float32)]
    out_specs = [
        pl.BlockSpec((bm, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((2, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out, stats_out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(a.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n,
            bytes_accessed=(m * k + m * n) * x.dtype.itemsize + k * n * 4,
            transcendentals=0),
    )(x, w, a, b)
    return out, (stats_out if stats else None)


def _conv3x3_kernel(x_hbm, w_ref, a_ref, b_ref, out_ref, stats_ref,
                    slab, im2col, sem, *, relu, stats, affine,
                    out_dtype, bm, c, img_w, img_h, m_total):
    """3x3 stride-1 pad-1 conv on NHWC-flat rows as ONE im2col matmul
    per tile: a halo slab (bm + 2*(W+1) rows) is DMA'd from HBM, the
    producer-BN affine(+relu) is applied once to the slab, nine shifted
    views (masked at image edges) form the [bm, 9C] im2col tile in
    VMEM, and a single [bm, 9C] x [9C, N] dot hits the MXU with a deep
    contraction even for narrow C."""
    i = pl.program_id(0)
    halo = -(-(img_w + 1) // 8) * 8   # 8-aligned: DMA offsets/sizes
    slab_rows = bm + 2 * halo         # must sit on sublane tiles

    # three DMA shapes (static sizes): interior, first, last tile
    nm = pl.num_programs(0)

    # Boundary rows that fall outside x are never READ un-masked (the
    # h/w validity masks below zero every out-of-image tap), so the
    # boundary tiles only need their copies clamped, not zero-filled.
    # pl.multiple_of: Mosaic must PROVE dynamic DMA row offsets sit on
    # sublane tiles (bm and halo are both multiples of 8).
    @pl.when(jnp.logical_and(i > 0, i < nm - 1))
    def _interior():
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(pl.multiple_of(i * bm - halo, 8),
                           slab_rows)], slab, sem)
        cp.start()
        cp.wait()

    @pl.when(i == 0)
    def _first():
        # slab[halo + j] = x[j]; rows [0, halo) stay garbage (masked)
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(0, bm + halo)],
            slab.at[pl.ds(halo, bm + halo)], sem)
        cp.start()
        cp.wait()

    @pl.when(jnp.logical_and(i == nm - 1, nm > 1))
    def _last():
        # tail rows past x's end stay garbage (masked)
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(pl.multiple_of(i * bm - halo, 8),
                           bm + halo)],
            slab.at[pl.ds(0, bm + halo)], sem)
        cp.start()
        cp.wait()

    # f32 through the rolls (Mosaic's rotate needs 32-bit data); the
    # im2col store downcasts back to the input dtype for the MXU
    sl = slab[:].astype(jnp.float32)
    if affine:
        sl = sl * a_ref[:] + b_ref[:]
    if relu:
        sl = jnp.maximum(sl, 0.0)

    # row coordinates of the bm output rows
    r = jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0) + i * bm
    h = (r // img_w) % img_h
    w_pos = r % img_w

    for t, (dh, dw) in enumerate((dh, dw) for dh in (-1, 0, 1)
                                 for dw in (-1, 0, 1)):
        off = halo + dh * img_w + dw          # static, in [0, 2*halo]
        # Mosaic cannot slice VMEM at unaligned sublane offsets; a
        # static roll + aligned [0:bm] slice expresses the same shift
        rows = sl.shape[0]
        tap = pltpu.roll(sl, rows - off, 0)[0:bm]
        valid = (h + dh >= 0) & (h + dh < img_h) & \
                (w_pos + dw >= 0) & (w_pos + dw < img_w)
        im2col[:, t * c:(t + 1) * c] = jnp.where(valid, tap, 0.0).astype(
            im2col.dtype)
    out = jnp.dot(im2col[:], w_ref[:], preferred_element_type=jnp.float32)
    out_ref[:] = out.astype(out_dtype)
    if stats:
        @pl.when(i == 0)
        def _init():
            stats_ref[:] = jnp.zeros_like(stats_ref)
        stats_ref[0, :] += jnp.sum(out, axis=0)
        stats_ref[1, :] += jnp.sum(out * out, axis=0)


def _pack_paired_w(w_flat, c, n):
    """Re-express tap-major 3x3 weights [9c, n] for the pixel-PAIR
    geometry: two adjacent pixels fold into one 2c-lane row (Mosaic
    DMAs need >=128 lanes), so the conv becomes 9 pair-taps with a
    [9*2c, 2n] weight carrying structural zeros (dw = 2*dp +
    half_in - half_out must land in {-1,0,1})."""
    wp = jnp.zeros((9 * 2 * c, 2 * n), w_flat.dtype)
    for dh in (-1, 0, 1):
        for dp in (-1, 0, 1):
            tp = (dh + 1) * 3 + (dp + 1)
            for half_in in (0, 1):
                for half_out in (0, 1):
                    dw = 2 * dp + half_in - half_out
                    if dw < -1 or dw > 1:
                        continue
                    t = (dh + 1) * 3 + (dw + 1)
                    wp = wp.at[
                        tp * 2 * c + half_in * c:
                        tp * 2 * c + half_in * c + c,
                        half_out * n: half_out * n + n,
                    ].set(w_flat[t * c:(t + 1) * c, :])
    return wp


def conv3x3_bn_act(x, w, img_h, img_w, a=None, b=None, relu=False,
                   stats=True, block_m=None, interpret=None):
    """Fused 3x3 stride-1 pad-1 conv on NHWC-flat input.

    x: [M, C] with M = N*img_h*img_w rows in NHWC-flat order.
    w: [9*C, N] tap-major weights (tap t = (dh+1)*3 + (dw+1) occupies
        rows t*C : (t+1)*C) — `pack_w3x3` converts OIHW.
    a, b, relu, stats: as conv1x1_bn_act (producer-BN prologue on x,
        consumer-BN stats epilogue on the f32 output).

    C must be a multiple of 128 (Mosaic lane tiling), or exactly 64 —
    the 64-channel case (ResNet stage 1) runs in a pixel-pair geometry:
    x reshapes (free) to [M/2, 128] rows of two adjacent pixels, the
    weights gain structural zeros (2x MXU work on an HBM-bound shape),
    and the output/stats fold back — wrapper-level only, same kernel.
    """
    if interpret is None:
        interpret = interpret_default()
    m, c = x.shape
    k9, n = w.shape
    assert k9 == 9 * c, (x.shape, w.shape)
    assert m % (img_h * img_w) == 0, (m, img_h, img_w)
    if c == 64 and img_w % 2 == 0:
        out, st = conv3x3_bn_act(
            x.reshape(m // 2, 2 * c), _pack_paired_w(w, c, n),
            img_h, img_w // 2,
            a=None if a is None else jnp.concatenate([a, a]),
            b=None if b is None else jnp.concatenate([b, b]),
            relu=relu, stats=stats,
            block_m=None,   # geometry halved: re-pick a valid divisor
            interpret=interpret)
        out = out.reshape(m, n)
        if st is not None:
            st = st[:, :n] + st[:, n:]
        return out, st
    affine = a is not None
    if affine:
        a = jnp.asarray(a, jnp.float32).reshape(1, c)
        b = jnp.asarray(b, jnp.float32).reshape(1, c)
    else:
        a = jnp.zeros((1, 1), jnp.float32)
        b = jnp.zeros((1, 1), jnp.float32)
    halo = -(-(img_w + 1) // 8) * 8
    bm = block_m or _pick_block_m(m, 512)
    assert m % bm == 0, (m, bm)
    if bm < halo + 8 or m // bm < 2 or \
            (not interpret and c % 128 != 0):
        # tiny inputs: one whole-array tile would need special DMA
        # cases; not the hot path — compose from the 1x1 kernel's
        # building blocks at the JAX level instead
        return _conv3x3_small(x, w, img_h, img_w, a if affine else None,
                              b if affine else None, relu, stats,
                              interpret)
    grid = (m // bm,)
    kernel = functools.partial(
        _conv3x3_kernel, relu=relu, stats=stats, affine=affine,
        out_dtype=x.dtype, bm=bm, c=c, img_w=img_w, img_h=img_h,
        m_total=m)
    out, stats_out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),       # x stays in HBM
            pl.BlockSpec((k9, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(a.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(b.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((m, n), x.dtype),
                   jax.ShapeDtypeStruct((2, n), jnp.float32)],
        scratch_shapes=[
            pltpu.VMEM((bm + 2 * halo, c), x.dtype),
            pltpu.VMEM((bm, 9 * c), x.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * m * 9 * c * n,
            bytes_accessed=(m * c + m * n) * x.dtype.itemsize
            + k9 * n * 4,
            transcendentals=0),
    )(x, w, a, b)
    return out, (stats_out if stats else None)


def _conv3x3_small(x, w, img_h, img_w, a, b, relu, stats, interpret):
    """Fallback for shapes too small for the halo kernel: same math in
    plain jnp (XLA) — shifted adds on the flat layout."""
    m, c = x.shape
    xf = x.astype(jnp.float32)
    if a is not None:
        xf = xf * a + b
        if relu:
            xf = jnp.maximum(xf, 0.0)
        xf = xf.astype(x.dtype).astype(jnp.float32)
    elif relu:
        xf = jnp.maximum(xf, 0.0)
    imgs = xf.reshape(-1, img_h, img_w, c)
    cols = []
    for dh in (-1, 0, 1):
        for dw in (-1, 0, 1):
            sh = jnp.roll(imgs, (-dh, -dw), axis=(1, 2))
            hi = jnp.arange(img_h)[None, :, None, None]
            wi = jnp.arange(img_w)[None, None, :, None]
            valid = (hi + dh >= 0) & (hi + dh < img_h) & \
                    (wi + dw >= 0) & (wi + dw < img_w)
            cols.append(jnp.where(valid, sh, 0.0))
    im2col = jnp.concatenate(cols, axis=-1).reshape(m, 9 * c)
    out = jnp.dot(im2col.astype(x.dtype), w,
                  preferred_element_type=jnp.float32)
    st = jnp.stack([out.sum(0), (out * out).sum(0)]) if stats else None
    return out.astype(x.dtype), st


def pack_w3x3(w_oihw):
    """[O, I, 3, 3] -> tap-major [9*I, O] for conv3x3_bn_act."""
    o, i, kh, kw = w_oihw.shape
    assert kh == 3 and kw == 3
    # tap-major: [kh, kw, I, O]
    return jnp.transpose(w_oihw, (2, 3, 1, 0)).reshape(9 * i, o)


def reference_conv1x1_bn_act(x, w, a=None, b=None, relu=False):
    """Pure-jnp oracle for tests: same math, composed ops."""
    xf = x.astype(jnp.float32)
    if a is not None:
        xf = xf * jnp.asarray(a, jnp.float32)[None, :] \
            + jnp.asarray(b, jnp.float32)[None, :]
        if relu:
            xf = jnp.maximum(xf, 0.0)
        xf = xf.astype(x.dtype).astype(jnp.float32)
    elif relu:
        xf = jnp.maximum(xf, 0.0)
    out = jnp.dot(xf.astype(x.dtype), w,
                  preferred_element_type=jnp.float32)
    stats = jnp.stack([out.sum(0), (out * out).sum(0)])
    return out.astype(x.dtype), stats
