"""Fused GRU time loop as Pallas TPU kernels (forward + backward).

Companion to fused_lstm.py (the reference hand-fuses GRU the same way in
paddle/cuda — hl_cuda_lstm.cu's sibling kernels). Recurrent state h
stays in VMEM scratch across all timesteps; backward walks in reverse
recomputing gates from (x_t, h_prev).

Layout (matches ops/sequence_ops.py _gru):
  x  [T, B, 3H]  pre-projected (+bias folded in by the caller),
                 order u (update), r (reset), c (candidate)
  w  [H, 3H]     packs [H, 2H] update/reset + [H, H] candidate
  h0 [B, H]; lengths [B] ragged mask (frozen rows / zeroed outputs,
  identical to _masked_scan_rnn).
  h = u * h_prev + (1 - u) * tanh(xc + (r * h_prev) @ w_c)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from . import interpret_default as _interpret_default  # shared policy


def _gates(x_t, h_prev, w_ref, hidden):
    w = w_ref[...].astype(jnp.float32)
    w_ur = w[:, :2 * hidden]
    w_c = w[:, 2 * hidden:]
    ur = jax.lax.dot_general(h_prev, w_ur, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    u = jax.nn.sigmoid(x_t[:, :hidden] + ur[:, :hidden])
    r = jax.nn.sigmoid(x_t[:, hidden:2 * hidden] + ur[:, hidden:])
    rh = r * h_prev
    c = jnp.tanh(x_t[:, 2 * hidden:] +
                 jax.lax.dot_general(rh, w_c, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32))
    return u, r, rh, c, w_ur, w_c


def _fwd_kernel(len_ref, x_ref, w_ref, h0_ref, h_all_ref, h_scr, *,
                hidden):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[...] = h0_ref[...].astype(jnp.float32)

    h_prev = h_scr[...]
    x_t = x_ref[0].astype(jnp.float32)
    u, r, rh, c, _, _ = _gates(x_t, h_prev, w_ref, hidden)
    h_new = u * h_prev + (1.0 - u) * c

    alive = t < len_ref[...]                     # [B, 1]
    h_scr[...] = jnp.where(alive, h_new, h_prev)
    h_all_ref[0] = jnp.where(alive, h_new,
                             jnp.zeros_like(h_new)).astype(h_all_ref.dtype)


def _bwd_kernel(len_ref, x_ref, w_ref, h0_ref, h_all_ref, dh_out_ref,
                dx_ref, dw_ref, dh0_ref,
                dh_scr, dw_scr, *, hidden, t_max):
    k = pl.program_id(0)
    t = t_max - 1 - k

    @pl.when(k == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)
        dw_scr[...] = jnp.zeros_like(dw_scr)

    use_init = (t == 0)
    h_prev = jnp.where(use_init, h0_ref[...].astype(jnp.float32),
                       h_all_ref[0].astype(jnp.float32))
    x_t = x_ref[0].astype(jnp.float32)
    u, r, rh, c, w_ur, w_c = _gates(x_t, h_prev, w_ref, hidden)

    alive = t < len_ref[...]
    dh = dh_out_ref[0].astype(jnp.float32) + dh_scr[...]
    dh = jnp.where(alive, dh, jnp.zeros_like(dh))

    du_pre = dh * (h_prev - c) * u * (1.0 - u)
    dc_pre = dh * (1.0 - u) * (1.0 - c * c)
    d_rh = jax.lax.dot_general(dc_pre, w_c, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dr_pre = d_rh * h_prev * r * (1.0 - r)
    dur_pre = jnp.concatenate([du_pre, dr_pre], axis=1)

    dh_prev = dh * u + d_rh * r + jax.lax.dot_general(
        dur_pre, w_ur, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    dx = jnp.concatenate([du_pre, dr_pre, dc_pre], axis=1)
    dx_ref[0] = jnp.where(alive, dx, jnp.zeros_like(dx)
                          ).astype(dx_ref.dtype)
    # dead rows contribute zeros automatically: every pre-activation
    # grad is proportional to the masked dh
    dw_ur = jax.lax.dot_general(h_prev, dur_pre,
                                (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
    dw_c = jax.lax.dot_general(rh, dc_pre, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dw_scr[...] += jnp.concatenate([dw_ur, dw_c], axis=1)

    dh_scr[...] = jnp.where(alive, dh_prev, dh_scr[...])

    @pl.when(k == t_max - 1)
    def _final():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)
        dh0_ref[...] = dh_scr[...].astype(dh0_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_gru(x, w, h0, lengths, interpret=None):
    """[T, B, 3H] pre-projected -> (h_all [T, B, H], h_last [B, H])."""
    return _fused_gru_fwd(x, w, h0, lengths, interpret)[0]


def _run_fwd(x, w, h0, lengths, interpret):
    if interpret is None:
        interpret = _interpret_default()
    t_max, bsz, g3 = x.shape
    hidden = g3 // 3
    kernel = functools.partial(_fwd_kernel, hidden=hidden)
    h_all = pl.pallas_call(
        kernel,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((bsz, 1), lambda t: (0, 0)),
            pl.BlockSpec((1, bsz, g3), lambda t: (t, 0, 0)),
            pl.BlockSpec((hidden, g3), lambda t: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda t: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bsz, hidden), lambda t: (t, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((t_max, bsz, hidden), x.dtype),
        scratch_shapes=[pltpu.VMEM((bsz, hidden), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(lengths.astype(jnp.int32).reshape(bsz, 1), x, w, h0)
    lens32 = lengths.astype(jnp.int32)
    idx = jnp.maximum(lens32 - 1, 0)
    h_last = jnp.take_along_axis(
        jnp.moveaxis(h_all, 0, 1), idx[:, None, None], axis=1)[:, 0]
    h_last = jnp.where((lens32 == 0)[:, None], h0.astype(h_last.dtype),
                       h_last)
    return h_all, h_last


def _fused_gru_fwd(x, w, h0, lengths, interpret):
    h_all, h_last = _run_fwd(x, w, h0, lengths, interpret)
    return (h_all, h_last), (x, w, h0, lengths, h_all)


def _fused_gru_bwd(interpret, res, grads):
    x, w, h0, lengths, h_all = res
    dh_all, dh_last = grads
    if interpret is None:
        interpret = _interpret_default()
    t_max, bsz, g3 = x.shape
    hidden = g3 // 3
    lens32 = lengths.astype(jnp.int32)
    idx = jnp.maximum(lens32 - 1, 0)
    dh_all = jnp.moveaxis(jnp.moveaxis(dh_all, 0, 1).at[
        jnp.arange(bsz), idx].add(
            jnp.where((lens32 == 0)[:, None], 0.0, dh_last)), 1, 0)

    kernel = functools.partial(_bwd_kernel, hidden=hidden, t_max=t_max)
    dx, dw, dh0 = pl.pallas_call(
        kernel,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((bsz, 1), lambda k: (0, 0)),
            pl.BlockSpec((1, bsz, g3), lambda k: (t_max - 1 - k, 0, 0)),
            pl.BlockSpec((hidden, g3), lambda k: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda k: (0, 0)),
            pl.BlockSpec((1, bsz, hidden),
                         lambda k: (jnp.maximum(t_max - 2 - k, 0), 0, 0)),
            pl.BlockSpec((1, bsz, hidden),
                         lambda k: (t_max - 1 - k, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bsz, g3), lambda k: (t_max - 1 - k, 0, 0)),
            pl.BlockSpec((hidden, g3), lambda k: (0, 0)),
            pl.BlockSpec((bsz, hidden), lambda k: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t_max, bsz, g3), x.dtype),
            jax.ShapeDtypeStruct((hidden, g3), w.dtype),
            jax.ShapeDtypeStruct((bsz, hidden), h0.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bsz, hidden), jnp.float32),
                        pltpu.VMEM((hidden, g3), jnp.float32)],
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(lens32.reshape(bsz, 1), x, w, h0, h_all, dh_all)
    # grad of the zero-length h_last passthrough
    dh0 = dh0 + jnp.where((lens32 == 0)[:, None], dh_last, 0.0)
    return dx, dw, dh0, None


fused_gru.defvjp(_fused_gru_fwd, _fused_gru_bwd)
