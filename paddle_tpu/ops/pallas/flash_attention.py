"""Flash attention as Pallas TPU kernels (forward + backward).

Online-softmax tiled attention: O(S) memory instead of the O(S^2) scores
matrix of the naive composition (reference composes attention from
matmul/softmax in python/paddle/fluid/nets.py:312; its hand-fused CUDA
analogue for recurrent hot loops is paddle/cuda/src/hl_cuda_lstm.cu —
Pallas is the TPU-native equivalent of that hand-fusion layer).

Layout: q [B, H, Sq, D], k/v [B, H, Sk, D], optional additive bias/mask
broadcastable as [B, {1|H}, Sq, Sk]. The grid iterates
(batch, head, q-block, k-block) with the k-block axis innermost ("arbitrary"
semantics) so VMEM scratch accumulators carry across k-blocks while Mosaic
pipelines the HBM->VMEM block copies.

The backward pass is two more Pallas kernels (dq and dkv) using the
logsumexp residual, plus an exact additive-bias gradient emitted from the
dq kernel — the standard flash-attention-2 recurrence.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


from . import interpret_default as _interpret_default  # shared policy


def _clamp_blocks(sq, sk, block_q, block_k, interpret):
    """Mosaic requires block last-two dims (div 8, div 128) or full-dim.
    Blocks over the scores matrix are (block_q, block_k), so compiled
    kernels need block_q % 8 == 0 and block_k % 128 == 0.

    The requested block size acts as a CAP: the axis is split into the
    fewest blocks that respect it, then the block is shrunk to fit the
    actual length so padding stays under one alignment unit PER BLOCK
    (e.g. sq=1100 with cap 1024 -> 2 blocks of 552 = 1104 padded rows,
    not 2 blocks of 1024 = 2048)."""
    if interpret:
        return min(block_q, _ceil_to(sq, 8)), min(block_k, _ceil_to(sk, 8))
    nq = -(-sq // max(block_q, 8))
    nk = -(-sk // max(block_k, 128))
    return (_ceil_to(-(-sq // nq), 8), _ceil_to(-(-sk // nk), 128))


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q,
                block_k, kv_len):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Whole k-block above the causal diagonal -> nothing to do.
    run = True
    if causal:
        run = iq * block_q + block_q - 1 >= ik * block_k

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                       # [bq, d]
        k = k_ref[0, 0]                       # [bk, d]
        v = v_ref[0, 0]                       # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kpos < kv_len, s, NEG_INF)  # mask seq padding
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            s = jnp.where(qpos >= kpos, s, NEG_INF)

        m_prev = m_scr[:, :1]                                 # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                                # [bq, bk]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, d]
        acc_scr[:] = acc_scr[:] * alpha + pv

    @pl.when(ik == nk - 1)
    def _fin():
        l = l_scr[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)       # fully-masked rows -> 0 out
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse = m_scr[:] + jnp.log(jnp.maximum(l_scr[:], 1e-37))
        lse_ref[0, 0] = lse.astype(lse_ref.dtype)


def _bias_spec(bias, sq_p, sk_p, block_q, block_k, order):
    """Padded bias + BlockSpec keeping broadcast (size-1) dims
    unmaterialized: broadcast dims get block size 1 and index 0, and the
    kernel's `s + bias_block` broadcasts in-register. order 'qk' means the
    grid is (b, h, iq, ik); 'kq' is (b, h, ik, iq)."""
    bb, bh, bsq, bsk = bias.shape
    biasp = jnp.pad(bias, ((0, 0), (0, 0),
                           (0, sq_p - bsq if bsq != 1 else 0),
                           (0, sk_p - bsk if bsk != 1 else 0)))
    blk = (1, 1, block_q if bsq != 1 else 1, block_k if bsk != 1 else 1)

    def im_qk(b, h, iq, ik):
        return (0 if bb == 1 else b, 0 if bh == 1 else h,
                0 if bsq == 1 else iq, 0 if bsk == 1 else ik)

    def im_kq(b, h, ik, iq):
        return im_qk(b, h, iq, ik)

    return biasp, pl.BlockSpec(blk, im_qk if order == "qk" else im_kq)


def _fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _clamp_blocks(sq, sk, block_q, block_k, interpret)
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    grid = (b, h, sq_p // block_q, sk_p // block_k)

    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, iq, ik: (b, h, iq, 0)),
        pl.BlockSpec((1, 1, block_k, d), lambda b, h, iq, ik: (b, h, ik, 0)),
        pl.BlockSpec((1, 1, block_k, d), lambda b, h, iq, ik: (b, h, ik, 0)),
    ]
    args = [qp, kp, vp]
    if bias is not None:
        biasp, bspec = _bias_spec(bias, sq_p, sk_p, block_q, block_k, "qk")
        in_specs.append(bspec)
        args.append(biasp)

        kernel = functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
            block_k=block_k, kv_len=sk)
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m, l, a):
            return _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                               m, l, a, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=sk)

    scratch = [
        _scratch((block_q, 128), jnp.float32),
        _scratch((block_q, 128), jnp.float32),
        _scratch((block_q, d), jnp.float32),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        jax.ShapeDtypeStruct((b, h, sq_p, 128), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda b, h, iq, ik: (b, h, iq, 0)),
        pl.BlockSpec((1, 1, block_q, 128),
                     lambda b, h, iq, ik: (b, h, iq, 0)),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=_compiler_params(("parallel",) * 3 + ("arbitrary",)),
        interpret=interpret,
    )(*args)
    return o[:, :, :sq], lse[:, :, :sq, :1]   # lse kept [B,H,Sq,1]


def _scratch(shape, dtype):
    return pltpu.VMEM(shape, dtype)


def _compiler_params(dimension_semantics):
    try:
        return pltpu.CompilerParams(dimension_semantics=dimension_semantics)
    except (AttributeError, TypeError):  # older jax spelling
        return pltpu.TPUCompilerParams(
            dimension_semantics=dimension_semantics)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dbias_ref, dq_scr, *, sm_scale, causal, block_q,
               block_k, kv_len):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = iq * block_q + block_q - 1 >= ik * block_k

    @pl.when(run)
    def _step():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]                                    # [bq, d]
        lse = lse_ref[0, 0][:, :1]                           # [bq, 1]
        delta = delta_ref[0, 0][:, :1]                       # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < kv_len, s, NEG_INF)
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        ds = p * (dp - delta)                                # [bq, bk]
        if dbias_ref is not None:
            dbias_ref[0, 0] = ds.astype(dbias_ref.dtype)
        dq_scr[:] += sm_scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if dbias_ref is not None:
        @pl.when(jnp.logical_not(run))
        def _zero_bias():
            dbias_ref[0, 0] = jnp.zeros_like(dbias_ref[0, 0])

    @pl.when(ik == nk - 1)
    def _fin():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale, causal,
                block_q, block_k, kv_len):
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = iq * block_q + block_q - 1 >= ik * block_k

    @pl.when(run)
    def _step():
        q, k, v = q_ref[0, 0], k_ref[0, 0], v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0].astype(jnp.float32)
        kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < kv_len, s, NEG_INF)
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[:] += sm_scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, d]

    @pl.when(iq == nq - 1)
    def _fin():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(res, g, sm_scale, causal, block_q, block_k, interpret,
         bias_needs_grad):
    q, k, v, bias, o, lse = res
    do = g
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _clamp_blocks(sq, sk, block_q, block_k, interpret)
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                 # [B,H,Sq,1]
    pad_q = ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))
    pad_k = ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))
    qp, dop = jnp.pad(q, pad_q), jnp.pad(do, pad_q)
    kp, vp = jnp.pad(k, pad_k), jnp.pad(v, pad_k)
    # lse rows for padded q positions must not produce NaN in exp(s - lse):
    lsep = jnp.pad(jnp.broadcast_to(lse, (b, h, sq, 128)), pad_q)
    deltap = jnp.pad(jnp.broadcast_to(delta, (b, h, sq, 128)), pad_q)

    def qspec(im):
        return pl.BlockSpec((1, 1, block_q, d), im)

    def kspec(im):
        return pl.BlockSpec((1, 1, block_k, d), im)

    def rspec(im):  # row stats [.., 128]
        return pl.BlockSpec((1, 1, block_q, 128), im)

    # ---- dq (+ dbias) over grid (b, h, iq, ik), k innermost ----
    qk_q = lambda b, h, iq, ik: (b, h, iq, 0)
    qk_k = lambda b, h, iq, ik: (b, h, ik, 0)
    in_specs = [qspec(qk_q), kspec(qk_k), kspec(qk_k)]
    args = [qp, kp, vp]
    has_bias = bias is not None
    if has_bias:
        biasp, bspec = _bias_spec(bias, sq_p, sk_p, block_q, block_k, "qk")
        in_specs.append(bspec)
        args.append(biasp)
    in_specs += [qspec(qk_q), rspec(qk_q), rspec(qk_q)]
    args += [dop, lsep, deltap]

    out_shape = [jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype)]
    out_specs = [qspec(qk_q)]
    emit_dbias = has_bias and bias_needs_grad
    if emit_dbias:
        out_shape.append(jax.ShapeDtypeStruct(
            (b, h, sq_p, sk_p), jnp.float32))
        out_specs.append(pl.BlockSpec(
            (1, 1, block_q, block_k), lambda b, h, iq, ik: (b, h, iq, ik)))

    def dq_kernel(*refs):
        n_in = len(args)
        ins, outs, scr = refs[:n_in], refs[n_in:-1], refs[-1]
        bias_ref = ins[3] if has_bias else None
        rest = ins[3 + int(has_bias):]
        _dq_kernel(ins[0], ins[1], ins[2], bias_ref, rest[0], rest[1],
                   rest[2], outs[0],
                   outs[1] if emit_dbias else None, scr,
                   sm_scale=sm_scale, causal=causal, block_q=block_q,
                   block_k=block_k, kv_len=sk)

    res_dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, sq_p // block_q, sk_p // block_k),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[_scratch((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel",) * 3 + ("arbitrary",)),
        interpret=interpret,
    )(*args)
    if emit_dbias:
        dq, dbias_full = res_dq
        dbias_full = dbias_full[:, :, :sq, :sk]
        # reduce over every broadcast dim of the original bias
        for ax in range(4):
            if bias.shape[ax] == 1 and dbias_full.shape[ax] != 1:
                dbias_full = jnp.sum(dbias_full, axis=ax, keepdims=True)
        dbias = dbias_full.astype(bias.dtype)
    else:
        dq = res_dq[0]
        dbias = jnp.zeros_like(bias) if bias is not None else None
    dq = dq[:, :, :sq]

    # ---- dk/dv over grid (b, h, ik, iq), q innermost ----
    kq_q = lambda b, h, ik, iq: (b, h, iq, 0)
    kq_k = lambda b, h, ik, iq: (b, h, ik, 0)
    in_specs = [qspec(kq_q), kspec(kq_k), kspec(kq_k)]
    args2 = [qp, kp, vp]
    if has_bias:
        biasp, bspec = _bias_spec(bias, sq_p, sk_p, block_q, block_k, "kq")
        in_specs.append(bspec)
        args2.append(biasp)
    in_specs += [qspec(kq_q), rspec(kq_q), rspec(kq_q)]
    args2 += [dop, lsep, deltap]

    def dkv_kernel(*refs):
        n_in = len(args2)
        ins, outs, scr = refs[:n_in], refs[n_in:n_in + 2], refs[n_in + 2:]
        bias_ref = ins[3] if has_bias else None
        rest = ins[3 + int(has_bias):]
        _dkv_kernel(ins[0], ins[1], ins[2], bias_ref, rest[0], rest[1],
                    rest[2], outs[0], outs[1], scr[0], scr[1],
                    sm_scale=sm_scale, causal=causal, block_q=block_q,
                    block_k=block_k, kv_len=sk)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, sk_p // block_k, sq_p // block_q),
        in_specs=in_specs,
        out_specs=[kspec(kq_k), kspec(kq_k)],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk_p, d), v.dtype)],
        scratch_shapes=[_scratch((block_k, d), jnp.float32),
                        _scratch((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(("parallel",) * 3 + ("arbitrary",)),
        interpret=interpret,
    )(*args2)
    dk, dv = dk[:, :, :sk], dv[:, :, :sk]
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret,
           bias_grad):
    o, _ = _fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
                interpret)
    return o


def _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
               interpret, bias_grad):
    o, lse = _fwd(q, k, v, bias, sm_scale, causal, block_q, block_k,
                  interpret)
    return o, (q, k, v, bias, o, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, bias_grad,
               res, g):
    dq, dk, dv, dbias = _bwd(res, g, sm_scale, causal, block_q, block_k,
                             interpret, bias_needs_grad=bias_grad)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias: Optional[jax.Array] = None,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    bias_grad: bool = False) -> jax.Array:
    """Tiled online-softmax attention.

    q: [B, H, Sq, D]; k, v: [B, H, Sk, D]; bias additive with any of the
    four dims broadcast (size 1). Returns [B, H, Sq, D].

    bias_grad=False (default) treats bias as a constant mask: backward
    returns zeros for it without materializing the O(Sq*Sk) dbias buffer.
    Set bias_grad=True for trainable biases (e.g. relative-position bias);
    the gradient is then emitted from the dq kernel and summed over any
    broadcast dims.

    block_q/block_k act as CAPS on the tile size: the sequence is split
    into the fewest cap-respecting tiles and the tile shrinks to fit
    (minimizing padding), so an explicit 256 with sq=900 runs 4 tiles
    of 232. None selects the per-path default cap below, swept on v5e
    with stacked-layer fwd+bwd marginal timing: 1024x1024 beat 128x128
    by 1.4x at seq 256, 2.7x at 1024, and was still fastest at 4096.
    """
    if interpret is None:
        interpret = _interpret_default()
    if sm_scale is None:
        sm_scale = 1.0 / (q.shape[-1] ** 0.5)
    # Default tile caps (explicit block_q/block_k always win): 1024 for
    # bias-free attention; a materialized bias adds score-sized blocks
    # to every kernel's VMEM footprint, so mask-bias defaults to 512
    # (~5 score-sized fp32 buffers = 5MB, well under the 16MB
    # scoped-vmem limit). Trainable-bias grads additionally accumulate
    # dbias tiles and show larger fp32 reassociation drift at big tiles
    # (~4e-3 rel between 128 and 512 at S=1024 on v5e) — they default
    # to the original 128 tiling for bit-stable gradients.
    if bias is None:
        default_blk = 1024
    elif bias_grad:
        default_blk = 128
    else:
        default_blk = 512
    block_q = default_blk if block_q is None else block_q
    block_k = default_blk if block_k is None else block_k
    if bias is not None:
        if bias.ndim == 2:        # [Sq|1, Sk|1]
            bias = bias[None, None]
        elif bias.ndim == 3:      # [B|1, Sq|1, Sk|1]
            bias = bias[:, None]
    return _flash(q, k, v, bias, float(sm_scale), bool(causal),
                  int(block_q), int(block_k), bool(interpret),
                  bool(bias_grad))
