"""Neural-net ops: conv, pool, normalization, dropout, losses, embeddings.

Reference parity: paddle/fluid/operators/{conv_op.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc, cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, lookup_table_op.cc, one_hot_op.cc,
smooth_l1_loss_op.cc, huber_loss_op.cc, hinge_loss_op.cc, nce_op.cc...}.
Layout follows the reference's NCHW API; XLA's layout assignment re-tiles
for the MXU internally, so parity costs nothing on TPU.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..amp import amp_cast
from ..core.registry import register_op
from .core_ops import jnp_dtype, _op_key


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


# -- convolution ------------------------------------------------------------

def _conv_nhwc():
    """Read at trace time (not import) so in-process A/B toggling works.
    A/B on real TPU showed NCHW ≥ NHWC (XLA's layout assignment already
    re-tiles internally), so NCHW stays the default."""
    return os.environ.get("PADDLE_TPU_CONV_LAYOUT", "nchw") == "nhwc"


def _conv2d_impl(x, w, strides, paddings, dilations, groups):
    # A strided 1x1 conv only READS the subsampled grid: slicing first
    # and convolving stride-1 is the same math, but its transpose
    # (weight/input grads) lowers to clean MXU matmuls + a pad, where
    # the strided form's gradients lowered to ~0.5ms/conv loop fusions
    # (copy_subtract in the device trace — the round-2 "stride-2
    # gradient fringe"). ResNet's downsample shortcuts hit this.
    if (tuple(w.shape[2:]) == (1, 1) and tuple(paddings) == (0, 0)
            and (strides[0] > 1 or strides[1] > 1) and groups == 1):
        x = x[:, :, ::strides[0], ::strides[1]]
        strides = (1, 1)
    # Under AMP both operands drop to bf16 and the OUTPUT STAYS bf16:
    # activations thread end-to-end at half width so every inter-op HBM
    # buffer halves. (Round 1 cast each op's result back to f32; device
    # traces showed the resulting convert_element_type fusions plus the
    # doubled f32 traffic dominating the HBM-bound step — see
    # MFU_BREAKDOWN.md. The MXU accumulates in f32 internally either
    # way; preferred_element_type=f32's conv transpose rule rejects
    # mixed-dtype cotangents, so full-bf16 it is.)
    x, w = amp_cast(x, w)
    nhwc = _conv_nhwc()
    if nhwc:
        # API stays NCHW; internally convs run NHWC. XLA cancels the
        # transposes between consecutive convs, so the whole network
        # effectively switches layout.
        x = jnp.transpose(x, (0, 2, 3, 1))
        w = jnp.transpose(w, (2, 3, 1, 0))
    out = jax.lax.conv_general_dilated(
        x, w,
        window_strides=strides,
        padding=[(paddings[0], paddings[0]), (paddings[1], paddings[1])],
        rhs_dilation=dilations,
        dimension_numbers=(("NHWC", "HWIO", "NHWC") if nhwc
                           else ("NCHW", "OIHW", "NCHW")),
        feature_group_count=groups,
    )
    if nhwc:
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


@register_op("conv2d")
def _conv2d(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")
    out = _conv2d_impl(x, w, _pair(ctx.attr("strides", [1, 1])),
                       _pair(ctx.attr("paddings", [0, 0])),
                       _pair(ctx.attr("dilations", [1, 1])),
                       ctx.attr("groups", 1))
    ctx.set_output("Output", out)


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")
    groups = x.shape[1]
    out = _conv2d_impl(x, w, _pair(ctx.attr("strides", [1, 1])),
                       _pair(ctx.attr("paddings", [0, 0])),
                       _pair(ctx.attr("dilations", [1, 1])), groups)
    ctx.set_output("Output", out)


def _conv_transpose_impl(x, w, s, p, d, nd):
    """Transposed conv as an input-dilated conv with a flipped, IO-swapped
    kernel — the gradient-of-conv identity, so output size is the
    reference's (i-1)*stride - 2*pad + dilation*(k-1) + 1
    (conv_transpose_op.cc). w: [in_c, out_c, *k]."""
    wk = jnp.flip(w, axis=tuple(range(2, 2 + nd))).swapaxes(0, 1)
    pad = [(d[i] * (w.shape[2 + i] - 1) - p[i],) * 2 for i in range(nd)]
    dn = (("NCHW", "OIHW", "NCHW") if nd == 2
          else ("NCDHW", "OIDHW", "NCDHW"))
    x, wk = amp_cast(x, wk)  # bf16 in, bf16 out under AMP (see conv2d)
    return jax.lax.conv_general_dilated(
        x, wk, window_strides=(1,) * nd, padding=pad,
        lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=dn)


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")  # [in_c, out_c, kh, kw]
    s = _pair(ctx.attr("strides", [1, 1]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    d = _pair(ctx.attr("dilations", [1, 1]))
    ctx.set_output("Output", _conv_transpose_impl(x, w, s, p, d, 2))


@register_op("conv3d")
def _conv3d(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")
    s = ctx.attr("strides", [1, 1, 1])
    p = ctx.attr("paddings", [0, 0, 0])
    d = ctx.attr("dilations", [1, 1, 1])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(s),
        padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
        rhs_dilation=tuple(d),
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=ctx.attr("groups", 1))
    ctx.set_output("Output", out)


# -- pooling ----------------------------------------------------------------

@register_op("pool2d")
def _pool2d(ctx):
    x = ctx.input("X")
    ptype = ctx.attr("pooling_type", "max")
    k = _pair(ctx.attr("ksize", [2, 2]))
    s = _pair(ctx.attr("strides", [2, 2]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    if ctx.attr("global_pooling", False):
        k = (x.shape[2], x.shape[3])
        s = k
        p = (0, 0)
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, pads)
    else:
        # accumulate avg windows in f32 (bf16 inputs under AMP lose
        # mantissa over 49-element global windows); the converts fuse
        # into the reduce, so the HBM buffers stay input-width
        xf = x.astype(jnp.float32) if x.dtype == jnp.bfloat16 else x
        summed = jax.lax.reduce_window(xf, 0.0, jax.lax.add, dims, strides,
                                       pads)
        if ctx.attr("exclusive", True) and (p[0] or p[1]):
            ones = jnp.ones_like(xf)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                           strides, pads)
            out = (summed / counts).astype(x.dtype)
        else:
            out = (summed / (k[0] * k[1])).astype(x.dtype)
    ctx.set_output("Out", out)


@register_op("adaptive_pool2d")
def _adaptive_pool2d(ctx):
    x = ctx.input("X")
    oh, ow = _pair(ctx.attr("pool_size", [1, 1]))
    n, c, h, w = x.shape
    assert h % oh == 0 and w % ow == 0, "adaptive pool needs divisible sizes"
    xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if ctx.attr("pooling_type", "avg") == "max":
        out = xr.max(axis=(3, 5))
    else:
        out = xr.mean(axis=(3, 5))
    ctx.set_output("Out", out)


# -- normalization ----------------------------------------------------------

def _bn_bshape(x, ch_axis):
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    return tuple(bshape)


def _bn_train(x, scale, bias, red_axes, eps):
    """Train-mode BN forward, LEFT TO AUTODIFF on purpose (round 3):
    traced on TPU, XLA fuses the single-pass stats and the coefficient
    normalize into the producing convolution's fusion, and — decisive —
    it also fuses the autodiffed backward reductions into the conv
    gradient fusions. The round-2 hand-written custom_vjp backward
    (kept below as _bn_train_custom for the A/B) pinned those
    reductions as standalone convert_reduce fusions: the device trace
    showed 64 of them costing ~30ms/step vs ~0 for this form."""
    (y, _m, _v), _res = _bn_train_fwd(x, scale, bias, red_axes, eps)
    return y


# round-2 variant: same forward under a custom_vjp with the
# hand-derived 2-pass backward. Superseded as the default (see
# _bn_train) but kept selectable for A/Bs via PADDLE_TPU_BN_CUSTOM_VJP.
_bn_train_custom = functools.partial(jax.custom_vjp,
                                     nondiff_argnums=(3, 4))(_bn_train)


def _bn_train_fwd(x, scale, bias, red_axes, eps):
    """Single-pass stats (sum / sum-of-squares fuse into ONE sweep over
    x) + a coefficient-form normalize (y = x*a + b with per-channel
    a,b). Written this way so XLA can fuse both the stats and the
    normalize into the producing conv's fusion — and, under autodiff
    (the default path), the backward reductions into the conv gradient
    fusions; see _bn_train."""
    ch_axis = [i for i in range(x.ndim) if i not in red_axes][0]
    bshape = _bn_bshape(x, ch_axis)
    n = 1
    for i in red_axes:
        n *= x.shape[i]
    xf = x.astype(jnp.float32)
    s1 = jnp.sum(xf, axis=red_axes)
    s2 = jnp.sum(xf * xf, axis=red_axes)
    mean = s1 / n
    var = s2 / n - mean * mean          # biased, matching jnp.var
    inv = jax.lax.rsqrt(var + eps)
    a = scale * inv                      # [C] f32
    b = bias - mean * a
    y = (xf * a.reshape(bshape) + b.reshape(bshape)).astype(x.dtype)
    return (y, mean, var), (x, scale, mean, inv)


def _bn_train_bwd(red_axes, eps, res, dy):
    x, scale, mean, inv = res
    ch_axis = [i for i in range(x.ndim) if i not in red_axes][0]
    bshape = _bn_bshape(x, ch_axis)
    n = 1
    for i in red_axes:
        n *= x.shape[i]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mean.reshape(bshape)) * inv.reshape(bshape)
    # pass 1: both channel reductions in one sweep over (x, dy)
    dbias = jnp.sum(dyf, axis=red_axes)
    dscale = jnp.sum(dyf * xhat, axis=red_axes)
    # pass 2: dx
    coef = (scale * inv).reshape(bshape)
    dx = coef * (dyf - (dbias.reshape(bshape)
                        + xhat * dscale.reshape(bshape)) / n)
    return dx.astype(x.dtype), dscale, dbias


def _bn_train_vjp_fwd(x, scale, bias, red_axes, eps):
    (y, _m, _v), res = _bn_train_fwd(x, scale, bias, red_axes, eps)
    return y, res


_bn_train_custom.defvjp(_bn_train_vjp_fwd, _bn_train_bwd)


@register_op("batch_norm")
def _batch_norm(ctx):
    """Inputs: X, Scale, Bias, Mean, Variance. Outputs: Y, MeanOut,
    VarianceOut, SavedMean, SavedVariance (reference: batch_norm_op.cc)."""
    x = ctx.input("X")
    scale = ctx.input("Scale")
    bias = ctx.input("Bias")
    mean_in = ctx.input("Mean")
    var_in = ctx.input("Variance")
    eps = ctx.attr("epsilon", 1e-5)
    momentum = ctx.attr("momentum", 0.9)
    is_test = ctx.attr("is_test", False)

    ch_axis = 1 if ctx.attr("data_layout", "NCHW") == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = _bn_bshape(x, ch_axis)

    if is_test:
        inv = jax.lax.rsqrt(var_in.astype(jnp.float32) + eps)
        a = scale * inv
        b = bias - mean_in * a
        y = (x.astype(jnp.float32) * a.reshape(bshape)
             + b.reshape(bshape)).astype(x.dtype)
        ctx.set_output("Y", y)
        ctx.set_output("MeanOut", mean_in)
        ctx.set_output("VarianceOut", var_in)
        ctx.set_output("SavedMean", mean_in)
        ctx.set_output("SavedVariance", var_in)
        return

    if os.environ.get("PADDLE_TPU_BN_CUSTOM_VJP", "0") == "1":
        y = _bn_train_custom(x, scale, bias, red_axes, eps)  # round-2 A/B
    else:
        y = _bn_train(x, scale, bias, red_axes, eps)
    # stats recomputed OUTSIDE the custom_vjp so running-stat updates
    # carry no gradient plumbing; XLA CSEs them with the fwd pass sums
    xf = x.astype(jnp.float32)
    n = 1
    for i in red_axes:
        n *= x.shape[i]
    mean = jnp.sum(xf, axis=red_axes) / n
    var = jnp.sum(xf * xf, axis=red_axes) / n - mean * mean
    ctx.set_output("Y", y)
    ctx.set_output("MeanOut", mean_in * momentum + mean * (1 - momentum))
    ctx.set_output("VarianceOut", var_in * momentum + var * (1 - momentum))
    ctx.set_output("SavedMean", mean)
    ctx.set_output("SavedVariance", jax.lax.rsqrt(var + eps))


@register_op("layer_norm")
def _layer_norm(ctx):
    """Naive mean -> var -> normalize form ON PURPOSE: the round-3
    single-pass/coefficient rewrite (the form that paid off for
    batch_norm) measured 5-12% SLOWER for the transformer in
    order-controlled same-session A/Bs — LN reduces over the minor
    (d_model) dim where XLA fuses the row-local chain fine, and the
    coefficient broadcasts only add traffic (MFU_BREAKDOWN.md r3)."""
    x = ctx.input("X")
    scale = ctx.input("Scale")
    bias = ctx.input("Bias")
    eps = ctx.attr("epsilon", 1e-5)
    begin = ctx.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    norm_shape = (1,) * begin + x.shape[begin:]
    if scale is not None:
        y = y * scale.reshape(norm_shape)
    if bias is not None:
        y = y + bias.reshape(norm_shape)
    ctx.set_output("Y", y.astype(x.dtype))
    ctx.set_output("Mean", mean.reshape(x.shape[:begin]))
    ctx.set_output("Variance", var.reshape(x.shape[:begin]))


@register_op("lrn")
def _lrn(ctx):
    x = ctx.input("X")  # NCHW
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    ctx.set_output("Out", x / jnp.power(k + alpha * acc, beta))
    ctx.set_output("MidOut", k + alpha * acc)


# -- dropout ----------------------------------------------------------------

@register_op("dropout")
def _dropout(ctx):
    x = ctx.input("X")
    prob = ctx.attr("dropout_prob", 0.5)
    if ctx.attr("is_test", False) or prob == 0.0:
        ctx.set_output("Out", x)
        ctx.set_output("Mask", jnp.ones_like(x))
        return
    keep = 1.0 - prob
    mask = jax.random.bernoulli(_op_key(ctx), keep, x.shape)
    impl = ctx.attr("dropout_implementation", "downgrade_in_infer")
    if impl == "upscale_in_train":
        out = jnp.where(mask, x / keep, 0.0)
    else:  # reference default: scale at inference instead
        out = jnp.where(mask, x, 0.0)
    ctx.set_output("Out", out.astype(x.dtype))
    ctx.set_output("Mask", mask.astype(x.dtype))


# -- losses -----------------------------------------------------------------

@register_op("cross_entropy", no_grad_slots=["Label"])
def _cross_entropy(ctx):
    x = ctx.input("X")  # probabilities [N, C] (post-softmax)
    x = x.astype(jnp.float32)  # log() of bf16 probs is too coarse
    label = ctx.input("Label")
    eps = 1e-8
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
            else label
        picked = jnp.take_along_axis(
            x, lab[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(picked + eps)
    ctx.set_output("Y", loss)


@jax.custom_vjp
def _softmax_xent_hard(logits, lab):
    loss, _ = _softmax_xent_hard_fwd(logits, lab)
    return loss


def _softmax_xent_hard_fwd(logits, lab):
    """Hard-label softmax cross-entropy that never materializes a
    full-vocab f32 buffer: loss_i = logsumexp(x_i) - x_i[label]. The
    f32 upcast fuses into the two reductions, so big-vocab heads (e.g.
    the transformer's [B*S, 32k] logits — ~17% of the step in the
    device trace) stream at bf16 width."""
    xf = logits.astype(jnp.float32)
    m = jnp.max(xf, axis=-1, keepdims=True)
    z = m + jnp.log(jnp.sum(jnp.exp(xf - m), axis=-1, keepdims=True))
    picked = jnp.take_along_axis(xf, lab[..., None], axis=-1)
    loss = z - picked
    return loss, (logits, lab, z)


def _softmax_xent_hard_bwd(res, g):
    logits, lab, z = res
    xf = logits.astype(jnp.float32)
    p = jnp.exp(xf - z)                       # softmax, one fused pass
    dl = p * g                                # g: [..., 1] cotangent
    # subtract g at the label position (the one-hot term) via scatter
    sub = jnp.take_along_axis(dl, lab[..., None], axis=-1) - g
    dl = _put_along_axis(dl, lab[..., None], sub)
    return dl.astype(logits.dtype), None


def _put_along_axis(a, idx, vals):
    """a.at[..., idx].set(vals) along the last axis."""
    flat_a = a.reshape(-1, a.shape[-1])
    flat_i = idx.reshape(-1)
    flat_v = vals.reshape(-1)
    rows = jnp.arange(flat_a.shape[0])
    out = flat_a.at[rows, flat_i].set(flat_v)
    return out.reshape(a.shape)


_softmax_xent_hard.defvjp(_softmax_xent_hard_fwd, _softmax_xent_hard_bwd)


@register_op("softmax_with_cross_entropy", no_grad_slots=["Label"])
def _softmax_with_cross_entropy(ctx):
    logits = ctx.input("Logits")
    label = ctx.input("Label")
    if ctx.attr("soft_label", False):
        logitsf = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logitsf, axis=-1)
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
        ctx.set_output("Softmax", jnp.exp(logp))
        ctx.set_output("Loss", loss)
        return
    lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 \
        else label
    lab = lab.astype(jnp.int32)
    if os.environ.get("PADDLE_TPU_FUSED_XENT", "0") == "1":
        # streaming custom-vjp variant: never materializes a full-vocab
        # f32 buffer — keeps peak memory O(bf16 logits) for very large
        # vocabularies. A/B on v5e at 32k vocab measured it 15% SLOWER
        # than XLA's autodiffed log_softmax (the backward scatter beats
        # the saved bandwidth only when memory is the binding
        # constraint), so it is opt-in.
        loss = _softmax_xent_hard(logits, lab)
    else:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, lab[..., None], axis=-1)
    # Softmax output computed independently; dead-code-eliminated by
    # XLA unless a consumer actually reads it
    ctx.set_output("Softmax",
                   jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
    ctx.set_output("Loss", loss)


@register_op("sigmoid_cross_entropy_with_logits", no_grad_slots=["Label"])
def _sigmoid_xent(ctx):
    x = ctx.input("X")
    label = ctx.input("Label")
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set_output("Out", loss)


@register_op("square_error_cost")
def _square_error_cost(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    ctx.set_output("Out", jnp.square(x - y))


@register_op("smooth_l1_loss")
def _smooth_l1(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    sigma = ctx.attr("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    ad = jnp.abs(diff)
    elem = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    ctx.set_output("Diff", diff)
    ctx.set_output("Out", jnp.sum(elem, axis=tuple(range(1, x.ndim)),
                                  keepdims=False).reshape(x.shape[0], 1))


@register_op("huber_loss")
def _huber_loss(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    delta = ctx.attr("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= delta, 0.5 * r * r,
                     delta * (ar - 0.5 * delta))
    ctx.set_output("Residual", r)
    ctx.set_output("Out", loss)


@register_op("hinge_loss", no_grad_slots=["Labels"])
def _hinge_loss(ctx):
    logits = ctx.input("Logits")
    labels = ctx.input("Labels")
    ctx.set_output("Loss",
                   jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0))


@register_op("log_loss", no_grad_slots=["Labels"])
def _log_loss(ctx):
    pred = ctx.input("Predicted")
    label = ctx.input("Labels")
    eps = ctx.attr("epsilon", 1e-4)
    loss = -label * jnp.log(pred + eps) \
        - (1.0 - label) * jnp.log(1.0 - pred + eps)
    ctx.set_output("Loss", loss)


@register_op("margin_rank_loss", no_grad_slots=["Label"])
def _margin_rank_loss(ctx):
    x1, x2 = ctx.input("X1"), ctx.input("X2")
    label = ctx.input("Label")
    margin = ctx.attr("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    ctx.set_output("Out", out)
    ctx.set_output("Activated", (out > 0).astype(x1.dtype))


@register_op("kldiv_loss", no_grad_slots=["Target"])
def _kldiv_loss(ctx):
    x = ctx.input("X")  # log-probabilities
    target = ctx.input("Target")
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    red = ctx.attr("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    ctx.set_output("Loss", loss)


# -- embeddings -------------------------------------------------------------

@register_op("lookup_table", no_grad_slots=["Ids"])
def _lookup_table(ctx):
    """Embedding lookup (reference: lookup_table_op.cc). Ids may carry a
    trailing [.., 1] dim like the reference's LoDTensor ids. With
    is_distributed under an active mesh, the table is row-sharded and
    gathered via shard_map + psum (parallel/sparse.py) — the ICI
    replacement for the reference's pserver prefetch path."""
    w = ctx.input("W")
    ids = ctx.input("Ids")
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    padding_idx = ctx.attr("padding_idx", -1)
    ids32 = ids.astype(jnp.int32)
    if ctx.attr("is_distributed", False) and \
            ctx.extra.get("mesh") is not None:
        from ..parallel.sparse import sharded_lookup
        out = sharded_lookup(w, ids32,
                             axis=ctx.attr("shard_axis", "model"),
                             mesh=ctx.extra["mesh"],
                             batch_axis=ctx.extra.get("feed_axis"))
    else:
        # explicit clip: jnp.take's default OOB mode is NaN-fill, and
        # the sharded path clips — keep the two paths identical
        out = jnp.take(w, ids32, axis=0, mode="clip")
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    ctx.set_output("Out", out)


@register_op("one_hot", no_grad_slots=["X"])
def _one_hot(ctx):
    x = ctx.input("X")
    depth = ctx.attr("depth")
    if x.shape and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    ctx.set_output("Out", jax.nn.one_hot(x.astype(jnp.int32), depth,
                                         dtype=jnp.float32))


@register_op("embedding_bag", no_grad_slots=["Ids"])
def _embedding_bag(ctx):
    w = ctx.input("W")
    ids = ctx.input("Ids")  # [batch, bag]
    emb = jnp.take(w, ids.astype(jnp.int32), axis=0)
    mode = ctx.attr("mode", "sum")
    out = emb.sum(axis=1) if mode == "sum" else emb.mean(axis=1)
    ctx.set_output("Out", out)


# -- attention / transformer helpers ---------------------------------------

@register_op("stack")
def _stack(ctx):
    xs = ctx.inputs("X")
    ctx.set_output("Y", jnp.stack(xs, axis=ctx.attr("axis", 0)))


@register_op("unstack")
def _unstack(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    num = x.shape[axis]
    parts = jnp.split(x, num, axis=axis)
    ctx.set_outputs("Y", [p.squeeze(axis) for p in parts])


@register_op("scaled_dot_product_attention", no_grad_slots=["Mask"])
def _sdpa(ctx):
    """Fused attention (TPU-native addition; the reference composes it from
    matmul/softmax in python/paddle/fluid/nets.py:312).

    Large shapes on TPU route to the Pallas flash-attention kernel
    (ops/pallas/flash_attention.py) — O(S) memory, online softmax; small
    shapes use the naive composition, which XLA fuses fine. Mask is a
    constant (no_grad_slots) on both paths; a *trainable* additive bias
    should call ops.pallas.flash_attention(bias_grad=True) directly.
    """
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    mask = ctx.input("Mask")
    causal = bool(ctx.attr("causal", False))

    # Sequence/context parallelism: attr seq_axis names a mesh axis the
    # sequence dim is sharded over (parallel/context_parallel.py).
    seq_axis = ctx.attr("seq_axis", None)
    mesh = ctx.extra.get("mesh") if ctx.extra else None
    if seq_axis and mesh is not None and seq_axis in mesh.axis_names:
        from ..parallel.context_parallel import sequence_parallel_attention
        kv_mask = None
        if mask is not None:
            if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
                kv_mask = mask[:, 0, 0, :]        # [b, Sk] key-row mask
            elif mask.ndim == 2:
                kv_mask = mask
            else:
                raise ValueError(
                    "sequence-parallel attention supports key-row masks "
                    "([b,1,1,Sk]); express causality via attr 'causal', "
                    f"got mask shape {mask.shape}")
        ctx.set_output("Out", sequence_parallel_attention(
            q, k, v, mesh, axis=seq_axis,
            impl=ctx.attr("seq_impl", "ring"), causal=causal,
            kv_mask=kv_mask,
            batch_axis=ctx.attr("batch_axis", "data"),
            head_axis=ctx.attr("head_axis", "model")))
        return

    # Explicit softmax scale (attr "scale"): stamped by the rewrite
    # layer when it outlines a composed attention chain, preserving the
    # user's exact scaling; None keeps the standard 1/sqrt(d_key).
    sm_scale = ctx.attr("scale", None)
    sm_scale = None if sm_scale is None else float(sm_scale)

    use_flash = ctx.attr("use_flash", None)
    if use_flash and q.ndim != 4:
        # the flash kernel's layout is [B, H, S, D]; an outlined 3-D
        # attention keeps the (identical-math) naive composition
        use_flash = False
    if use_flash is None:
        # measured crossover on v5e (bf16, h8 d64, fwd+bwd, marginal
        # protocol): naive/XLA wins 1.56x at S=256, parity at S=512,
        # flash wins 2.5x at S=1024 and 5.6x at S=4096 — the S^2 score
        # materialization only starts to bind around 512. Round 2's
        # threshold of 128 routed the transformer bench's S=256 through
        # flash and cost it ~35% end-to-end (MFU_BREAKDOWN.md round 3).
        min_seq = int(os.environ.get("PADDLE_TPU_FLASH_MIN_SEQ", "512"))
        use_flash = (jax.default_backend() == "tpu" and q.ndim == 4
                     and q.shape[2] >= min_seq
                     and k.shape[2] >= min_seq)
    if use_flash:
        from .pallas import flash_attention
        ctx.set_output("Out", flash_attention(q, k, v, mask,
                                              causal=causal,
                                              sm_scale=sm_scale))
        return
    scale = sm_scale if sm_scale is not None \
        else 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if mask is not None:
        scores = scores + mask
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx.set_output("Out", jnp.einsum("...qk,...kd->...qd", probs, v))


# -- misc -------------------------------------------------------------------

@register_op("nce", no_grad_slots=["Label", "SampleWeight"])
def _nce(ctx):
    """Noise-contrastive estimation loss (reference: nce_op.cc), with
    deterministic uniform sampling of negatives."""
    x = ctx.input("Input")            # [N, D]
    label = ctx.input("Label")        # [N, 1] int
    w = ctx.input("Weight")           # [V, D]
    b = ctx.input("Bias")             # [V]
    num_neg = ctx.attr("num_neg_samples", 10)
    num_total = w.shape[0]
    key = _op_key(ctx)
    neg = jax.random.randint(key, (x.shape[0], num_neg), 0, num_total)
    lab = label.reshape(-1).astype(jnp.int32)

    def logit(ids):
        ww = jnp.take(w, ids, axis=0)       # [..., D]
        bb = jnp.take(b, ids, axis=0) if b is not None else 0.0
        return jnp.einsum("nd,n...d->n...", x, ww) + bb

    pos_logit = logit(lab[:, None]).reshape(-1)      # [N]
    neg_logit = logit(neg)                           # [N, num_neg]
    pos_loss = jax.nn.softplus(-pos_logit)
    neg_loss = jax.nn.softplus(neg_logit).sum(axis=1)
    ctx.set_output("Cost", (pos_loss + neg_loss).reshape(-1, 1))


@register_op("im2sequence", no_grad_slots=[])
def _im2sequence(ctx):
    x = ctx.input("X")  # NCHW
    kh, kw = _pair(ctx.attr("kernels", [1, 1]))
    sh, sw = _pair(ctx.attr("strides", [1, 1]))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [n, c*kh*kw, oh, ow]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    ctx.set_output("Out", out)


# -- remaining pool/conv surface (reference: pool_op.cc 3D variants,
# pool_with_index_op.cc, unpool_op.cc, spp_op.cc, roi_pool_op.cc,
# conv_transpose_op.cc 3D) --------------------------------------------------

def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


@register_op("pool3d")
def _pool3d(ctx):
    x = ctx.input("X")  # NCDHW
    ptype = ctx.attr("pooling_type", "max")
    k = _triple(ctx.attr("ksize", [2, 2, 2]))
    s = _triple(ctx.attr("strides", [2, 2, 2]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        k = tuple(x.shape[2:5])
        s = k
        p = (0, 0, 0)
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides,
                                    pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                       pads)
        if ctx.attr("exclusive", True) and any(p):
            counts = jax.lax.reduce_window(jnp.ones_like(x), 0.0,
                                           jax.lax.add, dims, strides, pads)
            out = summed / counts
        else:
            out = summed / (k[0] * k[1] * k[2])
    ctx.set_output("Out", out)


def _pool_with_index(x, k, s, p):
    """Max pool + flat argmax index per window via conv patches
    (TPU-friendly: one gather-free argmax over the window axis)."""
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    oh, ow = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, k[0] * k[1], oh, ow)
    # positions of each window element in the (padded) input
    ky, kx = jnp.meshgrid(jnp.arange(k[0]), jnp.arange(k[1]), indexing="ij")
    ky, kx = ky.reshape(-1), kx.reshape(-1)               # [K]
    oy = jnp.arange(oh) * s[0] - p[0]                     # [oh]
    ox = jnp.arange(ow) * s[1] - p[1]                     # [ow]
    rows = oy[None, :] + ky[:, None]                      # [K, oh]
    cols = ox[None, :] + kx[:, None]                      # [K, ow]
    valid = ((rows >= 0) & (rows < h))[:, :, None] & \
            ((cols >= 0) & (cols < w))[:, None, :]        # [K, oh, ow]
    patches = jnp.where(valid[None, None], patches, -jnp.inf)
    widx = jnp.argmax(patches, axis=2)                    # [n, c, oh, ow]
    out = jnp.max(patches, axis=2)
    flat = rows[:, :, None] * w + cols[:, None, :]        # [K, oh, ow]
    index = jnp.take_along_axis(
        jnp.broadcast_to(flat[None, None], (n, c) + flat.shape),
        widx[:, :, None], axis=2).squeeze(2)
    return out, index.astype(jnp.int32)


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ctx):
    x = ctx.input("X")
    k = _pair(ctx.attr("ksize", [2, 2]))
    s = _pair(ctx.attr("strides", [2, 2]))
    p = _pair(ctx.attr("paddings", [0, 0]))
    if ctx.attr("global_pooling", False):
        k, s, p = (x.shape[2], x.shape[3]), (x.shape[2], x.shape[3]), (0, 0)
    out, index = _pool_with_index(x, k, s, p)
    ctx.set_output("Out", out)
    ctx.set_output("Mask", index)


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ctx):
    """3-D variant: loop the 2-D patch trick over depth slices of the
    pooling window (D is small: the kernel depth)."""
    x = ctx.input("X")  # NCDHW
    k = _triple(ctx.attr("ksize", [2, 2, 2]))
    s = _triple(ctx.attr("strides", [2, 2, 2]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    if ctx.attr("global_pooling", False):
        k = tuple(x.shape[2:5]); s = k; p = (0, 0, 0)
    n, c, d, h, w = x.shape
    od = (d + 2 * p[0] - k[0]) // s[0] + 1
    best_val, best_idx = None, None
    for kd in range(k[0]):
        zs = jnp.arange(od) * s[0] - p[0] + kd          # depth slice per od
        valid = (zs >= 0) & (zs < d)
        sl = x[:, :, jnp.clip(zs, 0, d - 1)]             # [n, c, od, h, w]
        sl = jnp.where(valid[None, None, :, None, None], sl, -jnp.inf)
        # apply 2-D pooling per depth slice by folding od into batch
        v2f = sl.transpose(0, 2, 1, 3, 4).reshape(n * od, c, h, w)
        out2, idx2 = _pool_with_index(v2f, k[1:], s[1:], p[1:])
        oh, ow = out2.shape[2], out2.shape[3]
        out2 = out2.reshape(n, od, c, oh, ow).transpose(0, 2, 1, 3, 4)
        idx2 = idx2.reshape(n, od, c, oh, ow).transpose(0, 2, 1, 3, 4)
        flat = jnp.clip(zs, 0, d - 1)[None, None, :, None, None] * (h * w) \
            + idx2
        if best_val is None:
            best_val, best_idx = out2, flat
        else:
            take = out2 > best_val
            best_val = jnp.where(take, out2, best_val)
            best_idx = jnp.where(take, flat, best_idx)
    ctx.set_output("Out", best_val)
    ctx.set_output("Mask", best_idx.astype(jnp.int32))


@register_op("unpool", no_grad_slots=["Indices"])
def _unpool(ctx):
    """Max-unpool with indices from max_pool2d_with_index (reference:
    unpool_op.cc): scatter pooled values back to their argmax positions."""
    x = ctx.input("X")            # [n, c, oh, ow]
    indices = ctx.input("Indices")
    oh_ow = ctx.attr("unpool_size", None)
    if oh_ow is None:
        ksize = _pair(ctx.attr("ksize", [2, 2]))
        strides = _pair(ctx.attr("strides", ksize))
        h = (x.shape[2] - 1) * strides[0] + ksize[0]
        w = (x.shape[3] - 1) * strides[1] + ksize[1]
    else:
        h, w = _pair(oh_ow)
    n, c = x.shape[0], x.shape[1]
    flat = jnp.zeros((n, c, h * w), x.dtype)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    flat = flat.at[jnp.arange(n)[:, None, None],
                   jnp.arange(c)[None, :, None], idx].set(vals)
    ctx.set_output("Out", flat.reshape(n, c, h, w))


@register_op("spp")
def _spp(ctx):
    """Spatial pyramid pooling (reference: spp_op.cc): concat flattened
    adaptive pools at 1x1, 2x2, ... 2^(L-1) bins."""
    x = ctx.input("X")
    levels = ctx.attr("pyramid_height", 3)
    ptype = ctx.attr("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for lv in range(levels):
        bins = 2 ** lv
        kh, kw = -(-h // bins), -(-w // bins)  # ceil
        sh, sw = kh, kw
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        dims, strides = (1, 1, kh, kw), (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if ptype == "max":
            o = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                      strides, pads)
        else:
            o = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                      pads) / (kh * kw)
        outs.append(o[:, :, :bins, :bins].reshape(n, -1))
    ctx.set_output("Out", jnp.concatenate(outs, axis=1))


@register_op("roi_pool", no_grad_slots=["ROIs"])
def _roi_pool(ctx):
    """Max pooling over regions of interest (reference: roi_pool_op.cc).
    ROIs: [R, 5] = (batch_idx, x1, y1, x2, y2) in input scale; static
    output [R, C, PH, PW] via per-bin masked max (TPU: no dynamic shapes)."""
    x = ctx.input("X")            # [n, c, h, w]
    rois = ctx.input("ROIs")      # [R, 5] float
    ph = ctx.attr("pooled_height", 1)
    pw = ctx.attr("pooled_width", 1)
    scale = ctx.attr("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[b]                                    # [c, h, w]
        ys = jnp.arange(h)[None, :]                   # [1, h]
        xs = jnp.arange(w)[None, :]                   # [1, w]
        binh = rh / ph
        binw = rw / pw
        hs = jnp.floor(y1 + jnp.arange(ph)[:, None] * binh).astype(jnp.int32)
        he = jnp.ceil(y1 + (jnp.arange(ph)[:, None] + 1) * binh).astype(jnp.int32)
        ws_ = jnp.floor(x1 + jnp.arange(pw)[:, None] * binw).astype(jnp.int32)
        we = jnp.ceil(x1 + (jnp.arange(pw)[:, None] + 1) * binw).astype(jnp.int32)
        mh = (ys >= hs) & (ys < he) & (ys >= 0) & (ys < h)   # [ph, h]
        mw = (xs >= ws_) & (xs < we) & (xs >= 0) & (xs < w)  # [pw, w]
        m = mh[:, None, :, None] & mw[None, :, None, :]      # [ph, pw, h, w]
        masked = jnp.where(m[None], img[:, None, None], -jnp.inf)
        out = masked.max(axis=(-1, -2))                      # [c, ph, pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    ctx.set_output("Out", jax.vmap(one_roi)(rois.astype(jnp.float32)))


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx):
    x = ctx.input("Input")
    w = ctx.input("Filter")  # [in_c, out_c, kd, kh, kw]
    s = _triple(ctx.attr("strides", [1, 1, 1]))
    p = _triple(ctx.attr("paddings", [0, 0, 0]))
    d = _triple(ctx.attr("dilations", [1, 1, 1]))
    ctx.set_output("Output", _conv_transpose_impl(x, w, s, p, d, 3))


def _interp_impl(ctx, method: str):
    """NCHW resize (reference capability: legacy gserver bilinear_interp /
    upsample / resize layers; later-fluid bilinear_interp_op). out size
    from out_h/out_w attrs or a scale factor."""
    x = ctx.input("X")
    n, c, h, w = x.shape
    out_h = int(ctx.attr("out_h", 0) or 0)
    out_w = int(ctx.attr("out_w", 0) or 0)
    scale = float(ctx.attr("scale", 0.0) or 0.0)
    if out_h <= 0 or out_w <= 0:
        if scale <= 0:
            raise ValueError(
                f"{ctx.op.type} needs positive out_h/out_w attrs or a "
                "positive scale attr")
        out_h, out_w = int(h * scale), int(w * scale)
    out = jax.image.resize(x, (n, c, out_h, out_w), method=method)
    ctx.set_output("Out", out.astype(x.dtype))


@register_op("bilinear_interp")
def _bilinear_interp(ctx):
    _interp_impl(ctx, "bilinear")


@register_op("nearest_interp")
def _nearest_interp(ctx):
    _interp_impl(ctx, "nearest")


@register_op("sampling_id", no_grad_slots=["X"])
def _sampling_id(ctx):
    """Sample one class id per row from a probability matrix (reference:
    legacy sampling_id layer; generation-time stochastic decode)."""
    x = ctx.input("X")  # [batch, n_classes] probabilities
    logits = jnp.log(jnp.maximum(x, 1e-20))
    ids = jax.random.categorical(_op_key(ctx), logits, axis=-1)
    ctx.set_output("Out", ids.astype(jnp.int64))



@register_op("mdlstm")
def _mdlstm(ctx):
    """2-D multi-dimensional LSTM (reference: MDLstmLayer,
    paddle/gserver/layers/MDLstmLayer.cpp — grid recurrence where each
    cell sees the states of its LEFT and TOP neighbours). TPU-native
    realization: lax.scan over rows carrying the whole previous row's
    (h, c); an inner scan over columns carries (h_left, c_left). Gate
    pre-activations from the input projection come in as X [b,H,W,5h]
    (i, f_left, f_top, o, g); recurrent weights Wl/Wt are [h, 5h]."""
    x = ctx.input("X")                       # [b, H, W, 5h]
    wl = ctx.input("WeightLeft")             # [h, 5h]
    wt = ctx.input("WeightTop")              # [h, 5h]
    b_, hgt, wid, five_h = x.shape
    hsz = five_h // 5

    def split_gates(g):
        i, fl, ft, o, c = jnp.split(g, 5, axis=-1)
        return (jax.nn.sigmoid(i), jax.nn.sigmoid(fl),
                jax.nn.sigmoid(ft), jax.nn.sigmoid(o), jnp.tanh(c))

    def row_step(row_carry, x_row):
        h_top, c_top = row_carry                 # [b, W, h] each

        def col_step(col_carry, inp):
            h_left, c_left = col_carry           # [b, h]
            x_cell, h_up, c_up = inp             # [b,5h], [b,h], [b,h]
            gates = x_cell + h_left @ wl + h_up @ wt
            i, fl, ft, o, g = split_gates(gates)
            c = i * g + fl * c_left + ft * c_up
            h = o * jnp.tanh(c)
            return (h, c), (h, c)

        zeros = jnp.zeros((b_, hsz), x.dtype)
        (_, _), (h_row, c_row) = jax.lax.scan(
            col_step, (zeros, zeros),
            (x_row.transpose(1, 0, 2),           # [W, b, 5h]
             h_top.transpose(1, 0, 2), c_top.transpose(1, 0, 2)))
        h_row = h_row.transpose(1, 0, 2)         # [b, W, h]
        c_row = c_row.transpose(1, 0, 2)
        return (h_row, c_row), h_row

    zeros_row = jnp.zeros((b_, wid, hsz), x.dtype)
    (_, _), hs = jax.lax.scan(row_step, (zeros_row, zeros_row),
                              x.transpose(1, 0, 2, 3))  # [H, b, W, 5h]
    ctx.set_output("Out", hs.transpose(1, 0, 2, 3))     # [b, H, W, h]
