"""Beam search ops, TPU static-shape design.

Reference parity: paddle/fluid/operators/beam_search_op.{h,cc} and
beam_search_decode_op.cc. The reference keeps a variable number of live
beams per source in two-level LoD tensors, prunes finished beams, and
reconstructs sentences by matching LoD offsets. That shape-dynamic design
cannot compile to one XLA program, so here every step keeps a FIXED
[batch, beam] lane grid:

- finished lanes (pre_id == end_id) are frozen: their only candidate is
  (end_id, pre_score), so they ride along at constant score instead of
  being pruned;
- selection is one top_k over the [batch, beam*cand] flattened totals;
- a parent_idx output records each selected lane's source lane, and
  beam_search_decode walks parents backward through the step arrays —
  replacing the reference's LoD-offset matching.

Whole decode loops (While + array ops + these) trace into a single
jitted program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _canon(pre_ids, pre_scores, ids, scores, beam_size):
    """Normalize flat [B*K, ...] inputs to [B, K, ...]; return a flag to
    restore the caller's convention on output."""
    flat = ids.ndim == 2
    if flat:
        b = ids.shape[0] // beam_size
        ids = ids.reshape(b, beam_size, -1)
        scores = scores.reshape(b, beam_size, -1)
    pre_ids = pre_ids.reshape(ids.shape[0], beam_size)
    if pre_scores is not None:
        pre_scores = pre_scores.reshape(ids.shape[0], beam_size)
    return pre_ids, pre_scores, ids, scores, flat


@register_op("beam_search", no_grad_slots=["pre_ids", "pre_scores", "ids",
                                           "scores"])
def _beam_search(ctx):
    """One expansion step: totals = pre_scores + scores (or scores alone
    when `is_accumulated`), frozen lanes for finished beams, one top_k
    over beam*cand."""
    k = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))
    is_acc = ctx.attr("is_accumulated", False)
    pre_ids, pre_scores, ids, scores, flat = _canon(
        ctx.input("pre_ids"), ctx.input("pre_scores"),
        ctx.input("ids"), ctx.input("scores"), k)
    b, _, c = ids.shape
    neg = jnp.asarray(jnp.finfo(scores.dtype).min / 2, scores.dtype)

    if pre_scores is None:
        totals = scores
        # no cumulative score to freeze at — rank dead lanes last so
        # they can never crowd out live hypotheses
        frozen = jnp.full((b, k), neg, scores.dtype)
    else:
        totals = scores if is_acc else pre_scores[..., None] + scores
        frozen = pre_scores
    finished = pre_ids.astype(jnp.int32) == end_id
    # finished lane -> exactly one candidate: (end_id, frozen score)
    totals = jnp.where(finished[..., None], neg, totals)
    totals = totals.at[..., 0].set(
        jnp.where(finished, frozen, totals[..., 0]))
    ids_eff = jnp.where(finished[..., None],
                        jnp.asarray(end_id, ids.dtype), ids)

    top_s, top_i = jax.lax.top_k(totals.reshape(b, k * c), k)
    parent = (top_i // c).astype(jnp.int32)
    sel_ids = jnp.take_along_axis(ids_eff.reshape(b, k * c), top_i, axis=1)

    if flat:
        sel_ids = sel_ids.reshape(b * k, 1)
        top_s = top_s.reshape(b * k, 1)
        parent = parent.reshape(b * k, 1)
    ctx.set_output("selected_ids", sel_ids)
    ctx.set_output("selected_scores", top_s)
    ctx.set_output("parent_idx", parent)


@register_op("beam_search_decode", no_grad_slots=["Ids", "Scores",
                                                  "ParentIdx", "Length"])
def _beam_search_decode(ctx):
    """Backtrack the step arrays into final sequences: lane order at the
    last valid step is already score-sorted (top_k), so walk parents
    from there. Output SentenceIds [B, K, T] padded with end_id,
    SentenceScores [B, K]."""
    k = int(ctx.attr("beam_size"))
    end_id = int(ctx.attr("end_id"))
    ids = ctx.input("Ids")          # [T, B, K] or [T, B*K, 1]
    scores = ctx.input("Scores")
    parents = ctx.input("ParentIdx")
    length = ctx.input("Length")    # scalar valid-step count (optional)
    t_cap = ids.shape[0]
    ids = ids.reshape(t_cap, -1, k)
    scores = scores.reshape(t_cap, -1, k)
    b = ids.shape[1]
    if parents is None:
        parents = jnp.tile(jnp.arange(k, dtype=jnp.int32)[None, None],
                           (t_cap, b, 1))
    else:
        parents = parents.reshape(t_cap, -1, k).astype(jnp.int32)
    n_valid = jnp.asarray(t_cap, jnp.int32) if length is None \
        else length.reshape(()).astype(jnp.int32)

    last = n_valid - 1
    sent_scores = jax.lax.dynamic_index_in_dim(scores, last, 0,
                                               keepdims=False)  # [B, K]
    lane0 = jnp.tile(jnp.arange(k, dtype=jnp.int32)[None], (b, 1))

    def step(lane, t):
        valid = t < n_valid
        ids_t = jax.lax.dynamic_index_in_dim(ids, t, 0, keepdims=False)
        par_t = jax.lax.dynamic_index_in_dim(parents, t, 0, keepdims=False)
        tok = jnp.take_along_axis(ids_t, lane, axis=1)
        nxt = jnp.take_along_axis(par_t, lane, axis=1)
        tok = jnp.where(valid, tok, jnp.asarray(end_id, tok.dtype))
        nxt = jnp.where(valid, nxt, lane)
        return nxt, tok

    ts = jnp.arange(t_cap - 1, -1, -1, dtype=jnp.int32)
    _, toks = jax.lax.scan(step, lane0, ts)          # [T, B, K] reversed
    sent_ids = jnp.flip(toks, axis=0).transpose(1, 2, 0)  # [B, K, T]
    ctx.set_output("SentenceIds", sent_ids)
    ctx.set_output("SentenceScores", sent_scores)
