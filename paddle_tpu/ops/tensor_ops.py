"""Tensor-manipulation ops: reshape, transpose, concat, split, slicing,
gather/scatter, padding, tiling.

Reference parity: paddle/fluid/operators/{reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, slice_op.cc, gather_op.cc, scatter_op.cc,
pad_op.cc, expand_op.cc, squeeze/unsqueeze, lod_reset_op.cc}.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import RaggedPair
from ..core.registry import register_op


@register_op("reshape")
def _reshape(ctx):
    x = ctx.input("X")
    shape = list(ctx.attr("shape"))
    # Reference semantics: 0 means copy dim from input (reshape_op.cc).
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)
             ] if any(s == 0 for s in shape) else shape
    ctx.set_output("Out", x.reshape(shape))


@register_op("reshape2")
def _reshape2(ctx):
    _reshape(ctx)
    ctx.set_output("XShape", jnp.zeros((0,), jnp.int64))


@register_op("transpose")
def _transpose(ctx):
    ctx.set_output("Out", jnp.transpose(ctx.input("X"), ctx.attr("axis")))


@register_op("transpose2")
def _transpose2(ctx):
    _transpose(ctx)
    ctx.set_output("XShape", jnp.zeros((0,), jnp.int64))


@register_op("concat")
def _concat(ctx):
    ctx.set_output("Out", jnp.concatenate(ctx.inputs("X"),
                                          axis=ctx.attr("axis", 0)))


@register_op("split")
def _split(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 0)
    sections = ctx.attr("sections")
    num = ctx.attr("num", 0)
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num, axis=axis)
    ctx.set_outputs("Out", parts)


@register_op("squeeze")
def _squeeze(ctx):
    x = ctx.input("X")
    axes = ctx.attr("axes", [])
    if axes:
        ctx.set_output("Out", jnp.squeeze(x, axis=tuple(axes)))
    else:
        ctx.set_output("Out", jnp.squeeze(x))


@register_op("unsqueeze")
def _unsqueeze(ctx):
    x = ctx.input("X")
    for ax in sorted(ctx.attr("axes")):
        x = jnp.expand_dims(x, ax)
    ctx.set_output("Out", x)


@register_op("flatten")
def _flatten(ctx):
    x = ctx.input("X")
    axis = ctx.attr("axis", 1)
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    ctx.set_output("Out", x.reshape(lead, -1))


@register_op("slice")
def _slice(ctx):
    x = ctx.input("Input")
    axes = ctx.attr("axes")
    starts = ctx.attr("starts")
    ends = ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    ctx.set_output("Out", x[tuple(idx)])


@register_op("strided_slice")
def _strided_slice(ctx):
    x = ctx.input("Input")
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(ctx.attr("axes"), ctx.attr("starts"),
                              ctx.attr("ends"), ctx.attr("strides")):
        idx[ax] = slice(st, en, sd)
    ctx.set_output("Out", x[tuple(idx)])


@register_op("gather", no_grad_slots=["Index"])
def _gather(ctx):
    x = ctx.input("X")
    index = ctx.input("Index").astype(jnp.int32)
    if index.ndim == 2 and index.shape[-1] == 1:
        index = index.reshape(-1)
    ctx.set_output("Out", jnp.take(x, index, axis=0))


@register_op("gather_nd", no_grad_slots=["Index"])
def _gather_nd(ctx):
    x = ctx.input("X")
    index = ctx.input("Index").astype(jnp.int32)
    ctx.set_output("Out", x[tuple(jnp.moveaxis(index, -1, 0))])


@register_op("scatter", no_grad_slots=["Ids"])
def _scatter(ctx):
    x = ctx.input("X")
    ids = ctx.input("Ids").astype(jnp.int32).reshape(-1)
    updates = ctx.input("Updates")
    if ctx.attr("overwrite", True):
        out = x.at[ids].set(updates)
    else:
        out = x.at[ids].add(updates)
    ctx.set_output("Out", out)


@register_op("pad")
def _pad(ctx):
    x = ctx.input("X")
    p = ctx.attr("paddings")  # [before0, after0, before1, after1, ...]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output("Out", jnp.pad(x, pairs, constant_values=ctx.attr(
        "pad_value", 0.0)))


@register_op("pad2d")
def _pad2d(ctx):
    x = ctx.input("X")  # NCHW
    p = ctx.attr("paddings", [0, 0, 0, 0])  # [top, bottom, left, right]
    mode = ctx.attr("mode", "constant")
    pairs = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]))
    if mode == "constant":
        out = jnp.pad(x, pairs, constant_values=ctx.attr("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pairs, mode="reflect")
    else:
        out = jnp.pad(x, pairs, mode="edge")
    ctx.set_output("Out", out)


@register_op("expand")
def _expand(ctx):
    x = ctx.input("X")
    times = ctx.attr("expand_times")
    ctx.set_output("Out", jnp.tile(x, times))


@register_op("expand_as")
def _expand_as(ctx):
    x = ctx.input("X")
    y = ctx.input("Y")
    ctx.set_output("Out", jnp.broadcast_to(x, y.shape))


@register_op("tile")
def _tile(ctx):
    ctx.set_output("Out", jnp.tile(ctx.input("X"),
                                   ctx.attr("repeat_times")))


@register_op("reverse")
def _reverse(ctx):
    ctx.set_output("Out", jnp.flip(ctx.input("X"),
                                   axis=tuple(ctx.attr("axis"))))


@register_op("roll")
def _roll(ctx):
    ctx.set_output("Out", jnp.roll(ctx.input("X"), ctx.attr("shifts"),
                                   axis=tuple(ctx.attr("axis"))))


@register_op("where", no_grad_slots=["Condition"])
def _where(ctx):
    cond = ctx.input("Condition")
    x, y = ctx.input("X"), ctx.input("Y")
    ctx.set_output("Out", jnp.where(cond, x, y))


@register_op("masked_select", no_grad_slots=["Mask"])
def _masked_select(ctx):
    # Dynamic-size output is hostile to XLA; reference parity is provided
    # via a fixed-capacity variant: output is padded to input size with a
    # count of valid elements, the TPU-native contract for dynamic shapes.
    x = ctx.input("X")
    mask = ctx.input("Mask")
    flat_x = x.reshape(-1)
    flat_m = mask.reshape(-1)
    order = jnp.argsort(~flat_m, stable=True)
    ctx.set_output("Out", jnp.where(jnp.sort(~flat_m, stable=True), 0,
                                    flat_x[order]))
    ctx.set_output("Count", jnp.sum(flat_m).astype(jnp.int64))


@register_op("lod_reset", no_grad_slots=["Y"], ragged_aware=True)
def _lod_reset(ctx):
    """Re-segment flat sequence steps with new lengths (reference:
    lod_reset_op.cc: same data, new LoD). In the padded representation
    that means REPACKING the flat step rows into [num_seq, T, ...] —
    just attaching new lengths to the old layout would mis-segment."""
    x = ctx.input("X")
    if isinstance(x, RaggedPair):
        # flatten to ordered valid steps first (stable mask compaction)
        b, t = x.data.shape[:2]
        flat = x.data.reshape((b * t,) + x.data.shape[2:])
        valid = (jnp.arange(t)[None, :] < x.lengths[:, None]).reshape(-1)
        flat = flat[jnp.argsort(~valid, stable=True)]
    else:
        flat = x
    n = flat.shape[0]
    y = ctx.input("Y")
    if y is not None:
        if isinstance(y, RaggedPair):
            lengths = y.lengths
            t_out = y.data.shape[1]
        else:  # dense int vector of new lengths; bound T by step count
            lengths = y.reshape(-1).astype(jnp.int32)
            t_out = n
    else:
        target = ctx.attr("target_lod")
        lens_py = [target[i + 1] - target[i]
                   for i in range(len(target) - 1)]
        lengths = jnp.asarray(lens_py, jnp.int32)
        t_out = max(lens_py) if lens_py else 1
    starts = jnp.cumsum(lengths) - lengths
    pos = jnp.arange(t_out)
    idx = jnp.clip(starts[:, None] + pos[None, :], 0, n - 1)
    padded = flat[idx]
    mask = (pos[None, :] < lengths[:, None])
    mask = mask.reshape(mask.shape + (1,) * (padded.ndim - 2))
    ctx.set_output("Out", RaggedPair(padded * mask.astype(padded.dtype),
                                     lengths.astype(jnp.int32)))


@register_op("linspace", no_grad_slots=["Start", "Stop", "Num"])
def _linspace(ctx):
    start = ctx.attr("start", 0.0)
    stop = ctx.attr("stop", 1.0)
    num = ctx.attr("num", 10)
    ctx.set_output("Out", jnp.linspace(start, stop, num))


@register_op("range", no_grad_slots=["Start", "End", "Step"])
def _range(ctx):
    ctx.set_output("Out", jnp.arange(ctx.attr("start", 0),
                                     ctx.attr("end"),
                                     ctx.attr("step", 1),
                                     dtype=jnp.int64
                                     if isinstance(ctx.attr("start", 0), int)
                                     else jnp.float32))


@register_op("diag")
def _diag(ctx):
    ctx.set_output("Out", jnp.diag(ctx.input("Diagonal")))


@register_op("eye")
def _eye(ctx):
    ctx.set_output("Out", jnp.eye(ctx.attr("num_rows"),
                                  ctx.attr("num_columns")))


@register_op("multiplex", no_grad_slots=["Ids"])
def _multiplex(ctx):
    """Row-wise select among candidate tensors by index (reference:
    multiplex_op.cc): Out[i] = X[Ids[i]][i]."""
    ids = ctx.input("Ids").reshape(-1).astype(jnp.int32)
    xs = jnp.stack(ctx.inputs("X"), axis=0)      # [k, n, ...]
    n = xs.shape[1]
    ctx.set_output("Out", xs[ids, jnp.arange(n)])


@register_op("crop", no_grad_slots=["Y", "Offsets"])
def _crop(ctx):
    """Crop X at `offsets` to the shape of Y (or the `shape` attr);
    offsets may also arrive as a runtime Offsets tensor which overrides
    the attr (reference: crop_op.cc)."""
    x = ctx.input("X")
    y = ctx.input("Y")
    shape = list(y.shape) if y is not None else list(ctx.attr("shape"))
    if len(shape) != x.ndim:
        raise ValueError(f"crop shape rank {len(shape)} != input rank "
                         f"{x.ndim}")
    off_in = ctx.input("Offsets")
    if off_in is not None:
        starts = off_in.reshape(-1).astype(jnp.int32)
        ctx.set_output("Out", jax.lax.dynamic_slice(
            x, [starts[i] for i in range(x.ndim)], shape))
        return
    offsets = list(ctx.attr("offsets") or [])
    offsets += [0] * (x.ndim - len(offsets))
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    ctx.set_output("Out", x[idx])


@register_op("scale_sub_region", no_grad_slots=["Indices"])
def _scale_sub_region(ctx):
    """Scale a per-sample sub-region of a [b, C, H, W] feature map by a
    constant (reference: ScaleSubRegionLayer / scale_sub_region_op.cc;
    Indices holds 1-based inclusive [c1, c2, h1, h2, w1, w2] per
    sample). Mask built by broadcast range-compares so shapes stay
    static under jit."""
    x = ctx.input("X")
    idx = ctx.input("Indices").astype(jnp.int32)  # [b, 6], 1-based
    value = ctx.attr("value", 1.0)
    _, c, h, w = x.shape
    rc = jnp.arange(1, c + 1)
    rh = jnp.arange(1, h + 1)
    rw = jnp.arange(1, w + 1)
    mc = (rc[None, :] >= idx[:, 0:1]) & (rc[None, :] <= idx[:, 1:2])
    mh = (rh[None, :] >= idx[:, 2:3]) & (rh[None, :] <= idx[:, 3:4])
    mw = (rw[None, :] >= idx[:, 4:5]) & (rw[None, :] <= idx[:, 5:6])
    mask = (mc[:, :, None, None] & mh[:, None, :, None]
            & mw[:, None, None, :])
    ctx.set_output("Out", jnp.where(mask, x * value, x))
