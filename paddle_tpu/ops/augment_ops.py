"""Device-side input augmentation ops.

Image augmentation as PROGRAM ops, so XLA fuses random crop / flip /
normalize into the forward step itself (*Operator Fusion in XLA*,
PAPERS.md): the streaming input plane (reader/streaming.py) ships raw
uint8 batches straight from decode, and the float conversion +
augmentation math that used to burn reader-host CPU runs on the
accelerator — in bf16 if requested — where it fuses with the first
conv's input handling instead of occupying the input pipeline.

All three ops are deterministic under the program seed (each layer call
stamps a `seed` attr via next_seed(), folded with the step counter by
`_op_key`), so seeded training stays bit-reproducible. Inputs are data,
not parameters: X carries no gradient.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .core_ops import _op_key, jnp_dtype


@register_op("random_crop", no_grad_slots=["X"])
def _random_crop(ctx):
    """Per-sample random spatial crop of an NCHW batch to attr
    `shape` = [crop_h, crop_w], after optional zero `pad` on each
    spatial edge (the pad-then-crop recipe of ResNet training). Output
    shape is static — [N, C, crop_h, crop_w] — so the executable's
    signature does not depend on the random offsets."""
    x = ctx.input("X")
    if x.ndim != 4:
        raise ValueError(
            f"random_crop expects an NCHW batch, got rank {x.ndim}")
    crop_h, crop_w = ctx.attr("shape")
    pad = int(ctx.attr("pad", 0))
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    n, c, h, w = x.shape
    if crop_h > h or crop_w > w:
        raise ValueError(
            f"crop {crop_h}x{crop_w} larger than (padded) input "
            f"{h}x{w}")
    kh, kw = jax.random.split(_op_key(ctx))
    oy = jax.random.randint(kh, (n,), 0, h - crop_h + 1)
    ox = jax.random.randint(kw, (n,), 0, w - crop_w + 1)

    def crop_one(img, y0, x0):
        return jax.lax.dynamic_slice(img, (0, y0, x0),
                                     (c, crop_h, crop_w))

    ctx.set_output("Out", jax.vmap(crop_one)(x, oy, ox))


@register_op("random_flip", no_grad_slots=["X"])
def _random_flip(ctx):
    """Per-sample horizontal flip (last axis) with probability attr
    `prob` (default 0.5). prob=0 is the identity, prob=1 flips every
    sample — both still trace the same fused program."""
    x = ctx.input("X")
    prob = float(ctx.attr("prob", 0.5))
    flip = jax.random.bernoulli(_op_key(ctx), prob, (x.shape[0],))
    cond = flip.reshape((-1,) + (1,) * (x.ndim - 1))
    ctx.set_output("Out", jnp.where(cond, x[..., ::-1], x))


@register_op("image_normalize", no_grad_slots=["X"])
def _image_normalize(ctx):
    """(x * scale - mean) / std per channel, emitting attr `dtype`
    (default float32; "bfloat16" is the TPU training path). Input is
    typically the reader's raw uint8 CHW batch — the cast and the
    normalize arithmetic run in f32 on device and only the final
    narrow happens, so bf16 output loses no normalize precision and the
    decode host never touches float pixels at all."""
    x = ctx.input("X")
    if x.ndim != 4:
        raise ValueError(
            f"image_normalize expects an NCHW batch, got rank {x.ndim}")
    scale = float(ctx.attr("scale", 1.0))
    mean = jnp.asarray(ctx.attr("mean"), jnp.float32).reshape(1, -1, 1, 1)
    std = jnp.asarray(ctx.attr("std"), jnp.float32).reshape(1, -1, 1, 1)
    out_dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    xf = x.astype(jnp.float32)
    ctx.set_output("Out", ((xf * scale - mean) / std).astype(out_dtype))
