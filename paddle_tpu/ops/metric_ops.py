"""Metric ops: accuracy, auc, precision/recall — in-graph metrics as in the
reference (paddle/fluid/operators/{accuracy_op.cc, auc_op.cc,
precision_recall_op.cc}).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("accuracy", no_grad_slots=["Out", "Indices", "Label"])
def _accuracy(ctx):
    """Top-k accuracy. Inputs: Out (topk values), Indices (topk indices),
    Label [N, 1]."""
    indices = ctx.input("Indices")
    label = ctx.input("Label")
    lab = label.reshape(-1, 1).astype(indices.dtype)
    correct = jnp.any(indices == lab, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = indices.shape[0]
    ctx.set_output("Accuracy",
                   (num_correct.astype(jnp.float32) / total).reshape(()))
    ctx.set_output("Correct", num_correct.reshape(()))
    ctx.set_output("Total", jnp.asarray(total, jnp.int32).reshape(()))


@register_op("auc", no_grad_slots=["Predict", "Label"])
def _auc(ctx):
    """Threshold-bucketed AUC (single-batch; streaming accumulation is done
    by the python Evaluator as in the reference's stat vars)."""
    predict = ctx.input("Predict")  # [N, 2] or [N, 1] prob of positive
    label = ctx.input("Label").reshape(-1)
    pos_prob = predict[:, -1]
    num_thresholds = ctx.attr("num_thresholds", 200)
    thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
    pos = (label > 0)[None, :]
    pred_pos = pos_prob[None, :] >= thresholds[:, None]
    tp = jnp.sum(pred_pos & pos, axis=1).astype(jnp.float32)
    fp = jnp.sum(pred_pos & ~pos, axis=1).astype(jnp.float32)
    fn = jnp.sum(~pred_pos & pos, axis=1).astype(jnp.float32)
    tn = jnp.sum(~pred_pos & ~pos, axis=1).astype(jnp.float32)
    tpr = tp / jnp.maximum(tp + fn, 1e-12)
    fpr = fp / jnp.maximum(fp + tn, 1e-12)
    # trapezoidal area over the (sorted by fpr) curve
    order = jnp.argsort(fpr)
    fpr_s = fpr[order]
    tpr_s = tpr[order]
    auc = jnp.sum((fpr_s[1:] - fpr_s[:-1]) * (tpr_s[1:] + tpr_s[:-1]) / 2.0)
    ctx.set_output("AUC", auc.reshape(()))
    ctx.set_output("TPOut", tp)
    ctx.set_output("FPOut", fp)
    ctx.set_output("TNOut", tn)
    ctx.set_output("FNOut", fn)


@register_op("precision_recall", no_grad_slots=["MaxProbs", "Indices",
                                                "Labels", "Weights"])
def _precision_recall(ctx):
    indices = ctx.input("Indices").reshape(-1)
    labels = ctx.input("Labels").reshape(-1)
    num_classes = ctx.attr("class_number")
    pred = indices.astype(jnp.int32)
    lab = labels.astype(jnp.int32)
    onehot_p = jax.nn.one_hot(pred, num_classes)
    onehot_l = jax.nn.one_hot(lab, num_classes)
    tp = jnp.sum(onehot_p * onehot_l, axis=0)
    fp = jnp.sum(onehot_p * (1 - onehot_l), axis=0)
    fn = jnp.sum((1 - onehot_p) * onehot_l, axis=0)
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    macro = jnp.stack([precision.mean(), recall.mean(), f1.mean()])
    tp_a, fp_a, fn_a = tp.sum(), fp.sum(), fn.sum()
    micro_p = tp_a / jnp.maximum(tp_a + fp_a, 1e-12)
    micro_r = tp_a / jnp.maximum(tp_a + fn_a, 1e-12)
    micro_f = 2 * micro_p * micro_r / jnp.maximum(micro_p + micro_r, 1e-12)
    metrics = jnp.concatenate([macro, jnp.stack([micro_p, micro_r, micro_f])])
    ctx.set_output("Metrics", metrics)
    ctx.set_output("BatchMetrics", metrics)
    ctx.set_output("AccumMetrics", metrics)


@register_op("edit_distance", no_grad_slots=["Hyps", "Refs"], ragged_aware=True)
def _edit_distance(ctx):
    """Levenshtein distance between ragged hypothesis/reference int
    sequences (reference: edit_distance_op.cu) via a dense DP in-graph."""
    from ..core.lod import RaggedPair

    hyps = ctx.input("Hyps")
    refs = ctx.input("Refs")
    h = hyps if isinstance(hyps, RaggedPair) else RaggedPair(
        hyps, jnp.full((hyps.shape[0],), hyps.shape[1], jnp.int32))
    r = refs if isinstance(refs, RaggedPair) else RaggedPair(
        refs, jnp.full((refs.shape[0],), refs.shape[1], jnp.int32))
    hd = h.data.reshape(h.data.shape[0], -1)
    rd = r.data.reshape(r.data.shape[0], -1)
    m, n = hd.shape[1], rd.shape[1]

    def per_pair(hrow, hlen, rrow, rlen):
        big = jnp.asarray(10**6, jnp.float32)
        row0 = jnp.arange(n + 1, dtype=jnp.float32)
        row0 = jnp.where(jnp.arange(n + 1) <= rlen, row0, big)

        def outer(i, carry):
            row, ans = carry
            ins_cost = jnp.where(i < hlen + 1, i + 0.0, big)

            def inner(j, icarry):
                row_new, prev_diag = icarry
                sub = prev_diag + (hrow[i - 1] != rrow[j - 1])
                val = jnp.minimum(jnp.minimum(row[j] + 1,
                                              row_new[j - 1] + 1), sub)
                val = jnp.where((i <= hlen) & (j <= rlen), val, big)
                return row_new.at[j].set(val), row[j]

            row_new = jnp.full((n + 1,), big).at[0].set(ins_cost)
            row_new, _ = jax.lax.fori_loop(
                1, n + 1, inner, (row_new, row[0]))
            # capture the answer at the hyp's TRUE length: rows past hlen
            # are all `big` (padding), so the final row is wrong whenever
            # hlen < m — snapshot when i == hlen instead
            ans = jnp.where(i == hlen, row_new[rlen.astype(jnp.int32)], ans)
            return row_new, ans

        ans0 = row0[rlen.astype(jnp.int32)]  # hlen == 0: all-insertions
        _, ans = jax.lax.fori_loop(1, m + 1, outer, (row0, ans0))
        return ans

    dist = jax.vmap(per_pair)(hd, h.lengths, rd, r.lengths)
    if ctx.attr("normalized", False):
        dist = dist / jnp.maximum(r.lengths.astype(jnp.float32), 1.0)
    ctx.set_output("Out", dist.reshape(-1, 1))
    ctx.set_output("SequenceNum", jnp.asarray(hd.shape[0], jnp.int64))


@register_op("chunk_eval", no_grad_slots=["Inference", "Label"],
             ragged_aware=True)
def _chunk_eval(ctx):
    """Chunking (NER-style) precision/recall/F1 over IOB-tagged ragged
    sequences (reference: chunk_eval_op.cc). Tags encode
    (chunk_type, tag_pos) as type * num_tag + pos with IOB pos: B=0, I=1.
    A predicted chunk counts as correct when its begin, end, and type all
    match a label chunk — computed here with a vectorized boundary match
    instead of the reference's per-sequence C++ walk."""
    inf = ctx.input("Inference")
    lab = ctx.input("Label")
    num_chunk_types = ctx.attr("num_chunk_types")
    scheme = ctx.attr("chunk_scheme", "IOB")
    # tag layouts per scheme (reference ChunkEvaluator.cpp:79-107):
    #   plain: 1 tag; IOB: B=0 I=1; IOE: I=0 E=1; IOBES: B I E S = 0..3
    num_tag_by_scheme = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}
    if scheme not in num_tag_by_scheme:
        raise ValueError(f"chunk_eval: unknown scheme {scheme!r} "
                         f"(one of {sorted(num_tag_by_scheme)})")
    from ..core.lod import RaggedPair as _RP
    if isinstance(inf, _RP):
        mask, inf, lab = inf.mask(), inf.data, lab.data
    else:
        mask = jnp.ones(inf.shape[:2], bool)
    if inf.ndim == 3:
        inf, lab = inf[..., 0], lab[..., 0]
    num_tag = num_tag_by_scheme[scheme]

    excluded = [int(t) for t in (ctx.attr("excluded_chunk_types") or [])]

    def chunks(tags):
        """Per-position begin/outside flags + chunk type. Every
        non-Other position belongs to some chunk (the reference's
        isChunkBegin returns True whenever prev is Other or the type
        changes), so only the same-type begin rule is scheme-specific
        (reference isChunkBegin, ChunkEvaluator.cpp:235-245)."""
        ctype = tags // num_tag
        pos = tags % num_tag
        outside = (tags < 0) | (tags >= num_chunk_types * num_tag)
        for ex in excluded:  # excluded types count as outside
            outside = outside | (ctype == ex)
        prev_t = jnp.concatenate(
            [jnp.full_like(ctype[:, :1], -1), ctype[:, :-1]], axis=1)
        prev_pos = jnp.concatenate(
            [jnp.zeros_like(pos[:, :1]), pos[:, :-1]], axis=1)
        prev_out = jnp.concatenate(
            [jnp.ones_like(outside[:, :1]), outside[:, :-1]], axis=1)
        if scheme == "plain":        # same-type run = one chunk
            same_begin = jnp.zeros_like(outside)
        elif scheme == "IOB":        # new chunk at every B
            same_begin = pos == 0
        elif scheme == "IOE":        # new chunk right after an E
            same_begin = prev_pos == 1
        else:                        # IOBES
            same_begin = (pos == 0) | (pos == 3) | \
                (((pos == 1) | (pos == 2)) &
                 ((prev_pos == 2) | (prev_pos == 3)))
        begin = ~outside & (prev_out | (ctype != prev_t) | same_begin)
        return begin & mask, outside | ~mask, ctype

    b_i, o_i, t_i = chunks(inf)
    b_l, o_l, t_l = chunks(lab)
    # chunk end at position k: in-chunk at k and (next is outside/begin/EOS)
    def ends(begin, outside):
        in_chunk = ~outside
        nxt_boundary = jnp.concatenate(
            [begin[:, 1:] | outside[:, 1:],
             jnp.ones_like(begin[:, :1])], axis=1)
        return in_chunk & nxt_boundary
    e_i = ends(b_i, o_i)
    e_l = ends(b_l, o_l)
    # a chunk is a (begin position, end position, type); correct when all
    # three coincide. Identify each chunk by its begin position: the end is
    # the first end-flag at or after the begin. Compare via segment ids:
    seg_i = jnp.cumsum(b_i.astype(jnp.int32), axis=1)
    seg_l = jnp.cumsum(b_l.astype(jnp.int32), axis=1)
    # positions agree on both segmentations and types and in/out status
    agree = (b_i == b_l) & (e_i == e_l) & (o_i == o_l) & \
        ((t_i == t_l) | o_i)
    # a label chunk is correct if every position from its begin to its end
    # agrees -> begin positions where cummin(agree) holds until end.
    # Compute per position: "disagreement seen since chunk begin":
    def correct_count(begin, end, outside):
        # running flag reset at each begin
        def step(carry, xs):
            b, a = xs
            ok = jnp.where(b, a, carry & a)
            return ok, ok
        agree_t = jnp.moveaxis(agree, 1, 0)
        begin_t = jnp.moveaxis(begin, 1, 0)
        _, ok_seq = jax.lax.scan(step, jnp.ones_like(agree[:, 0]),
                                 (begin_t, agree_t))
        ok_seq = jnp.moveaxis(ok_seq, 0, 1)
        return jnp.sum((ok_seq & end & ~outside).astype(jnp.int64))
    num_correct = correct_count(b_l, e_l, o_l)
    num_inf = jnp.sum(b_i.astype(jnp.int64))
    num_lab = jnp.sum(b_l.astype(jnp.int64))
    precision = num_correct / jnp.maximum(num_inf, 1)
    recall = num_correct / jnp.maximum(num_lab, 1)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    ctx.set_output("Precision", precision.astype(jnp.float32))
    ctx.set_output("Recall", recall.astype(jnp.float32))
    ctx.set_output("F1-Score", f1.astype(jnp.float32))
    ctx.set_output("NumInferChunks", num_inf)
    ctx.set_output("NumLabelChunks", num_lab)
    ctx.set_output("NumCorrectChunks", num_correct)


@register_op("positive_negative_pair", no_grad_slots=["Score", "Label",
                                                      "QueryID"])
def _positive_negative_pair(ctx):
    """Ranking pair statistics (reference: positive_negative_pair_op.cc):
    within each query, count (pos, neg) item pairs ordered correctly /
    incorrectly / tied by score."""
    score = ctx.input("Score").reshape(-1)
    label = ctx.input("Label").reshape(-1)
    qid = ctx.input("QueryID").reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    higher_label = label[:, None] > label[None, :]
    pair = same_q & higher_label          # (i better than j) pairs
    s_i = score[:, None]
    s_j = score[None, :]
    pos = jnp.sum((pair & (s_i > s_j)).astype(jnp.float32))
    neg = jnp.sum((pair & (s_i < s_j)).astype(jnp.float32))
    neu = jnp.sum((pair & (s_i == s_j)).astype(jnp.float32))
    acc_pos = ctx.input("AccumulatePositivePair")
    acc_neg = ctx.input("AccumulateNegativePair")
    acc_neu = ctx.input("AccumulateNeutralPair")
    if acc_pos is not None:
        pos, neg, neu = pos + acc_pos, neg + acc_neg, neu + acc_neu
    ctx.set_output("PositivePair", pos.reshape(1))
    ctx.set_output("NegativePair", neg.reshape(1))
    ctx.set_output("NeutralPair", neu.reshape(1))
