"""Core ops: feed/fetch, constants, random init, sum, cast, and the generic
vjp-based grad op that powers desc-level autodiff.

Reference parity: fill_constant/uniform_random/gaussian_random ops
(paddle/fluid/operators/fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc), sum_op.cc, cast_op.cc, scale_op.cc, assign_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.ir import OpDesc
from ..core.lod import RaggedNested, RaggedPair, RaggedTree
from ..core.registry import ExecutionContext, OpRegistry, register_op

_JNP_DTYPE = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "int8": jnp.int8, "int16": jnp.int16,
    "int32": jnp.int32, "int64": jnp.int64, "uint8": jnp.uint8,
    "bool": jnp.bool_,
}


def jnp_dtype(name: str):
    return _JNP_DTYPE[name]


# -- plumbing ---------------------------------------------------------------

@register_op("feed")
def _feed(ctx):
    # Feeding is handled by the Executor before tracing; kept for IR parity
    # with the reference's feed_op (feed_fetch_method.cc).
    x = ctx.input("X")
    if x is not None:
        ctx.set_output("Out", x)


@register_op("fetch")
def _fetch(ctx):
    x = ctx.input("X")
    if x is not None:
        ctx.set_output("Out", x)


@register_op("assign")
def _assign(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("share_data")
def _share_data(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("print")
def _print(ctx):
    # Debug printing inside a jitted graph (reference: print_op.cc).
    x = ctx.input("X")
    jax.debug.print(ctx.attr("message", "print_op") + ": {}", x)
    ctx.set_output("Out", x)


# -- constants / random -----------------------------------------------------

@register_op("fill_constant")
def _fill_constant(ctx):
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    value = ctx.attr("value", 0.0)
    ctx.set_output("Out", jnp.full(shape, value, dtype=dtype))


@register_op("fill_constant_like")
def _fill_constant_like(ctx):
    x = ctx.input("X")
    value = ctx.attr("value", 0.0)
    ctx.set_output("Out", jnp.full(jnp.shape(x), value, dtype=x.dtype))


@register_op("fill_constant_batch_size_like", no_grad_slots=["Input"])
def _fill_constant_batch_size_like(ctx):
    x = ctx.input("Input")
    shape = list(ctx.attr("shape"))
    shape[ctx.attr("output_dim_idx", 0)] = x.shape[ctx.attr("input_dim_idx", 0)]
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jnp.full(shape, ctx.attr("value", 0.0), dtype=dtype))


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx):
    ctx.set_output("Out", jnp.zeros_like(ctx.input("X")))


def _op_key(ctx):
    """Deterministic PRNG key for a random op: seed attr folded with step."""
    seed = ctx.attr("seed", 0) or 0
    prng = ctx.extra.get("prng")
    if prng is None:
        return jax.random.PRNGKey(seed)
    return prng(seed)


@register_op("uniform_random")
def _uniform_random(ctx):
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    out = jax.random.uniform(_op_key(ctx), tuple(shape), dtype=jnp.float32,
                             minval=lo, maxval=hi).astype(dtype)
    ctx.set_output("Out", out)


@register_op("gaussian_random")
def _gaussian_random(ctx):
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = mean + std * jax.random.normal(_op_key(ctx), tuple(shape),
                                         dtype=jnp.float32)
    ctx.set_output("Out", out.astype(dtype))


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ctx):
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = mean + std * jax.random.truncated_normal(
        _op_key(ctx), -2.0, 2.0, tuple(shape), dtype=jnp.float32)
    ctx.set_output("Out", out.astype(dtype))


@register_op("assign_value")
def _assign_value(ctx):
    import numpy as _np
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    vals = _np.asarray(ctx.attr("values"), dtype=dtype).reshape(shape)
    ctx.set_output("Out", jnp.asarray(vals))


@register_op("fill")
def _fill(ctx):
    """Fill Out with the literal `value` list (reference: fill_op.cc)."""
    import numpy as _np
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    vals = _np.asarray(ctx.attr("value"), dtype=dtype).reshape(shape)
    ctx.set_output("Out", jnp.asarray(vals))


def _batch_size_like_shape(ctx):
    """Output shape = attr `shape` with the batch dim taken from Input
    (reference: batch_size_like.h)."""
    shape = list(ctx.attr("shape"))
    in_idx = ctx.attr("input_dim_idx", 0)
    out_idx = ctx.attr("output_dim_idx", 0)
    shape[out_idx] = ctx.input("Input").shape[in_idx]
    return tuple(shape)


@register_op("uniform_random_batch_size_like", no_grad_slots=["Input"])
def _uniform_random_batch_size_like(ctx):
    """reference: uniform_random_batch_size_like_op.cc"""
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    out = jax.random.uniform(_op_key(ctx), _batch_size_like_shape(ctx),
                             dtype=jnp.float32, minval=lo, maxval=hi)
    ctx.set_output("Out", out.astype(dtype))


@register_op("gaussian_random_batch_size_like", no_grad_slots=["Input"])
def _gaussian_random_batch_size_like(ctx):
    """reference: gaussian_random_batch_size_like_op.cc"""
    dtype = jnp_dtype(ctx.attr("dtype", "float32"))
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = mean + std * jax.random.normal(
        _op_key(ctx), _batch_size_like_shape(ctx), dtype=jnp.float32)
    ctx.set_output("Out", out.astype(dtype))


@register_op("randint")
def _randint(ctx):
    shape = ctx.attr("shape")
    dtype = jnp_dtype(ctx.attr("dtype", "int64"))
    out = jax.random.randint(_op_key(ctx), tuple(shape), ctx.attr("low", 0),
                             ctx.attr("high", 100), dtype=dtype)
    ctx.set_output("Out", out)


# -- basic transforms -------------------------------------------------------

@register_op("sum")
def _sum(ctx):
    xs = ctx.inputs("X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output("Out", out)


@register_op("cast")
def _cast(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", x.astype(jnp_dtype(ctx.attr("out_dtype", "float32"))))


@register_op("scale")
def _scale(ctx):
    x = ctx.input("X")
    scale = ctx.attr("scale", 1.0)
    bias = ctx.attr("bias", 0.0)
    if ctx.attr("bias_after_scale", True):
        ctx.set_output("Out", x * scale + bias)
    else:
        ctx.set_output("Out", (x + bias) * scale)


@register_op("increment")
def _increment(ctx):
    x = ctx.input("X")
    # keep the carry dtype stable (int counters must stay int inside
    # while loops)
    ctx.set_output("Out", x + jnp.asarray(ctx.attr("step", 1.0), x.dtype))


@register_op("shape")
def _shape(ctx):
    ctx.set_output("Out", jnp.asarray(jnp.shape(ctx.input("X")),
                                      dtype=jnp.int64))


# -- the generic grad op ----------------------------------------------------

@register_op("__vjp__", ragged_aware=True)
def _vjp(ctx):
    """Gradient of an arbitrary forward op via jax.vjp on its compute rule.

    See core/backward.py for how this op is constructed. XLA CSE merges the
    re-traced forward values with the original forward ops post-fusion.
    """
    fwd = OpDesc.from_dict(ctx.attr("fwd_op"))
    fwd_def = OpRegistry.get(fwd.type)
    fwd_in_names = fwd.input_names()
    fwd_out_names = fwd.output_names()
    in_vals = ctx.inputs("FwdIn")
    out_grads = ctx.inputs("OutGrad")
    out_has_grad = ctx.attr("out_has_grad")
    in_need_grad = ctx.attr("in_need_grad")
    # Sub-block ops read outer vars via closure (see backward.py
    # _sub_block_free_vars); those ride along as extra FwdIn entries so
    # jax.vjp sees them as arguments and produces their gradients.
    closure_names = ctx.attr("closure_names", []) or []
    grad_out_names = [n for n, h in zip(fwd_out_names, out_has_grad) if h]
    replay_names = fwd_in_names + list(closure_names)

    # Only grad-receiving outputs go through vjp (others contribute nothing),
    # and ragged values pass as their dense data (lengths are non-diff ints).
    from ..core.registry import run_op

    def f(vals):
        env = dict(ctx.env)
        for n, v in zip(replay_names, vals):
            env[n] = v
        outs = run_op(fwd, env, ctx.extra)
        res = []
        for n in grad_out_names:
            v = outs[n]
            res.append(v.data if isinstance(
                v, (RaggedPair, RaggedNested, RaggedTree)) else v)
        return tuple(res)

    _, vjp_fn = jax.vjp(f, tuple(in_vals))
    cts = tuple(g.data if isinstance(
        g, (RaggedPair, RaggedNested, RaggedTree)) else g
        for g in out_grads)
    (in_grads,) = vjp_fn(cts)

    idx = 0
    for need, g, v in zip(in_need_grad, in_grads, in_vals):
        if not need:
            continue
        if isinstance(g, RaggedPair):
            g = RaggedPair(g.data, v.lengths)
        elif isinstance(g, RaggedNested):
            g = RaggedNested(g.data, v.sub_lengths, v.tok_lengths)
        elif isinstance(g, RaggedTree):
            g = RaggedTree(g.data, v.lengths)
        ctx.set_output("InGrad", g, index=idx)
        idx += 1
