"""paddle_tpu: a TPU-native deep-learning framework with the capabilities
of PaddlePaddle Fluid v0.11 (reference layout: SURVEY.md), built on
JAX/XLA/pjit/Pallas. Programs are IR (ops/blocks/vars); the Executor
JIT-compiles whole blocks to fused XLA programs; parallelism is GSPMD
sharding over a device mesh instead of NCCL/parameter servers.
"""

from .core.scope import Scope, global_scope, reset_global_scope  # noqa
from .core.lod import LoDTensor, RaggedNested, RaggedPair  # noqa
from .core.backward import append_backward, calc_gradient  # noqa
from . import ops  # noqa  (registers all op types)
from .framework import (  # noqa
    Program, Variable, Parameter, Block, default_main_program,
    default_startup_program, program_guard, unique_name,
    reset_default_programs,
)
from .executor import (Executor, CPUPlace, CUDAPlace,  # noqa
                       TPUPlace, StepResult, scope_guard)
from .layer_helper import (LayerHelper, ParamAttr,  # noqa
                           WeightNormParamAttr)
from . import layers  # noqa
from . import initializer  # noqa
from . import optimizer  # noqa
from . import regularizer  # noqa
from . import clip  # noqa
from . import nets  # noqa
from . import io  # noqa
from . import metrics  # noqa
from . import profiler  # noqa
from . import flags  # noqa
from . import debug  # noqa
from .parallel import ParallelExecutor  # noqa
from . import reader  # noqa
from . import dataset  # noqa  (reference paddle/__init__.py imports it)
from .reader import batch  # noqa
from . import concurrency  # noqa
from . import amp  # noqa
from . import observability  # noqa  (metrics registry, step tracing, telemetry endpoint)
from . import analysis  # noqa  (static ProgramDesc verifier, lint passes, pre-compile gate)
from . import resilience  # noqa  (fault injection, retry/backoff, circuit breaker)
from . import serving  # noqa  (inference server: dynamic batching + bucketed compile cache)
from . import embedding  # noqa  (billion-row sharded embedding subsystem)

# reference fluid.__all__ surface (module paths a migrating user
# imports directly; see each shim's docstring)
from .core import backward  # noqa
from .core.lod import LoDTensor as Tensor  # noqa
from . import average  # noqa
from . import default_scope_funcs  # noqa
from . import evaluator  # noqa
from . import learning_rate_decay  # noqa
from . import param_attr  # noqa
from . import recordio_writer  # noqa
from .data_feeder import DataFeeder  # noqa
from .transpiler.distribute_transpiler import (  # noqa
    DistributeTranspiler, DistributeTranspiler as
    SimpleDistributeTranspiler)
from .transpiler.memory_optimization_transpiler import (  # noqa
    memory_optimize, release_memory)

__version__ = "0.1.0"
