"""Static per-op cost model over ProgramDesc IR: FLOPs, bytes accessed,
parameter bytes.

The TensorFlow paper (PAPERS.md) treats per-op cost attribution as core
runtime infrastructure, and the XLA-fusion paper shows that FLOPs/bytes
per op is what locates fusion headroom. This module makes that
attribution a static property of every program: walk the reachable ops
(same traversal as the verifier's ``iter_ops``), resolve each operand's
shape from the declared + build-time-inferred VarDescs (dynamic ``-1``
dims bound from the feed shapes), and apply a per-op-type FLOP rule.

Accuracy contract (see KNOWN_GAPS "Performance attribution
boundaries"): matmul/conv-family ops are counted exactly (2 x MACs,
the same convention XLA's ``cost_analysis()`` uses for the dominant
terms); ``__vjp__`` grad ops are costed at 2x their embedded forward op
(the standard backward approximation — a train step totals ~3x the
forward); everything else is approximated at one FLOP per output
element. ``bytes_accessed`` is the PRE-fusion operand traffic (every
op reads its inputs and writes its outputs) — an upper bound that XLA's
fusion then reduces, so arithmetic intensity from this model is a lower
bound on the compiled executable's.

The model is pure and cheap (one O(ops) walk, no trace, no device):
the executor attaches it to every compile-cache miss, and
``tools/lint_ir.py --cost`` prints it offline.
"""
from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core import ir
from .passes import AnalysisPass, PassContext, iter_ops, register_pass

__all__ = ["OpCost", "ProgramCost", "program_cost", "CostModelPass",
           "ZERO_FLOP_OPS", "ITEMSIZE"]

_ITEMSIZE = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
             "float16": 2, "bfloat16": 2, "int16": 2, "int8": 1,
             "uint8": 1, "bool": 1}

#: public alias — the memory planner (analysis/memory.py) binds shapes
#: to bytes with the same table so the two analyses can never disagree
ITEMSIZE = _ITEMSIZE

#: ops that move/alias/select data without arithmetic — zero FLOPs by
#: contract (their bytes still count: a transpose is pure HBM traffic)
ZERO_FLOP_OPS = frozenset({
    "feed", "fetch", "assign", "share_data", "print", "shape",
    "fill_constant", "fill_constant_like",
    "fill_constant_batch_size_like", "fill_zeros_like", "fill",
    "assign_value", "reshape", "reshape2", "squeeze", "unsqueeze",
    "flatten", "transpose", "transpose2", "concat", "split", "slice",
    "strided_slice", "cast", "one_hot", "stack", "unstack", "expand",
    "expand_as", "tile", "reverse", "pad", "pad2d", "gather",
    "gather_nd", "lookup_table", "embedding_bag", "kv_cache_write",
    "kv_cache_append",
})

#: FLOPs per parameter element for each optimizer update rule (read +
#: decay + moment updates + write, counted from the compute rules)
_OPTIMIZER_FLOPS = {
    "sgd": 2, "momentum": 5, "adam": 12, "adagrad": 6, "adamax": 9,
    "adadelta": 9, "rmsprop": 9, "decayed_adagrad": 7, "ftrl": 12,
    "lars_momentum": 9, "proximal_gd": 6, "proximal_adagrad": 9,
}

#: same per-element rules for the sparse (touched-rows-only) variants
#: (ops/optimizer_ops.py sparse_sgd/sparse_adagrad/sparse_adam) — but
#: keyed on the DEDUPED row-grad numel, not Param numel: charging the
#: dense rule's Param numel would overcount by vocab/touched, which at
#: embedding scale is ~1e5x (PAPER sparse update path)
_SPARSE_OPTIMIZER_FLOPS = {
    "sparse_sgd": 2, "sparse_adagrad": 6, "sparse_adam": 12,
}


def _prod(dims: Sequence[int]) -> int:
    p = 1
    for d in dims:
        p *= int(d)
    return p


class _VarInfo:
    """Resolved operand: concrete shape (``-1`` bound), element count,
    bytes, and persistability."""

    __slots__ = ("name", "shape", "numel", "bytes", "persistable")

    def __init__(self, name: str, shape: List[int], itemsize: int,
                 persistable: bool):
        self.name = name
        self.shape = shape
        self.numel = _prod(shape)
        self.bytes = self.numel * itemsize
        self.persistable = persistable


class OpCost:
    """Cost of one op: FLOPs, operand bytes, parameter bytes read."""

    __slots__ = ("op_type", "block_path", "op_index", "flops",
                 "bytes_accessed", "param_bytes", "exact", "note")

    def __init__(self, op_type: str, block_path: Tuple[int, ...],
                 op_index: int, flops: int, bytes_accessed: int,
                 param_bytes: int, exact: bool,
                 note: Optional[str] = None):
        self.op_type = op_type
        self.block_path = tuple(block_path)
        self.op_index = op_index
        self.flops = int(flops)
        self.bytes_accessed = int(bytes_accessed)
        self.param_bytes = int(param_bytes)
        self.exact = bool(exact)
        self.note = note

    def to_dict(self) -> Dict:
        return {"op_type": self.op_type,
                "block_path": list(self.block_path),
                "op_index": self.op_index, "flops": self.flops,
                "bytes_accessed": self.bytes_accessed,
                "param_bytes": self.param_bytes, "exact": self.exact,
                "note": self.note}

    def __repr__(self):
        return (f"OpCost({self.op_type}, flops={self.flops}, "
                f"bytes={self.bytes_accessed})")


class ProgramCost:
    """Per-op costs plus program totals for one block tree.

    ``param_bytes`` deduplicates persistable vars program-wide (a param
    read by forward, backward, and its optimizer op counts once) —
    the resident-weights number; per-op ``param_bytes`` keeps every
    read for traffic accounting.
    """

    def __init__(self, ops: List[OpCost], param_bytes: int, batch: int,
                 block_idx: int, label: str = "program"):
        self.ops = ops
        self.param_bytes = int(param_bytes)
        self.batch = int(batch)
        self.block_idx = int(block_idx)
        self.label = label
        self.flops = sum(c.flops for c in ops)
        self.bytes_accessed = sum(c.bytes_accessed for c in ops)
        self.unresolved = sum(1 for c in ops
                              if c.note == "unresolved shapes")

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per byte of pre-fusion operand traffic (a LOWER bound
        on the fused executable's intensity)."""
        return self.flops / self.bytes_accessed if self.bytes_accessed \
            else 0.0

    @property
    def exact_flops_fraction(self) -> float:
        """Fraction of total FLOPs carried by exactly-counted ops (the
        matmul/conv/optimizer family) — how much of the total is rule-
        derived rather than one-flop-per-element approximation."""
        if not self.flops:
            return 0.0
        return sum(c.flops for c in self.ops if c.exact) / self.flops

    def top_ops(self, limit: int = 20) -> List[OpCost]:
        return sorted(self.ops, key=lambda c: -c.flops)[:limit]

    def table(self, limit: int = 20) -> str:
        """Human-readable cost table, heaviest ops first."""
        lines = [
            f"cost {self.label} (block {self.block_idx}, "
            f"batch={self.batch}): {len(self.ops)} ops, "
            f"{self.flops / 1e9:.3f} GFLOP, "
            f"{self.bytes_accessed / 1e6:.2f} MB accessed, "
            f"{self.param_bytes / 1e6:.2f} MB params, "
            f"intensity {self.arithmetic_intensity:.1f} flop/B "
            f"({self.exact_flops_fraction * 100:.0f}% of flops exact, "
            f"{self.unresolved} op(s) unresolved)",
            f"{'flops':>14s} {'bytes':>12s} {'params':>10s}  op",
        ]
        for c in self.top_ops(limit):
            loc = "/".join(str(b) for b in c.block_path)
            note = f"  [{c.note}]" if c.note else ""
            lines.append(
                f"{c.flops:14d} {c.bytes_accessed:12d} "
                f"{c.param_bytes:10d}  b{loc}:op{c.op_index} "
                f"{c.op_type}{note}")
        if len(self.ops) > limit:
            lines.append(f"  ... {len(self.ops) - limit} more op(s)")
        return "\n".join(lines)

    def to_dict(self) -> Dict:
        return {
            "label": self.label, "block_idx": self.block_idx,
            "batch": self.batch, "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "param_bytes": self.param_bytes,
            "arithmetic_intensity": round(self.arithmetic_intensity, 4),
            "exact_flops_fraction":
                round(self.exact_flops_fraction, 4),
            "unresolved_ops": self.unresolved,
            "ops": [c.to_dict() for c in self.ops],
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    def __repr__(self):
        return (f"ProgramCost({self.label}, flops={self.flops}, "
                f"bytes={self.bytes_accessed}, "
                f"params={self.param_bytes})")


# ---------------------------------------------------------------------------
# FLOP rules
# ---------------------------------------------------------------------------
def _flops_for(op: ir.OpDesc,
               lookup: Callable[[str], Optional[_VarInfo]]
               ) -> Tuple[Optional[int], bool, Optional[str]]:
    """(flops, exact, note) for one op; flops None = needed shapes are
    unresolvable (caller falls back to the generic estimate)."""

    def first(slot: str) -> Optional[_VarInfo]:
        names = op.input(slot)
        return lookup(names[0]) if names else None

    def out(slot: str) -> Optional[_VarInfo]:
        names = op.output(slot)
        return lookup(names[0]) if names else None

    t = op.type
    if t in ZERO_FLOP_OPS:
        return 0, True, None

    if t == "mul":
        x, y = first("X"), first("Y")
        if x is None or y is None:
            return None, False, None
        xn = int(op.attrs.get("x_num_col_dims", 1))
        yn = int(op.attrs.get("y_num_col_dims", 1))
        m = _prod(x.shape[:xn])
        k = _prod(x.shape[xn:])
        n = _prod(y.shape[yn:])
        return 2 * m * k * n, True, None

    if t == "matmul":
        x, o = first("X"), out("Out")
        if x is None or o is None or not x.shape:
            return None, False, None
        k = x.shape[-2] if op.attrs.get("transpose_X") and \
            len(x.shape) > 1 else x.shape[-1]
        return 2 * o.numel * int(k), True, None

    if t in ("conv2d", "depthwise_conv2d", "conv3d"):
        o, w = out("Output"), first("Filter")
        if o is None or w is None or len(w.shape) < 2:
            return None, False, None
        # filter [Cout, Cin/groups, *k]: MACs per output element
        return 2 * o.numel * _prod(w.shape[1:]), True, None

    if t in ("conv2d_transpose", "conv3d_transpose"):
        x, w = first("Input"), first("Filter")
        if x is None or w is None or len(w.shape) < 2:
            return None, False, None
        # filter [Cin, Cout, *k]: every input element hits Cout*k MACs
        return 2 * x.numel * _prod(w.shape[1:]), True, None

    if t in ("pool2d", "pool3d", "adaptive_pool2d"):
        o = out("Out")
        if o is None:
            return None, False, None
        k = op.attrs.get("ksize") or [1]
        return o.numel * _prod(k), False, None

    if t in ("softmax", "log_softmax"):
        x = first("X")
        return (None, False, None) if x is None else \
            (5 * x.numel, False, None)

    if t == "softmax_with_cross_entropy":
        x = first("Logits")
        return (None, False, None) if x is None else \
            (6 * x.numel, False, None)

    if t == "batch_norm":
        x = first("X")
        return (None, False, None) if x is None else \
            (6 * x.numel, False, None)

    if t == "layer_norm":
        x = first("X")
        return (None, False, None) if x is None else \
            (8 * x.numel, False, None)

    if t == "scaled_dot_product_attention":
        # the outlined attention mega-op (analysis/rewrite.py): two
        # seq^2 contractions plus the online softmax. Without this rule
        # the generic 1-flop/elem fallback would book ~Sq*d instead of
        # ~4*Sq*Sk*d and silently crater reported MFU post-rewrite.
        q, k = first("Q"), first("K")
        if q is None or k is None or len(q.shape) < 3:
            return None, False, None
        lead = _prod(q.shape[:-2])
        sq, d = q.shape[-2], q.shape[-1]
        sk = k.shape[-2]
        return (4 * lead * sq * sk * d + 5 * lead * sq * sk,
                True, None)

    if t in ("lstm", "gru"):
        # the fused recurrence mega-ops (ops/sequence_ops.py, Pallas
        # fused_lstm/fused_gru): the per-step recurrent matmul
        # [n,h]x[h,Gh] over all timesteps dominates; +12 flop/elem
        # covers the gate nonlinearities. The leading dims product is
        # n*t for a padded [n, t, Gh] input and the declared row count
        # for a ragged 2-D declaration (the padded time extent is not
        # statically known — same documented approximation as the
        # generic -1 binding).
        x, w = first("Input"), first("Weight")
        if x is None or w is None or len(x.shape) < 2 \
                or len(w.shape) != 2:
            return None, False, None
        nt = _prod(x.shape[:-1])
        h = w.shape[0]
        gates = 4 if t == "lstm" else 3
        return 2 * nt * h * gates * h + 12 * nt * h, True, None

    if t == "se_block":
        # outlined squeeze-excitation gate (ops/fusion_ops.py): global
        # pool + gate multiply sweep the activation twice; the two
        # bottleneck FCs are 2*MAC each
        x, w1 = first("X"), first("W1")
        if x is None or w1 is None or len(x.shape) != 4 \
                or len(w1.shape) != 2:
            return None, False, None
        n, c = x.shape[0], x.shape[1]
        r = w1.shape[1]
        return 2 * x.numel + 4 * n * c * r, True, None

    if t in _OPTIMIZER_FLOPS:
        p = first("Param")
        if p is None:
            return None, False, None
        return _OPTIMIZER_FLOPS[t] * p.numel, True, None

    if t in _SPARSE_OPTIMIZER_FLOPS:
        g = first("Grad")
        if g is None:
            return None, False, None
        return (_SPARSE_OPTIMIZER_FLOPS[t] * g.numel, True,
                "sparse apply: touched rows only")

    if t == "__vjp__":
        fwd_dict = op.attrs.get("fwd_op")
        if not fwd_dict:
            return None, False, None
        fwd = ir.OpDesc.from_dict(fwd_dict)
        f_flops, _f_exact, _ = _flops_for(fwd, lookup)
        if f_flops is None:
            # fall back on the forward op's output sizes
            f_flops = sum((lookup(n).numel if lookup(n) else 0)
                          for n in fwd.output_names())
        # backward ~= 2x forward (input-grad + weight-grad each pay one
        # forward-sized contraction for the matmul/conv family)
        return 2 * f_flops, False, f"vjp x2 of {fwd.type}"

    return None, False, None


def _bytes_override(op: ir.OpDesc,
                    lookup: Callable[[str], Optional[_VarInfo]]
                    ) -> Optional[Tuple[int, str]]:
    """Op types whose generic operand-bytes walk badly overcounts."""
    if op.type in ("lookup_table", "embedding_bag", "gather",
                   "gather_nd"):
        # a gather touches the SELECTED rows, not the whole table
        # (MULTICHIP_r05: model-axis gather traffic scales with touched
        # rows) — count ids + read of gathered rows + write of output
        touched = 0
        for names in op.outputs.values():
            for n in names:
                v = lookup(n)
                if v is not None:
                    touched += v.bytes
        ids = 0
        for slot in ("Ids", "Index"):
            v_names = op.input(slot)
            if v_names:
                v = lookup(v_names[0])
                if v is not None:
                    ids += v.bytes
        return 2 * touched + ids, "gather: touched rows only"
    if op.type in ("kv_cache_write", "kv_cache_append"):
        # an in-place dynamic-update-slice touches the UPDATED rows,
        # not the whole cache: counting the full [slots, h, max_seq, d]
        # cache as read+written per decoded token would overstate
        # decode-step traffic by max_seq/1 and crater reported
        # arithmetic intensity. The cache-READ traffic of attention is
        # booked on the consumer (slice + scaled_dot_product_attention
        # operands), not here.
        new_b = 0
        names = op.input("New")
        if names:
            v = lookup(names[0])
            if v is not None:
                new_b = v.bytes
        idx = 0
        for slot in ("Slot", "Pos"):
            v_names = op.input(slot)
            if v_names:
                v = lookup(v_names[0])
                if v is not None:
                    idx += v.bytes
        return 2 * new_b + idx, "kv cache: updated rows only"
    if op.type in ("sparse_sgd", "sparse_adagrad", "sparse_adam"):
        # sparse apply touches the DEDUPED rows only: the generic walk
        # would charge the full [vocab, dim] param (and each slot) as
        # read+written, overstating a billion-row table's update
        # traffic by vocab/touched. Real traffic per touched row:
        # param read+write + grad read (3x touched) plus a read+write
        # of every row-wise slot (adagrad: moment; adam: m1+m2), plus
        # the deduped ids. Scalar beta-pow accumulators are noise.
        touched = 0
        names = op.input("Grad")
        if names:
            v = lookup(names[0])
            if v is not None:
                touched = v.bytes
        n_slots = {"sparse_sgd": 0, "sparse_adagrad": 1,
                   "sparse_adam": 2}[op.type]
        ids = 0
        names = op.input("Ids")
        if names:
            v = lookup(names[0])
            if v is not None:
                ids = v.bytes
        return ((3 + 2 * n_slots) * touched + ids,
                "sparse apply: touched rows + slots only")
    if op.type == "slice":
        # a slice reads exactly the rows it keeps — the decode step
        # slices the first L rows out of a [slots, h, max_seq, d]
        # cache, and charging the full cache read here would double the
        # whole point of cache-length bucketing
        out_b = 0
        for names in op.outputs.values():
            for n in names:
                v = lookup(n)
                if v is not None:
                    out_b += v.bytes
        return 2 * out_b, "slice: kept rows only"
    return None


# ---------------------------------------------------------------------------
def program_cost(program, block_idx: int = 0,
                 feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
                 batch: Optional[int] = None,
                 label: Optional[str] = None) -> ProgramCost:
    """Walk every reachable op of ``program`` (builder wrapper or core
    ``ir.Program``) and return its :class:`ProgramCost`.

    ``feed_shapes`` maps feed names to concrete shapes (the executor
    passes the current dispatch's device-feed shapes); a declared
    leading ``-1`` then resolves to the fed batch. Without feeds,
    ``batch`` (default 1) binds the dynamic batch dim. Non-leading
    dynamic dims bind to 1 (documented approximation — ragged padded
    time dims are not statically known).
    """
    desc = program.desc if hasattr(program, "desc") else program
    feed_shapes = {k: tuple(int(d) for d in v)
                   for k, v in (feed_shapes or {}).items()}
    root = desc.blocks[block_idx]
    if batch is None:
        batch = 1
        for name, shape in feed_shapes.items():
            v = root.find_var_recursive(name)
            if v is not None and v.shape and shape \
                    and len(v.shape) == len(shape) and v.shape[0] == -1:
                batch = int(shape[0])
                break
    batch = max(1, int(batch))

    param_reads: Dict[str, int] = {}
    op_costs: List[OpCost] = []

    # one resolution cache per block, shared by every op in it: params
    # and activations are read by several ops (fwd, __vjp__, optimizer)
    # and the parent-chain walk is the expensive part
    block_caches: Dict[int, Dict[str, Optional[_VarInfo]]] = {}

    for blk, path, i, op in iter_ops(desc, block_idx):
        cache = block_caches.setdefault(id(blk), {})

        def lookup(name: str, _blk=blk, _cache=cache
                   ) -> Optional[_VarInfo]:
            if name in _cache:
                return _cache[name]
            v = _blk.find_var_recursive(name)
            info = None
            if v is not None:
                if name in feed_shapes:
                    shape = list(feed_shapes[name])
                elif v.shape is not None:
                    shape = [
                        (batch if j == 0 else 1)
                        if (not isinstance(d, int) or d == -1) else int(d)
                        for j, d in enumerate(v.shape)]
                else:
                    shape = None
                if shape is not None:
                    info = _VarInfo(
                        name, shape,
                        _ITEMSIZE.get(v.dtype or "float32", 4),
                        v.persistable)
            _cache[name] = info
            return info

        flops, exact, note = _flops_for(op, lookup)
        in_infos = [lookup(n) for n in dict.fromkeys(op.input_names())]
        out_infos = [lookup(n) for n in dict.fromkeys(op.output_names())]
        if flops is None:
            # generic estimate: one FLOP per output element
            resolved_out = [v for v in out_infos if v is not None]
            if resolved_out:
                flops, exact, note = (
                    sum(v.numel for v in resolved_out), False, "generic")
            else:
                flops, exact, note = 0, False, "unresolved shapes"

        ov = _bytes_override(op, lookup)
        if ov is not None:
            bytes_acc, bnote = ov
            note = note or bnote
        else:
            bytes_acc = sum(v.bytes for v in in_infos if v is not None) \
                + sum(v.bytes for v in out_infos if v is not None)
        pbytes = 0
        for v in in_infos:
            if v is not None and v.persistable:
                pbytes += v.bytes
                param_reads.setdefault(v.name, v.bytes)
        op_costs.append(OpCost(op.type, path, i, flops, bytes_acc,
                               pbytes, exact, note))

    return ProgramCost(op_costs, sum(param_reads.values()), batch,
                       block_idx,
                       label=label or f"program uid={desc.uid}")


# ---------------------------------------------------------------------------
@register_pass
class CostModelPass(AnalysisPass):
    """Attach a :class:`ProgramCost` to the verify report
    (``report.cost``). Produces no diagnostics — it is an attribution
    pass on the same framework, runnable alongside the verifier
    (``ProgramVerifier(passes=[..., "cost_model"])``) or standalone via
    :func:`program_cost`."""

    name = "cost_model"

    def __init__(self, feed_shapes=None, batch=None):
        self.feed_shapes = feed_shapes
        self.batch = batch

    def run(self, ctx: PassContext) -> None:
        ctx.report.cost = program_cost(
            ctx.program, ctx.block_idx, feed_shapes=self.feed_shapes,
            batch=self.batch, label=ctx.report.program_label)
