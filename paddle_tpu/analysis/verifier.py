"""ProgramVerifier: run analysis passes over a program, and the
pre-compile safety gates built on it.

The verifier is the ProgramDesc-layer analog of XLA's HLO verifier
(PAPERS.md): program-as-data makes whole-program static checking cheap,
so every consumer that is about to pay a JAX trace + XLA compile (or
pin a model for serving) first gets a structured report instead of a
deep trace error or a silent wrong answer:

- ``Executor.run`` verifies on every compile-cache MISS, before the
  cache is populated (``executor_gate``);
- ``serving.ServableModel`` verifies the frozen program at load;
- ``trainer.Trainer`` verifies the (main, startup) pair once at setup;
- ``io.save_inference_model`` verifies the pruned program before it is
  written to disk;
- ``tools/lint_ir.py`` runs the same passes from the command line.

All gates honor ``PADDLE_TPU_VERIFY=0`` (kill switch, read per call so
tests can flip it), and publish verify wall time to the observability
registry (``paddle_tpu_verify_seconds``).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

from ..core import ir
from .diagnostics import Severity, VerificationError, VerifyReport
from .passes import (PASS_REGISTRY, AnalysisPass, PassContext,
                     default_passes)

__all__ = ["ProgramVerifier", "verify_program", "verify_enabled",
           "executor_gate", "clear_gate_cache"]


def verify_enabled() -> bool:
    """The PADDLE_TPU_VERIFY kill switch, read per call (flippable in
    tests / emergencies without re-importing)."""
    return os.environ.get("PADDLE_TPU_VERIFY", "1") != "0"


def _desc(program) -> ir.Program:
    """Accept the python builder wrapper or the core ir.Program."""
    return program.desc if hasattr(program, "desc") else program


class ProgramVerifier:
    """Run a configurable pass pipeline over one program.

    ``passes`` accepts pass instances or registered names
    (see ``analysis.passes.PASS_REGISTRY``); default: all of them.
    """

    def __init__(self, passes: Optional[Sequence[
            Union[str, AnalysisPass]]] = None):
        if passes is None:
            self.passes: List[AnalysisPass] = default_passes()
        else:
            self.passes = [PASS_REGISTRY[p]() if isinstance(p, str) else p
                           for p in passes]

    def verify(self, program, startup=None,
               feed_names: Optional[Iterable[str]] = None,
               fetch_names: Optional[Sequence[str]] = None,
               block_idx: int = 0, donate: bool = False,
               async_dispatch: bool = False,
               program_label: str = "program") -> VerifyReport:
        report = VerifyReport(program_label=program_label)
        ctx = PassContext(
            _desc(program),
            startup=_desc(startup) if startup is not None else None,
            feed_names=feed_names, fetch_names=fetch_names,
            block_idx=block_idx, donate=donate,
            async_dispatch=async_dispatch, report=report)
        t0 = time.perf_counter()
        for p in self.passes:
            p.run(ctx)
        _publish(time.perf_counter() - t0, report)
        return report


def verify_program(program, startup=None, feed_names=None,
                   fetch_names=None, block_idx: int = 0,
                   donate: bool = False, async_dispatch: bool = False,
                   passes=None, program_label: str = "program"
                   ) -> VerifyReport:
    """One-shot convenience wrapper around ProgramVerifier."""
    return ProgramVerifier(passes=passes).verify(
        program, startup=startup, feed_names=feed_names,
        fetch_names=fetch_names, block_idx=block_idx, donate=donate,
        async_dispatch=async_dispatch, program_label=program_label)


# ---------------------------------------------------------------------------
# observability: verify wall time + outcome counts, resolved against the
# CURRENT default registry (identity-checked, same pattern as the
# executor's compile-cache instruments)
# ---------------------------------------------------------------------------
_obs_cache = None


def _publish(seconds: float, report: VerifyReport) -> None:
    global _obs_cache
    try:
        from ..observability.registry import default_registry
        reg = default_registry()
        if _obs_cache is None or _obs_cache[0] is not reg:
            _obs_cache = (
                reg,
                reg.histogram(
                    "paddle_tpu_verify_seconds",
                    "Wall time of one static program verification "
                    "(all gates: executor pre-compile, serving load, "
                    "trainer setup, save_inference_model, lint CLI)."),
                reg.counter(
                    "paddle_tpu_verify_total",
                    "Static program verifications run, by outcome.",
                    ("outcome",)),
            )
        _, hist, total = _obs_cache
        hist.record(seconds)
        total.labels(outcome="clean" if report.ok else "errors").inc()
    except Exception:
        pass  # telemetry must never fail a verification


# ---------------------------------------------------------------------------
# the executor's pre-compile gate, memoized per program version
# ---------------------------------------------------------------------------
_GATE_CACHE_MAX = 512
_gate_cache: Dict[Tuple, bool] = {}
# serving workers and a trainer thread can hit the gate concurrently;
# the membership check / FIFO eviction must be atomic
_gate_cache_lock = threading.Lock()


def clear_gate_cache() -> None:
    with _gate_cache_lock:
        _gate_cache.clear()


def executor_gate(program, block_idx: int,
                  fetch_names: Sequence[str],
                  feed_names: Iterable[str],
                  donate: bool, sync: bool) -> None:
    """Error-severity verification before the executor populates its
    compile cache. Raises VerificationError (a ValueError) with the
    full rendered error list; memoized on (program uid, version, fetch
    list, feeds, donation context) so repeated dispatches of the same
    program pay a dict lookup.
    """
    desc = _desc(program)
    feed_key = frozenset(feed_names)
    key = (desc.uid, desc.version, block_idx, tuple(fetch_names),
           feed_key, bool(donate), bool(sync))
    with _gate_cache_lock:
        if _gate_cache.get(key):
            return
    from .passes import fast_passes
    report = verify_program(
        desc, feed_names=feed_key, fetch_names=list(fetch_names),
        block_idx=block_idx, donate=donate, async_dispatch=not sync,
        # the hot path runs the shared no-retrace pipeline (build-time
        # markers only): pure Python, O(ops) — the full
        # abstract-inference re-trace stays on the cold gates
        # (serving load, save_inference_model, lint CLI)
        passes=fast_passes(),
        program_label=f"program uid={desc.uid} block={block_idx}")
    report.raise_if_errors(context="pre-compile gate")
    with _gate_cache_lock:
        while len(_gate_cache) >= _GATE_CACHE_MAX:
            _gate_cache.pop(next(iter(_gate_cache)), None)
        _gate_cache[key] = True
