"""Structured findings of the static program verifier.

A `Diagnostic` pins one defect (or observation) to a program location:
severity, a stable machine-readable code, the op index inside its
block, the *block path* from the root block down through sub-blocks
(While/IfElse bodies), the variable involved, and a fix hint. A
`VerifyReport` aggregates the diagnostics of one verification run and
renders them as text or JSON — the shared currency between the
pre-compile gate (core/executor.py), the serving load check, the
trainer setup check, `tools/lint_ir.py`, and `debug.draw_graph`'s
finding-colored DOT export.
"""
from __future__ import annotations

import json
from enum import IntEnum
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["Severity", "Diagnostic", "VerifyReport", "VerificationError"]


class Severity(IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


class Diagnostic:
    """One verifier finding, attributable to an op in a block path."""

    __slots__ = ("severity", "code", "message", "block_path", "op_index",
                 "op_type", "var", "hint")

    def __init__(self, severity: Severity, code: str, message: str,
                 block_path: Sequence[int] = (0,),
                 op_index: Optional[int] = None,
                 op_type: Optional[str] = None,
                 var: Optional[str] = None,
                 hint: Optional[str] = None):
        self.severity = Severity(severity)
        self.code = code
        self.message = message
        self.block_path = tuple(int(b) for b in block_path)
        self.op_index = op_index
        self.op_type = op_type
        self.var = var
        self.hint = hint

    @property
    def block_idx(self) -> int:
        """The innermost block holding the finding."""
        return self.block_path[-1]

    def location(self) -> str:
        """Human-readable position: ``block 0 > block 2 / op 3 (while)``."""
        path = " > ".join(f"block {b}" for b in self.block_path)
        if self.op_index is None:
            return path
        op = f"op {self.op_index}"
        if self.op_type:
            op += f" ({self.op_type})"
        return f"{path} / {op}"

    def render(self) -> str:
        line = f"{self.severity}[{self.code}] {self.location()}: " \
               f"{self.message}"
        if self.hint:
            line += f"\n    hint: {self.hint}"
        return line

    def to_dict(self) -> Dict[str, Any]:
        return {"severity": str(self.severity), "code": self.code,
                "message": self.message,
                "block_path": list(self.block_path),
                "op_index": self.op_index, "op_type": self.op_type,
                "var": self.var, "hint": self.hint}

    def __repr__(self):
        return f"Diagnostic({self.severity}[{self.code}] {self.location()})"


class VerifyReport:
    """All diagnostics of one verification run, worst first."""

    def __init__(self, diagnostics: Optional[List[Diagnostic]] = None,
                 program_label: str = "program"):
        self.diagnostics: List[Diagnostic] = list(diagnostics or [])
        self.program_label = program_label
        # filled by the cost_model pass when it runs in the pipeline
        self.cost = None
        # filled by the memory pass / budget gate (analysis/memory.py)
        self.memory = None

    def add(self, diag: Diagnostic) -> Diagnostic:
        self.diagnostics.append(diag)
        return diag

    # -- queries ------------------------------------------------------
    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics
                if d.severity == Severity.WARNING]

    @property
    def ok(self) -> bool:
        """True when no error-severity diagnostic was found."""
        return not self.errors

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.code == code]

    def sorted(self) -> List[Diagnostic]:
        return sorted(self.diagnostics,
                      key=lambda d: (-int(d.severity), d.block_path,
                                     -1 if d.op_index is None
                                     else d.op_index))

    # -- rendering ----------------------------------------------------
    def render_text(self, min_severity: Severity = Severity.INFO) -> str:
        shown = [d for d in self.sorted() if d.severity >= min_severity]
        head = (f"verify {self.program_label}: "
                f"{len(self.errors)} error(s), "
                f"{len(self.warnings)} warning(s), "
                f"{len(self.diagnostics) - len(self.errors) - len(self.warnings)}"
                f" note(s)")
        return "\n".join([head] + [d.render() for d in shown])

    def to_json(self) -> str:
        return json.dumps({
            "program": self.program_label,
            "ok": self.ok,
            "counts": {"error": len(self.errors),
                       "warning": len(self.warnings),
                       "info": len(self.diagnostics) - len(self.errors)
                       - len(self.warnings)},
            "diagnostics": [d.to_dict() for d in self.sorted()]})

    def raise_if_errors(self, context: str = ""):
        if not self.ok:
            err = VerificationError(self, context=context)
            try:
                # a failed verification is a flight-recorder trigger:
                # the dump carries the recent events + metrics leading
                # up to the rejected program (no-op when disabled)
                from ..observability.flight_recorder import record_failure
                record_failure("verification_error", exc=err,
                               context={"program": self.program_label,
                                        "context": context})
            except Exception:
                pass  # telemetry must never mask the verification error
            raise err
        return self

    def __len__(self):
        return len(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)


class VerificationError(ValueError):
    """Error-severity diagnostics found by the verifier.

    Subclasses ValueError so call sites that previously relied on the
    executor's runtime guards (e.g. the async donated-state fetch
    ValueError) keep their exception contract when the same defect is
    now caught statically at verify time.
    """

    def __init__(self, report: VerifyReport, context: str = ""):
        self.report = report
        lines = [d.render() for d in report.sorted()
                 if d.severity == Severity.ERROR]
        prefix = f"{context}: " if context else ""
        super().__init__(
            f"{prefix}program verification failed with "
            f"{len(lines)} error(s) (set PADDLE_TPU_VERIFY=0 to bypass "
            f"the gate):\n" + "\n".join(lines))
