"""Composable static-analysis passes over core IR programs.

Each pass inspects one `ir.Program` (plus optional context: the paired
startup program, feed/fetch names, executor donation mode) and appends
`Diagnostic`s to the shared report. Passes never mutate the program —
they are safe to run between transformations (backward, pruning,
donation, serving freeze), the HLO-verifier stance from PAPERS.md's
XLA-fusion paper applied to the ProgramDesc layer.

Walk order mirrors the executor's: blocks are visited depth-first
through the same sub-block attrs the tracer follows
(``sub_block`` / ``sub_block_idx`` / ``true_block_idx`` /
``false_block_idx``), so every diagnostic carries the block path the
op would execute under.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..core import ir
from ..core.registry import OpRegistry
from .diagnostics import Diagnostic, Severity, VerifyReport

__all__ = ["PassContext", "AnalysisPass", "default_passes",
           "register_pass", "PASS_REGISTRY", "iter_ops", "iter_blocks",
           "rw_state_names", "DONATED_FETCH_HINT"]

#: op attrs naming a sub-block the tracer descends into — the shared
#: canonical list (core/ir.py) the executor walks with
from ..core.ir import SUB_BLOCK_ATTRS  # noqa: E402  (re-export)

#: op attrs whose names are *machinery-defined* inside a sub-block: the
#: enclosing control-flow op injects these values into the trace env
#: (step inputs, pre-memories), so no OpDesc ever writes them.
MACHINERY_DEF_ATTRS = ("step_in_names", "mem_pre_names", "stage_in_name")

#: var types that never flow through the dense trace env as plain reads
_OPAQUE_VAR_TYPES = (ir.VAR_TYPE_READER, ir.VAR_TYPE_STEP_SCOPES,
                     ir.VAR_TYPE_RAW)

DONATED_FETCH_HINT = ("fetch it with sync=True, or build the Executor "
                      "with donate_state=False")

_DTYPE_FAMILY = {
    "float16": "float", "bfloat16": "float", "float32": "float",
    "float64": "float",
    "int8": "int", "int16": "int", "int32": "int", "int64": "int",
    "uint8": "int",
    "bool": "bool",
}


def iter_blocks(program: ir.Program, block_idx: int = 0):
    """Yield ``(block, path)`` depth-first from ``block_idx``, following
    the sub-block attrs of each op (the executor's reachability). Each
    block is visited at most once: a corrupted program whose sub-block
    attr points at itself (or an ancestor) must yield diagnostics, not
    a RecursionError."""
    seen = set()

    def visit(blk: ir.BlockDesc, path: Tuple[int, ...]):
        if blk.idx in seen:
            return
        seen.add(blk.idx)
        yield blk, path
        for op in blk.ops:
            for attr in SUB_BLOCK_ATTRS:
                idx = op.attrs.get(attr)
                if isinstance(idx, int) and 0 <= idx < len(program.blocks):
                    yield from visit(program.blocks[idx], path + (idx,))
    yield from visit(program.blocks[block_idx], (block_idx,))


def iter_ops(program: ir.Program, block_idx: int = 0):
    """Yield ``(block, path, op_index, op)`` over every reachable op."""
    for blk, path in iter_blocks(program, block_idx):
        for i, op in enumerate(blk.ops):
            yield blk, path, i, op


def _written_names(program: ir.Program, block_idx: int = 0) -> Set[str]:
    """Every name some reachable op writes, plus machinery-injected
    names (step inputs / pre-memories of RNN-family ops)."""
    written: Set[str] = set()
    for _blk, _path, _i, op in iter_ops(program, block_idx):
        written.update(op.output_names())
        for attr in MACHINERY_DEF_ATTRS:
            v = op.attrs.get(attr)
            if isinstance(v, str):
                written.add(v)
            elif isinstance(v, (list, tuple)):
                written.update(n for n in v if isinstance(n, str))
    return written


def _write_positions(program: ir.Program, block_idx: int = 0
                     ) -> Dict[str, List[Tuple[int, int]]]:
    """{name: [(block idx, op position), ...]} for every op write."""
    pos: Dict[str, List[Tuple[int, int]]] = {}
    for blk, _path, i, op in iter_ops(program, block_idx):
        for name in op.output_names():
            pos.setdefault(name, []).append((blk.idx, i))
    return pos


def rw_state_names(program: ir.Program, block_idx: int = 0) -> List[str]:
    """Persistable vars the program both reads and writes — the set the
    executor donates to the jitted step (params + optimizer state)."""
    reads, writes = set(), set()
    for blk, _path, _i, op in iter_ops(program, block_idx):
        for name in op.input_names():
            v = blk.find_var_recursive(name)
            if v is not None and v.persistable:
                reads.add(name)
        for name in op.output_names():
            v = blk.find_var_recursive(name)
            if v is not None and v.persistable:
                writes.add(name)
    return sorted(reads & writes)


class PassContext:
    """Everything a pass may consult for one verification run."""

    def __init__(self, program: ir.Program,
                 startup: Optional[ir.Program] = None,
                 feed_names: Optional[Iterable[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None,
                 block_idx: int = 0,
                 donate: bool = False,
                 async_dispatch: bool = False,
                 report: Optional[VerifyReport] = None):
        self.program = program
        self.startup = startup
        self.feed_names = (None if feed_names is None
                           else set(feed_names))
        self.fetch_names = (None if fetch_names is None
                            else list(fetch_names))
        self.block_idx = block_idx
        self.donate = donate
        self.async_dispatch = async_dispatch
        self.report = report if report is not None else VerifyReport()
        # memoized across passes
        self._written: Optional[Set[str]] = None

    @property
    def written(self) -> Set[str]:
        if self._written is None:
            self._written = _written_names(self.program, self.block_idx)
        return self._written

    def diag(self, severity, code, message, path, op_index=None,
             op_type=None, var=None, hint=None) -> Diagnostic:
        return self.report.add(Diagnostic(
            severity, code, message, block_path=path, op_index=op_index,
            op_type=op_type, var=var, hint=hint))


class AnalysisPass:
    """Base class: subclasses set `name` and implement run(ctx)."""

    name = "pass"

    def run(self, ctx: PassContext) -> None:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


PASS_REGISTRY: Dict[str, type] = {}


def register_pass(cls):
    """Class decorator: make a pass available by name to the verifier
    (``ProgramVerifier(passes=["def_use", ...])``)."""
    PASS_REGISTRY[cls.name] = cls
    return cls


def default_passes() -> List[AnalysisPass]:
    return [DefBeforeUsePass(), ShapeDtypePass(), UninitPersistablePass(),
            DeadCodePass(), DonationHazardPass()]


def fast_passes(with_uninit: bool = False) -> List[AnalysisPass]:
    """THE no-retrace gate pipeline: structural passes plus the
    marker-reading shape pass — pure Python, O(ops), what the hot
    executor gate runs per compile miss. ``with_uninit=True`` adds
    uninitialized-persistable detection for callers that know the
    startup program (trainer setup, the lint CLI's network mode).
    Defined once so the gates cannot drift from each other."""
    passes: List[AnalysisPass] = [DefBeforeUsePass(),
                                  ShapeDtypePass(retrace=False)]
    if with_uninit:
        passes.append(UninitPersistablePass())
    passes.extend([DeadCodePass(), DonationHazardPass()])
    return passes


# ---------------------------------------------------------------------------
@register_pass
class DefBeforeUsePass(AnalysisPass):
    """Dangling-name and def-before-use resolution.

    - ``dangling-input`` (error): an op input that resolves to NO
      VarDesc anywhere along the block parent chain — the trace env
      lookup would KeyError deep inside JAX.
    - ``read-never-written`` (error in the root block when the feed set
      is known, warning otherwise): a declared non-persistable var that
      is read but written by no op, not fed, and not injected by
      control-flow machinery.
    - ``read-before-write`` (same severity scheme): the var IS written,
      but every write sits at a LATER position in the SAME block as the
      first read — there is no earlier same-block write and no writer
      in any other block (a loop-carry initialized outside the body, or
      a parent-block producer, excuses the pattern), so the first
      execution reads an undefined value.
    """

    name = "def_use"

    def run(self, ctx: PassContext) -> None:
        written = ctx.written
        writes_at = _write_positions(ctx.program, ctx.block_idx)
        feeds = ctx.feed_names
        flagged: Set[str] = set()
        for blk, path, i, op in iter_ops(ctx.program, ctx.block_idx):
            for name in op.input_names():
                v = blk.find_var_recursive(name)
                if v is None:
                    ctx.diag(
                        Severity.ERROR, "dangling-input",
                        f"op input {name!r} does not resolve to any "
                        f"variable along the block parent chain",
                        path, i, op.type, var=name,
                        hint="declare the variable in this block (or an "
                             "ancestor), or fix the op's input wiring")
                    continue
                if name in flagged:
                    continue
                if name in written:
                    ws = writes_at.get(name)
                    # op-written (not machinery-injected): ordered
                    # check — only definite when every writer is a
                    # later op of THIS block (any outside-block or
                    # earlier writer may feed the first execution)
                    if ws and not v.persistable \
                            and (feeds is None or name not in feeds) \
                            and all(b == blk.idx and j > i
                                    for b, j in ws):
                        flagged.add(name)
                        in_root = len(path) == 1
                        ctx.diag(
                            Severity.ERROR if in_root
                            and feeds is not None else Severity.WARNING,
                            "read-before-write",
                            f"var {name!r} is read here but only "
                            f"written later in this block (op "
                            f"position(s) {sorted(j for _, j in ws)}) "
                            f"— the first execution reads an "
                            f"undefined value",
                            path, i, op.type, var=name,
                            hint="move the producer before this op, "
                                 "or initialize the variable first")
                    continue
                if v.persistable or v.initializer is not None:
                    continue
                if v.type in _OPAQUE_VAR_TYPES:
                    continue
                if feeds is not None and name in feeds:
                    continue
                in_root = len(path) == 1
                if feeds is None:
                    # without a feed set, a never-written root-block var
                    # is indistinguishable from a feed placeholder
                    if not in_root:
                        flagged.add(name)
                        ctx.diag(
                            Severity.WARNING, "read-never-written",
                            f"var {name!r} is read but no op or "
                            f"control-flow machinery writes it",
                            path, i, op.type, var=name,
                            hint="if this is a feed, pass feed names to "
                                 "the verifier to silence this")
                    continue
                flagged.add(name)
                ctx.diag(
                    Severity.ERROR if in_root else Severity.WARNING,
                    "read-never-written",
                    f"var {name!r} is read by this op but never written "
                    f"by any op and not in the feed set",
                    path, i, op.type, var=name,
                    hint="feed the variable, or add the op that "
                         "produces it before this point")


# ---------------------------------------------------------------------------
@register_pass
class ShapeDtypePass(AnalysisPass):
    """Declared vs inferred dtype/shape consistency, plus inference
    coverage.

    Re-runs the registry's abstract inference
    (`framework.infer_op_outputs` — pure, never mutates the program)
    per op and compares against the declared VarDescs:

    - ``dtype-mismatch``: inferred and declared dtypes are in
      different families (float/int/bool). A bool⇄number conflict is
      an ERROR (almost always a condition wired to the wrong slot);
      int⇄float drift is a WARNING — python-scalar promotion routinely
      floats an int tensor (e.g. ``scale``) while the declared dtype
      stays behind, and the runtime follows the trace, not the
      declaration. Same-family width drift (f32 vs bf16 under AMP,
      i32 vs i64 under x64-off) is tolerated outright.
    - ``shape-mismatch`` (warning): rank differs, or two static extents
      conflict (-1 wildcards match anything).
    - ``shape-coverage`` (warning): the op has neither a traceable
      compute rule nor an explicit `infer_shape` rule — its outputs
      flow through the builder unchecked.
    """

    name = "shape_dtype"

    def __init__(self, retrace: bool = True):
        # retrace=True re-runs abstract inference per op — thorough,
        # used by the standalone verifier / CLI / serving load.
        # retrace=False reads the markers the BUILDER stamped
        # (SHAPE_INFER_SKIPPED_ATTR / SHAPE_INFER_CONFLICT_ATTR): pure
        # dict walks, cheap enough for the per-compile executor gate.
        self.retrace = retrace

    def run(self, ctx: PassContext) -> None:
        from ..framework import (SHAPE_INFER_CONFLICT_ATTR,
                                 SHAPE_INFER_SKIPPED_ATTR,
                                 infer_op_outputs)
        for blk, path, i, op in iter_ops(ctx.program, ctx.block_idx):
            if not self.retrace:
                skip = op.attrs.get(SHAPE_INFER_SKIPPED_ATTR)
                if skip is not None:
                    self._coverage(ctx, path, i, op, skip)
                for c in op.attrs.get(SHAPE_INFER_CONFLICT_ATTR) or ():
                    self._conflict_diag(ctx, path, i, op, c)
                continue
            outs, skip = infer_op_outputs(blk, op)
            if outs is None:
                # the generic trace can't run this op — give its
                # explicit infer_shape rule (control-flow family) a
                # chance, so the full-retrace cold gates check those
                # conflicts too, not just build-time markers
                opdef = (OpRegistry.get(op.type)
                         if OpRegistry.has(op.type) else None)
                rule = opdef.infer_shape if opdef is not None else None
                if rule is not None:
                    try:
                        outs, skip = rule(blk, op) or {}, None
                    except Exception as e:
                        skip = ("explicit rule failed: "
                                f"{type(e).__name__}")
                if outs is not None:
                    # a partial rule (resolves only some outputs) must
                    # still report the rest as uncovered — same
                    # definition as build-time marker stamping
                    from ..framework import (RULE_UNRESOLVED_PREFIX,
                                             unresolved_outputs)
                    unresolved = unresolved_outputs(blk, op,
                                                    covered=outs)
                    if unresolved:
                        self._coverage(
                            ctx, path, i, op,
                            RULE_UNRESOLVED_PREFIX + str(unresolved[:3]))
            if outs is None:
                self._coverage(ctx, path, i, op, skip)
                continue
            for name, spec in outs.items():
                v = blk.find_var_recursive(name)
                if v is None:
                    continue  # def_use reports the dangling name
                for c in self.compare(name, v, spec):
                    self._conflict_diag(ctx, path, i, op, c)

    @staticmethod
    def compare(name, v, spec) -> List[Dict]:
        """Declared VarDesc vs inferred spec: a list of conflict dicts
        (empty = consistent). Shared by this pass and the builder's
        conflict stamping (framework._apply_inferred) so gate-time
        marker reads and full re-traces agree on what a conflict is."""
        conflicts: List[Dict] = []
        inferred_dt = spec.get("dtype")
        if v.dtype is not None and inferred_dt is not None:
            fam_d = _DTYPE_FAMILY.get(v.dtype)
            fam_i = _DTYPE_FAMILY.get(inferred_dt)
            if fam_d and fam_i and fam_d != fam_i:
                conflicts.append({"kind": "dtype", "var": name,
                                  "declared": v.dtype,
                                  "inferred": inferred_dt})
        inferred_sh = spec.get("shape")
        if v.shape is None or inferred_sh is None:
            return conflicts
        # ragged outputs compare feature dims only when levels agree;
        # a level mismatch changes which axes the declared shape omits
        if spec.get("lod_level", 0) != v.lod_level:
            return conflicts
        if len(v.shape) != len(inferred_sh):
            conflicts.append({"kind": "rank", "var": name,
                              "declared": list(v.shape),
                              "inferred": list(inferred_sh)})
            return conflicts
        for d, (a, b) in enumerate(zip(v.shape, inferred_sh)):
            # anything non-static (-1, None, or a non-int placeholder)
            # is a wildcard — only two concrete ints can conflict
            if not isinstance(a, int) or not isinstance(b, int):
                continue
            if a != -1 and b != -1 and a != b:
                conflicts.append({"kind": "dim", "var": name, "dim": d,
                                  "declared": list(v.shape),
                                  "inferred": list(inferred_sh)})
                break
        return conflicts

    @staticmethod
    def _coverage(ctx, path, i, op, skip):
        opdef = (OpRegistry.get(op.type)
                 if OpRegistry.has(op.type) else None)
        rule_failed = isinstance(skip, str) and \
            skip.startswith("explicit rule")
        if opdef is not None and opdef.infer_shape is not None \
                and not rule_failed:
            return  # covered by an explicit rule (that worked)
        ctx.diag(
            Severity.WARNING, "shape-coverage",
            f"op has no shape-inference coverage ({skip}); its "
            f"outputs are unchecked until the executor trace",
            path, i, op.type,
            hint="register an infer_shape rule on the OpDef, or "
                 "declare input shapes")

    @staticmethod
    def _conflict_diag(ctx, path, i, op, c):
        name = c.get("var")
        if c.get("kind") == "dtype":
            fam_d = _DTYPE_FAMILY.get(c["declared"])
            fam_i = _DTYPE_FAMILY.get(c["inferred"])
            # bool⇄number: a condition wired into a numeric slot (or
            # vice versa) — error. int⇄float: benign scalar-promotion
            # drift; the executor follows the trace — warning.
            sev = Severity.ERROR if "bool" in (fam_d, fam_i) \
                else Severity.WARNING
            ctx.diag(
                sev, "dtype-mismatch",
                f"output {name!r} is declared {c['declared']} but the "
                f"op's compute rule produces {c['inferred']}",
                path, i, op.type, var=name,
                hint=f"fix the variable's declared dtype (or cast the "
                     f"op result to {c['declared']})")
        elif c.get("kind") == "rank":
            ctx.diag(
                Severity.WARNING, "shape-mismatch",
                f"output {name!r} is declared rank "
                f"{len(c['declared'])} {c['declared']} but the compute "
                f"rule produces rank {len(c['inferred'])} "
                f"{c['inferred']}",
                path, i, op.type, var=name)
        else:
            ctx.diag(
                Severity.WARNING, "shape-mismatch",
                f"output {name!r} dim {c.get('dim')}: declared "
                f"{c['declared']} vs inferred {c['inferred']}",
                path, i, op.type, var=name)


# ---------------------------------------------------------------------------
@register_pass
class UninitPersistablePass(AnalysisPass):
    """Persistable vars read by the main program must be initialized by
    the paired startup program (or carry a builder initializer) — a
    miss surfaces at runtime as a scope KeyError mid-trace, or worse,
    as stale state from an earlier test. Runs only when the verifier is
    given the startup program (weights loaded from a checkpoint are
    initialized out-of-band, so the pass would false-positive there).
    """

    name = "uninit_persistable"

    def run(self, ctx: PassContext) -> None:
        if ctx.startup is None:
            return
        startup_writes = _written_names(ctx.startup)
        program = ctx.program
        # first access of each persistable var in EXECUTION order: a
        # sub-block executes at its enclosing control-flow op, so its
        # reads/writes are interleaved there (an op's own inputs are
        # read before its body runs; its outputs are written after) —
        # iter_ops' blocks-last order would mis-attribute a body read
        # that precedes a later root-block write
        first: Dict[str, Tuple[str, Tuple[int, ...], int, str]] = {}
        seen_blocks: set = set()

        def record(blk, name, kind, path, i, op_type):
            v = blk.find_var_recursive(name)
            if v is not None and v.persistable and name not in first:
                first[name] = (kind, path, i, op_type)

        def visit(blk: ir.BlockDesc, path: Tuple[int, ...]):
            if blk.idx in seen_blocks:
                return
            seen_blocks.add(blk.idx)
            for i, op in enumerate(blk.ops):
                for name in op.input_names():
                    record(blk, name, "read", path, i, op.type)
                for attr in SUB_BLOCK_ATTRS:
                    idx = op.attrs.get(attr)
                    if isinstance(idx, int) \
                            and 0 <= idx < len(program.blocks):
                        visit(program.blocks[idx], path + (idx,))
                for name in op.output_names():
                    record(blk, name, "write", path, i, op.type)

        visit(program.blocks[ctx.block_idx], (ctx.block_idx,))
        for name, (kind, path, op_i, op_type) in sorted(first.items()):
            if kind != "read" or name in startup_writes:
                continue
            blk = ctx.program.blocks[path[-1]]
            v = blk.find_var_recursive(name)
            if v is not None and v.initializer is not None:
                continue
            ctx.diag(
                Severity.ERROR, "uninit-persistable",
                f"persistable var {name!r} is read before any write, "
                f"but the startup program never initializes it",
                path, op_i, op_type, var=name,
                hint="add an initializer op for it to the startup "
                     "program (or load it from a checkpoint before "
                     "running)")


# ---------------------------------------------------------------------------
@register_pass
class DeadCodePass(AnalysisPass):
    """Dead ops and unreachable vars relative to the fetch targets.

    Backward liveness over the root block: an op is live when an output
    is (transitively) needed by a fetch, or it has effects — writes
    persistable state, is host-stateful (channels/readers), or contains
    such an op in a sub-block. Root block only: liveness inside a
    sub-block depends on the enclosing op's carry semantics
    (KNOWN_GAPS: lints are heuristic).
    """

    name = "dead_code"

    def run(self, ctx: PassContext) -> None:
        if not ctx.fetch_names:
            return
        program = ctx.program
        root = program.blocks[ctx.block_idx]
        needed: Set[str] = set(ctx.fetch_names)
        live: List[bool] = [False] * len(root.ops)
        for i in range(len(root.ops) - 1, -1, -1):
            op = root.ops[i]
            if needed.intersection(op.output_names()) \
                    or self._has_effects(program, root, op):
                live[i] = True
                needed.update(op.input_names())
                needed.update(self._closure_reads(program, op))
        for i, op in enumerate(root.ops):
            if not live[i]:
                ctx.diag(
                    Severity.WARNING, "dead-op",
                    f"op contributes to no fetch target and has no "
                    f"side effects (fetches: {ctx.fetch_names})",
                    (ctx.block_idx,), i, op.type,
                    hint="remove it, or fetch one of its outputs")
        self._unreachable_vars(ctx, root)

    @staticmethod
    def _has_effects(program: ir.Program, block: ir.BlockDesc,
                     op: ir.OpDesc) -> bool:
        seen: Set[int] = set()   # guards corrupt self-referential blocks

        def visit(blk: ir.BlockDesc, o: ir.OpDesc) -> bool:
            if OpRegistry.has(o.type) and OpRegistry.get(o.type).stateful:
                return True
            for name in o.output_names():
                # resolve along the op's OWN parent chain — a same-named
                # persistable var in an unrelated block is not an effect
                v = blk.find_var_recursive(name)
                if v is not None and v.persistable:
                    return True
            for attr in SUB_BLOCK_ATTRS:
                idx = o.attrs.get(attr)
                if isinstance(idx, int) and 0 <= idx < len(program.blocks) \
                        and idx not in seen:
                    seen.add(idx)
                    sub = program.blocks[idx]
                    if any(visit(sub, s) for s in sub.ops):
                        return True
            return False
        return visit(block, op)

    @staticmethod
    def _closure_reads(program: ir.Program, op: ir.OpDesc,
                       _seen: Optional[Set[int]] = None) -> Set[str]:
        """Sub-block ops read enclosing-scope vars directly (closure
        style); a live control-flow op therefore needs every name its
        body reads."""
        seen = set() if _seen is None else _seen
        reads: Set[str] = set()
        for attr in SUB_BLOCK_ATTRS:
            idx = op.attrs.get(attr)
            if isinstance(idx, int) and 0 <= idx < len(program.blocks) \
                    and idx not in seen:
                seen.add(idx)
                for sub_op in program.blocks[idx].ops:
                    reads.update(sub_op.input_names())
                    reads.update(DeadCodePass._closure_reads(
                        program, sub_op, seen))
        return reads

    def _unreachable_vars(self, ctx: PassContext, root: ir.BlockDesc):
        referenced: Set[str] = set()
        for _blk, _path, _i, op in iter_ops(ctx.program, ctx.block_idx):
            referenced.update(op.input_names())
            referenced.update(op.output_names())
        feeds = ctx.feed_names or set()
        fetches = set(ctx.fetch_names or ())
        for name, v in root.vars.items():
            if name in referenced or name in feeds or name in fetches:
                continue
            if v.persistable or v.is_parameter:
                continue
            ctx.diag(
                Severity.INFO, "unreachable-var",
                f"var {name!r} is declared but referenced by no op, "
                f"feed, or fetch", (ctx.block_idx,), var=name,
                hint="drop the declaration, or wire it into the graph")


# ---------------------------------------------------------------------------
@register_pass
class DonationHazardPass(AnalysisPass):
    """Fetches of donated rw-state vars.

    With state donation on, the executor aliases read-write persistable
    buffers (params + optimizer accumulators) into the jitted step: an
    ASYNC fetch of such a var would hand back a lazy handle onto a
    buffer the next step donates (and XLA deletes). Previously this
    was only caught at runtime in core/executor.py; here the same
    hazard is flagged statically — as an error under
    (donate, async dispatch), as a warning otherwise (the sync
    materialize-before-next-step path is safe).
    """

    name = "donation"

    def run(self, ctx: PassContext) -> None:
        if not ctx.fetch_names:
            return
        rw = set(rw_state_names(ctx.program, ctx.block_idx))
        hazardous = [n for n in ctx.fetch_names if n in rw]
        if not hazardous:
            return
        is_error = ctx.donate and ctx.async_dispatch
        for name in hazardous:
            ctx.diag(
                Severity.ERROR if is_error else Severity.WARNING,
                "donated-fetch",
                f"fetch of donated state var {name!r}: with state "
                f"donation the lazy StepResult would hold a buffer the "
                f"next step donates (and XLA deletes)"
                + ("" if is_error else
                   " — safe now, but breaks under async dispatch "
                   "(sync=False) with donation on"),
                (ctx.block_idx,), var=name,
                hint=DONATED_FETCH_HINT)
