"""Static analysis over ProgramDesc-level IR: a pass-based verifier and
the pre-compile safety gates built on it.

    report = analysis.verify_program(main, startup=startup,
                                     feed_names=["x"],
                                     fetch_names=[loss.name])
    print(report.render_text())
    report.raise_if_errors()

See ``analysis.verifier`` for gate wiring (executor / serving /
trainer / io) and ``analysis.passes`` for the individual checks.
"""
from .diagnostics import (Diagnostic, Severity, VerificationError,  # noqa
                          VerifyReport)
from .passes import (AnalysisPass, PASS_REGISTRY, PassContext,  # noqa
                     default_passes, register_pass)
from .verifier import (ProgramVerifier, clear_gate_cache,  # noqa
                       executor_gate, verify_enabled, verify_program)
from .cost_model import (CostModelPass, OpCost, ProgramCost,  # noqa
                         program_cost)
from .memory import (MemoryPass, MemoryReport, VarInterval,  # noqa
                     check_budget, hbm_budget_bytes, program_memory)
from .rewrite import (RewritePass, RewriteResult,  # noqa
                      REWRITE_PASS_REGISTRY, default_rewrite_passes,
                      optimize_enabled, rewrite_program)

__all__ = [
    "Diagnostic", "Severity", "VerificationError", "VerifyReport",
    "AnalysisPass", "PASS_REGISTRY", "PassContext", "default_passes",
    "register_pass", "ProgramVerifier", "verify_program",
    "verify_enabled", "executor_gate", "clear_gate_cache",
    "CostModelPass", "OpCost", "ProgramCost", "program_cost",
    "MemoryPass", "MemoryReport", "VarInterval", "check_budget",
    "hbm_budget_bytes", "program_memory",
    "RewritePass", "RewriteResult", "REWRITE_PASS_REGISTRY",
    "default_rewrite_passes", "optimize_enabled", "rewrite_program",
]
