"""Static memory planner: liveness, peak-HBM estimate, OOM budget gate.

Walks every reachable op of a program (the verifier's execution-order
traversal, cycle-guarded sub-block descent) and assigns each referenced
variable a *buffer* with a live interval over the global op order.
Shapes come from the declared+inferred VarDescs with the cost model's
``-1`` binding: feed shapes bind exactly, a declared leading ``-1``
binds to the fed batch, other dynamic dims bind to 1.

Two numbers come out of the same walk:

- ``peak_bytes`` — the planner's headline estimate, under the
  *arena* model the executor actually implements: one buffer per
  distinct var name, allocated at its first reference and held to the
  end of the step (the trace env never frees mid-step; legacy Fluid
  freed only at scope exit). Persistable vars (params, optimizer
  state, KV caches) are resident for the whole step. This is an upper
  bound that the ``inplace_reuse`` rewrite pass genuinely tightens:
  renaming a dead buffer's successor onto it removes one arena slot.
- ``ideal_peak_bytes`` — the free-at-last-use interval sweep: what a
  perfect allocator (XLA's, roughly) could reach on the un-fused
  graph. The true device footprint lies between the two; see
  KNOWN_GAPS "Memory planning boundaries".

The ``memory`` analysis pass attaches a :class:`MemoryReport` to the
verify report; :func:`check_budget` turns an over-budget report into a
structured ``hbm-oom`` diagnostic that the Executor raises BEFORE the
program ever reaches XLA (``PADDLE_TPU_HBM_BYTES``, default one v5e
core's 16 GiB, 0 disables).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import ir
from .cost_model import _ITEMSIZE, _prod
from .diagnostics import Diagnostic, Severity, VerifyReport
from .passes import (AnalysisPass, PassContext, SUB_BLOCK_ATTRS,
                     register_pass)

__all__ = ["VarInterval", "MemoryReport", "program_memory",
           "MemoryPass", "check_budget", "hbm_budget_bytes",
           "publish_peak", "DEFAULT_HBM_BYTES"]

#: one TPU v5e core's HBM — the default pre-compile budget
DEFAULT_HBM_BYTES = 16 * 1024 ** 3


def hbm_budget_bytes() -> int:
    """The configured HBM budget: ``PADDLE_TPU_HBM_BYTES`` (bytes;
    ``0`` disables the gate), defaulting to one v5e core's 16 GiB."""
    raw = os.environ.get("PADDLE_TPU_HBM_BYTES", "")
    if not raw.strip():
        return DEFAULT_HBM_BYTES
    try:
        return max(0, int(float(raw)))
    except (TypeError, ValueError):
        return DEFAULT_HBM_BYTES


def _fmt_bytes(n: int) -> str:
    if n >= 1024 ** 3:
        return f"{n / 1024 ** 3:.2f} GiB"
    if n >= 1024 ** 2:
        return f"{n / 1024 ** 2:.2f} MiB"
    return f"{n} B"


class VarInterval:
    """One planned buffer: a var name, its bound shape/bytes, and the
    [first, last] op-step interval over the global execution order."""

    __slots__ = ("name", "shape", "dtype", "bytes", "kind",
                 "first", "last")

    def __init__(self, name: str, shape: Optional[List[int]],
                 dtype: Optional[str], nbytes: int, kind: str,
                 first: int, last: int):
        self.name = name
        self.shape = shape
        self.dtype = dtype
        self.bytes = int(nbytes)
        self.kind = kind            # "resident" | "activation"
        self.first = int(first)
        self.last = int(last)

    def to_dict(self) -> Dict:
        return {"name": self.name, "shape": self.shape,
                "dtype": self.dtype, "bytes": self.bytes,
                "kind": self.kind, "first": self.first,
                "last": self.last}

    def __repr__(self):
        return (f"VarInterval({self.name!r}, {self.bytes} B, "
                f"{self.kind}, [{self.first}, {self.last}])")


class MemoryReport:
    """Liveness intervals plus the peak-HBM estimate of one block tree.

    ``peak_bytes`` is the arena (no mid-step free) watermark:
    ``resident_bytes`` + one buffer per distinct activation name.
    ``ideal_peak_bytes`` is the interval-sweep lower bound a perfect
    allocator could reach. ``high_water`` locates the op at which the
    arena watermark is reached (the last first-allocation)."""

    def __init__(self, intervals: List[VarInterval], n_ops: int,
                 batch: int, block_idx: int,
                 order: List[Tuple[Tuple[int, ...], int, str]],
                 unresolved: int, label: str = "program"):
        self.intervals = intervals
        self.n_ops = int(n_ops)
        self.batch = int(batch)
        self.block_idx = int(block_idx)
        self.unresolved = int(unresolved)
        self.label = label
        self.resident_bytes = sum(v.bytes for v in intervals
                                  if v.kind == "resident")
        self.activation_bytes = sum(v.bytes for v in intervals
                                    if v.kind == "activation")
        self.peak_bytes = self.resident_bytes + self.activation_bytes
        acts = [v for v in intervals
                if v.kind == "activation" and v.bytes]
        # arena watermark is non-decreasing: it tops out at the LAST
        # first-allocation of any non-empty activation buffer
        self.high_water_step = max((v.first for v in acts), default=0)
        self.high_water = None
        if order and 0 <= self.high_water_step < len(order):
            path, op_i, op_type = order[self.high_water_step]
            self.high_water = {"block_path": list(path),
                               "op_index": op_i, "op_type": op_type,
                               "step": self.high_water_step}
        # free-at-last-use sweep: the ideal-allocator lower bound
        delta: Dict[int, int] = {}
        for v in acts:
            delta[v.first] = delta.get(v.first, 0) + v.bytes
            delta[v.last + 1] = delta.get(v.last + 1, 0) - v.bytes
        cur = peak = 0
        for t in sorted(delta):
            cur += delta[t]
            peak = max(peak, cur)
        self.ideal_peak_bytes = self.resident_bytes + peak

    def top(self, k: int = 10) -> List[VarInterval]:
        """The k largest buffers live at the peak (under the arena
        model every planned buffer is live there)."""
        return sorted(self.intervals, key=lambda v: -v.bytes)[:k]

    def table(self, limit: int = 10) -> str:
        hw = ""
        if self.high_water is not None:
            loc = "/".join(str(b) for b in
                           self.high_water["block_path"])
            hw = (f", high water @ b{loc}:op"
                  f"{self.high_water['op_index']} "
                  f"({self.high_water['op_type']})")
        lines = [
            f"memory {self.label} (block {self.block_idx}, "
            f"batch={self.batch}): peak {_fmt_bytes(self.peak_bytes)} "
            f"= {_fmt_bytes(self.resident_bytes)} resident + "
            f"{_fmt_bytes(self.activation_bytes)} activations over "
            f"{self.n_ops} op(s){hw}; ideal-allocator bound "
            f"{_fmt_bytes(self.ideal_peak_bytes)}"
            + (f"; {self.unresolved} name(s) unresolved"
               if self.unresolved else ""),
            f"{'bytes':>14s} {'kind':>10s} {'live':>13s}  var",
        ]
        for v in self.top(limit):
            lines.append(
                f"{v.bytes:14d} {v.kind:>10s} "
                f"{f'[{v.first},{v.last}]':>13s}  {v.name} "
                f"{v.shape if v.shape is not None else '?'} "
                f"{v.dtype or '?'}")
        if len(self.intervals) > limit:
            lines.append(
                f"  ... {len(self.intervals) - limit} more buffer(s)")
        return "\n".join(lines)

    def to_dict(self, top_k: int = 10) -> Dict:
        return {
            "label": self.label, "block_idx": self.block_idx,
            "batch": self.batch, "n_ops": self.n_ops,
            "n_buffers": len(self.intervals),
            "peak_bytes": self.peak_bytes,
            "resident_bytes": self.resident_bytes,
            "activation_bytes": self.activation_bytes,
            "ideal_peak_bytes": self.ideal_peak_bytes,
            "high_water": self.high_water,
            "unresolved": self.unresolved,
            "top": [v.to_dict() for v in self.top(top_k)],
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    def __repr__(self):
        return (f"MemoryReport({self.label}, "
                f"peak={self.peak_bytes}, "
                f"resident={self.resident_bytes}, "
                f"buffers={len(self.intervals)})")


# ---------------------------------------------------------------------------
def program_memory(program, block_idx: int = 0,
                   feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
                   batch: Optional[int] = None,
                   feed_names: Optional[Sequence[str]] = None,
                   label: Optional[str] = None) -> MemoryReport:
    """Liveness + peak-HBM plan for ``program`` (builder wrapper or
    core ``ir.Program``), rooted at ``block_idx``.

    Walks ops in EXECUTION order (an op's inputs are read before its
    sub-blocks run; its outputs are written after), so sub-block
    references land between the enclosing op's reads and writes.
    Buffers are keyed by var name program-wide — exactly the executor's
    name-keyed trace env. Feeds are materialized before op 0, so their
    intervals are pinned to start at step 0.
    """
    desc = program.desc if hasattr(program, "desc") else program
    feed_shapes = {k: tuple(int(d) for d in v)
                   for k, v in (feed_shapes or {}).items()}
    feeds = set(feed_names if feed_names is not None
                else feed_shapes.keys())
    root = desc.blocks[block_idx]
    if batch is None:
        batch = 1
        for name, shape in feed_shapes.items():
            v = root.find_var_recursive(name)
            if v is not None and v.shape and shape \
                    and len(v.shape) == len(shape) and v.shape[0] == -1:
                batch = int(shape[0])
                break
    batch = max(1, int(batch))

    order: List[Tuple[Tuple[int, ...], int, str]] = []
    # name -> [shape, dtype, bytes, persistable, resolvable, first, last]
    bufs: Dict[str, list] = {}
    resolve_cache: Dict[Tuple[int, str], Optional[tuple]] = {}

    def resolve(blk: ir.BlockDesc, name: str) -> Optional[tuple]:
        key = (blk.idx, name)
        if key in resolve_cache:
            return resolve_cache[key]
        v = blk.find_var_recursive(name)
        spec = None
        if v is not None:
            if name in feed_shapes:
                shape = list(feed_shapes[name])
            elif v.shape is not None:
                shape = [
                    (batch if j == 0 else 1)
                    if (not isinstance(d, int) or d == -1) else int(d)
                    for j, d in enumerate(v.shape)]
            else:
                shape = None
            nbytes = (_prod(shape)
                      * _ITEMSIZE.get(v.dtype or "float32", 4)
                      if shape is not None else 0)
            spec = (shape, v.dtype, nbytes, bool(v.persistable),
                    shape is not None)
        resolve_cache[key] = spec
        return spec

    def touch(blk: ir.BlockDesc, name: str, t: int):
        buf = bufs.get(name)
        if buf is None:
            spec = resolve(blk, name)
            if spec is None:
                return
            bufs[name] = list(spec) + [t, t]
        else:
            buf[5] = min(buf[5], t)
            buf[6] = max(buf[6], t)

    seen_blocks: set = set()

    def visit(blk: ir.BlockDesc, path: Tuple[int, ...]):
        if blk.idx in seen_blocks:
            return
        seen_blocks.add(blk.idx)
        for i, op in enumerate(blk.ops):
            t = len(order)
            order.append((path, i, op.type))
            for name in op.input_names():
                touch(blk, name, t)
            for attr in SUB_BLOCK_ATTRS:
                idx = op.attrs.get(attr)
                if isinstance(idx, int) \
                        and 0 <= idx < len(desc.blocks):
                    visit(desc.blocks[idx], path + (idx,))
            # writes land after the op's sub-blocks finished: the last
            # step issued so far (== t when there is no sub-block)
            t_out = len(order) - 1
            for name in op.output_names():
                touch(blk, name, t_out)

    # feeds exist before the first op runs
    for name in sorted(feeds):
        touch(root, name, 0)
    visit(root, (block_idx,))

    n_ops = len(order)
    last_step = max(0, n_ops - 1)
    intervals: List[VarInterval] = []
    unresolved = 0
    for name, (shape, dtype, nbytes, persistable, resolvable,
               first, last) in sorted(bufs.items()):
        if not resolvable:
            unresolved += 1
        if persistable:
            # params / optimizer state / KV caches: resident all step
            intervals.append(VarInterval(name, shape, dtype, nbytes,
                                         "resident", 0, last_step))
        else:
            if name in feeds:
                first = 0
            intervals.append(VarInterval(name, shape, dtype, nbytes,
                                         "activation", first, last))
    return MemoryReport(intervals, n_ops, batch, block_idx, order,
                        unresolved,
                        label=label or f"program uid={desc.uid}")


# ---------------------------------------------------------------------------
def check_budget(report: MemoryReport, budget: Optional[int] = None,
                 top_k: int = 5) -> VerifyReport:
    """Diagnose ``report.peak_bytes`` against the HBM budget.

    Returns a :class:`VerifyReport` that is clean when the plan fits
    (or the gate is disabled with budget 0) and carries one structured
    ``hbm-oom`` ERROR — top-K offenders, high-water op index, fix
    hint — when it does not. Callers gate with ``raise_if_errors()``.
    """
    if budget is None:
        budget = hbm_budget_bytes()
    vr = VerifyReport(program_label=report.label)
    vr.memory = report
    if budget <= 0 or report.peak_bytes <= budget:
        return vr
    offenders = ", ".join(
        f"{v.name} {_fmt_bytes(v.bytes)} ({v.kind})"
        for v in report.top(top_k))
    hw = report.high_water or {}
    vr.add(Diagnostic(
        Severity.ERROR, "hbm-oom",
        f"static peak-HBM estimate {_fmt_bytes(report.peak_bytes)} "
        f"({_fmt_bytes(report.resident_bytes)} resident + "
        f"{_fmt_bytes(report.activation_bytes)} activations) exceeds "
        f"the {_fmt_bytes(budget)} budget; top buffers: {offenders}",
        block_path=hw.get("block_path") or (report.block_idx,),
        op_index=hw.get("op_index"), op_type=hw.get("op_type"),
        hint="reduce batch/sequence length or cache buckets, keep "
             "PADDLE_TPU_INPLACE_REUSE=1, or raise PADDLE_TPU_HBM_BYTES "
             "(0 disables this gate); the estimate is the pre-XLA "
             "no-reuse upper bound — see the `memory` analysis pass"))
    return vr


# ---------------------------------------------------------------------------
@register_pass
class MemoryPass(AnalysisPass):
    """Attach a :class:`MemoryReport` to the verify report
    (``report.memory``). Like the cost pass it produces no diagnostics
    by itself — budget enforcement is :func:`check_budget`, wired into
    the Executor's pre-compile gate."""

    name = "memory"

    def __init__(self, feed_shapes=None, batch=None):
        self.feed_shapes = feed_shapes
        self.batch = batch

    def run(self, ctx: PassContext) -> None:
        ctx.report.memory = program_memory(
            ctx.program, ctx.block_idx, feed_shapes=self.feed_shapes,
            batch=self.batch, feed_names=ctx.feed_names,
            label=ctx.report.program_label)


# ---------------------------------------------------------------------------
_obs_cache = None


def publish_peak(job: str, peak_bytes: int) -> None:
    """Best-effort gauge of the most recent compile's static peak
    (``paddle_tpu_memory_peak_bytes{job}``) — same registry-identity
    caching as the rewrite pipeline's publisher."""
    global _obs_cache
    try:
        from ..observability import default_registry
        reg = default_registry()
        if reg is None:
            return
        cache = _obs_cache
        if cache is None or cache[0] is not reg:
            g = reg.gauge(
                "paddle_tpu_memory_peak_bytes",
                "Static pre-compile peak-HBM estimate of the most "
                "recently dispatched program (arena model, bytes)",
                ("job",))
            cache = _obs_cache = (reg, g)
        cache[1].labels(job=str(job)).set(float(peak_bytes))
    except Exception:
        pass  # telemetry must never break a dispatch
