"""ProgramDesc rewrite passes: the transform layer on the analysis
framework.

PR 5's passes (analysis/passes.py) walk the IR and *report*; this module
adds passes that *rewrite* — the "from verifier to optimizer" step of
ROADMAP item 5, the graph-rewriting layer the TensorFlow paper treats as
core runtime infrastructure and the pre-XLA grouping the XLA-fusion
paper shows XLA will not recover on its own (PAPERS.md):

- ``dce``            dead-op elimination (liveness against the fetch
                     set, same effect rules as the dead_code verifier
                     pass, but conservative enough to delete);
- ``cse``            common-subexpression elimination over pure ops
                     with identical inputs and attrs;
- ``const_fold``     constant folding of ops whose inputs are all
                     startup-independent literals (fill_constant /
                     assign_value chains), evaluated eagerly with the
                     op's own compute rule;
- ``fuse_attention`` pattern-match the composed scaled-dot-product
                     attention chain (matmul -> [scale] -> [+mask] ->
                     softmax -> matmul) and outline it into ONE
                     ``scaled_dot_product_attention`` mega-op — the op
                     that dispatches to the Pallas flash kernel — with
                     the chain's ``__vjp__`` grad ops merged into one
                     ``__vjp__`` of the mega-op, so the kernel's
                     backward engages too;
- ``fuse_se``        same outlining for the SE (squeeze-excitation)
                     block (global avgpool -> fc/relu -> fc/sigmoid ->
                     reshape -> channel gate) into a ``se_block``
                     mega-op;
- ``kernel_dispatch`` annotate lstm/gru (and sdpa) ops with a
                     program-level ``__pallas__``/``use_flash`` dispatch
                     decision, replacing trace-time env sniffing with an
                     IR-visible, lintable attribute.

Safety contract: every pass runs on a CLONE; after each pass the
``fast_passes()`` verifier re-checks the program and a failed
verification discards that pass's changes (the verifier as the rewrite
safety net). The executor falls back to the unrewritten program when
nothing survives. Rewrites never touch persistable state names, never
remove ops with sub-blocks or host side effects, and never rename a
name referenced from op attrs (control-flow carried/cond names).

Wired into ``Executor.run``'s compile-cache-miss path behind
``PADDLE_TPU_OPTIMIZE`` (default on, flags.py); offline via
``tools/lint_ir.py --optimize``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import ir
from ..core.ir import SUB_BLOCK_ATTRS
from ..core.registry import OpRegistry, run_op
from .cost_model import ITEMSIZE as _ITEMSIZE
from .passes import fast_passes, iter_blocks, iter_ops, rw_state_names
from .verifier import verify_program

__all__ = ["optimize_enabled", "RewritePass", "RewriteResult",
           "default_rewrite_passes", "rewrite_program",
           "REWRITE_PASS_REGISTRY"]

#: builder bookkeeping attrs — never part of an op's semantic identity
_MARKER_ATTRS = ("__shape_infer_skipped__", "__shape_infer_conflict__",
                 "__dead_vars__")

#: ops whose compute draws from the per-step PRNG (or host state) —
#: never CSE'd, never folded
_RANDOM_OPS = frozenset({
    "dropout", "uniform_random", "gaussian_random",
    "truncated_gaussian_random", "randint", "sampling_id", "nce",
    "uniform_random_batch_size_like", "gaussian_random_batch_size_like",
})

#: plumbing ops that must survive any rewrite
_KEEP_OPS = frozenset({"feed", "fetch", "print"})


def optimize_enabled() -> bool:
    """The PADDLE_TPU_OPTIMIZE kill switch, read per call (same pattern
    as verifier.verify_enabled)."""
    return os.environ.get("PADDLE_TPU_OPTIMIZE", "1") != "0"


def _desc(program) -> ir.Program:
    return program.desc if hasattr(program, "desc") else program


def _has_sub_block(op: ir.OpDesc) -> bool:
    return any(isinstance(op.attrs.get(a), int) for a in SUB_BLOCK_ATTRS)


def _is_stateful(op: ir.OpDesc) -> bool:
    """Host-side effects: the op's own compute, or — for the generic
    grad op — the embedded forward op it REPLAYS under jax.vjp."""
    if not OpRegistry.has(op.type):
        return True  # unknown op: assume the worst
    if OpRegistry.get(op.type).stateful:
        return True
    if op.type == "__vjp__":
        fwd_type = (op.attrs.get("fwd_op") or {}).get("type")
        if fwd_type is None or not OpRegistry.has(fwd_type):
            return True
        return OpRegistry.get(fwd_type).stateful
    return False


def _attr_referenced_names(program: ir.Program, block_idx: int
                           ) -> Set[str]:
    """Every string appearing in op attrs (except the embedded
    ``fwd_op`` replay dicts and builder markers). Control-flow ops read
    outer vars by attr name (``cond_name``, ``carried_names``, ...);
    any such name must be treated as live and never renamed."""
    names: Set[str] = set()

    def collect(v):
        if isinstance(v, str):
            names.add(v)
        elif isinstance(v, (list, tuple)):
            for e in v:
                collect(e)
        elif isinstance(v, dict):
            for e in v.values():
                collect(e)

    for _blk, _path, _i, op in iter_ops(program, block_idx):
        for key, v in op.attrs.items():
            if key == "fwd_op" or key in _MARKER_ATTRS:
                continue
            collect(v)
    return names


def _writer_counts(program: ir.Program, block_idx: int) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for _blk, _path, _i, op in iter_ops(program, block_idx):
        for n in op.output_names():
            counts[n] = counts.get(n, 0) + 1
    return counts


def _clean_attrs(op: ir.OpDesc) -> Dict[str, Any]:
    return {k: v for k, v in op.attrs.items() if k not in _MARKER_ATTRS}


class RewriteContext:
    """Everything one rewrite pass may consult (mirror of PassContext,
    for transforms)."""

    def __init__(self, block_idx: int = 0,
                 feed_names: Optional[Sequence[str]] = None,
                 fetch_names: Optional[Sequence[str]] = None):
        self.block_idx = block_idx
        self.feed_names = set(feed_names or ())
        self.fetch_names = list(fetch_names or ())


class RewritePass:
    """Base class: subclasses set ``name`` and implement
    ``apply(program, ctx) -> list[action dict]`` mutating ``program``
    in place. Actions are ``{"action": ..., "op_type": ..., ...}``."""

    name = "rewrite"

    def apply(self, program: ir.Program, ctx: RewriteContext
              ) -> List[Dict]:
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


REWRITE_PASS_REGISTRY: Dict[str, type] = {}


def register_rewrite_pass(cls):
    REWRITE_PASS_REGISTRY[cls.name] = cls
    return cls


# ---------------------------------------------------------------------------
# dead-op elimination
# ---------------------------------------------------------------------------
@register_rewrite_pass
class DeadOpElimination(RewritePass):
    """Remove root-block ops that contribute to no fetch target and have
    no effects. The liveness mirrors the ``dead_code`` verifier pass,
    tightened for deletion: ops with sub-blocks, host-stateful ops,
    persistable writers, and plumbing (feed/fetch/print) are always
    kept, and every name read from a sub-block (closure) or referenced
    from an op attr (control-flow carried/cond names) is a liveness
    root."""

    name = "dce"

    def apply(self, program, ctx) -> List[Dict]:
        root = program.blocks[ctx.block_idx]
        needed: Set[str] = set(ctx.fetch_names)
        needed |= _attr_referenced_names(program, ctx.block_idx)
        # closure reads: every input of every reachable non-root op
        for blk, _path in iter_blocks(program, ctx.block_idx):
            if blk is root:
                continue
            for op in blk.ops:
                needed.update(op.input_names())

        def must_keep(op: ir.OpDesc) -> bool:
            if op.type in _KEEP_OPS or _has_sub_block(op) \
                    or _is_stateful(op):
                return True
            for n in op.output_names():
                v = root.find_var_recursive(n)
                if v is not None and v.persistable:
                    return True
            return False

        keep = [False] * len(root.ops)
        for i in range(len(root.ops) - 1, -1, -1):
            op = root.ops[i]
            if must_keep(op) or needed.intersection(op.output_names()):
                keep[i] = True
                needed.update(op.input_names())

        actions: List[Dict] = []
        for i in range(len(root.ops) - 1, -1, -1):
            if not keep[i]:
                actions.append({"action": "remove_op",
                                "op_type": root.ops[i].type,
                                "op_index": i})
                del root.ops[i]
        if actions:
            program._bump_version()
        return actions


# ---------------------------------------------------------------------------
# common-subexpression elimination
# ---------------------------------------------------------------------------
@register_rewrite_pass
class CommonSubexpressionElimination(RewritePass):
    """Merge root-block ops with identical (type, inputs, attrs): the
    second op is removed and every read of its outputs is renamed to the
    first op's outputs (the read must RESOLVE to the root declaration —
    shadowed sub-block names are left alone). Only pure, single-writer,
    non-random ops participate; ops whose outputs are fetched,
    persistable, or attr-referenced are skipped."""

    name = "cse"

    def apply(self, program, ctx) -> List[Dict]:
        root = program.blocks[ctx.block_idx]
        writers = _writer_counts(program, ctx.block_idx)
        attr_names = _attr_referenced_names(program, ctx.block_idx)
        fetches = set(ctx.fetch_names)
        alias: Dict[str, str] = {}
        # single-writer positions: (block idx, op idx) — the ordering
        # check below needs to know WHERE the one write happens
        writer_pos: Dict[str, Tuple[int, int]] = {}
        for blk, _path, j, op in iter_ops(program, ctx.block_idx):
            for n in op.output_names():
                writer_pos[n] = (blk.idx, j)

        def resolve(n: str) -> str:
            while n in alias:
                n = alias[n]
            return n

        def mergeable(op: ir.OpDesc) -> bool:
            if op.type in _KEEP_OPS or op.type in _RANDOM_OPS \
                    or _has_sub_block(op) or _is_stateful(op):
                return False
            if op.type == "__vjp__":
                fwd = (op.attrs.get("fwd_op") or {}).get("type")
                if fwd in _RANDOM_OPS or fwd is None:
                    return False
            outs = op.output_names()
            if not outs:
                return False
            for n in outs:
                v = root.find_var_recursive(n)
                if v is None or v.persistable or n in fetches \
                        or n in attr_names or writers.get(n, 0) != 1:
                    return False
            # inputs must be single-assignment so both occurrences see
            # the same value (feeds / startup-initialized persistables
            # have zero in-program writers); once-written inputs get an
            # ordering check at merge time
            for n in op.input_names():
                if writers.get(resolve(n), 0) > 1:
                    return False
            return True

        def same_value(op: ir.OpDesc, i1: int, i2: int) -> bool:
            """Both candidate positions observe the same input values:
            every once-written input's single write must be a ROOT op
            strictly outside the [first, second] candidate span — a
            persistable param updated by its optimizer between a
            pre-update and a post-update read (sgd writes it exactly
            once), or an in-place self-write where one CANDIDATE is
            the writer (increment(x, in_place=True) at i1 or i2),
            would otherwise alias a read to the wrong-epoch value."""
            for n in op.input_names():
                rn = resolve(n)
                if writers.get(rn, 0) != 1:
                    continue  # zero writers: feed / startup-initialized
                blk_idx, p = writer_pos[rn]
                if blk_idx != root.idx:
                    return False  # sub-block write: order unknowable
                if i1 <= p <= i2:
                    return False
            return True

        seen: Dict[Tuple, Tuple[ir.OpDesc, int]] = {}
        removed: List[int] = []
        actions: List[Dict] = []
        for i, op in enumerate(root.ops):
            if not mergeable(op):
                continue
            key = (op.type,
                   json.dumps({s: [resolve(n) for n in ns]
                               for s, ns in sorted(op.inputs.items())}),
                   json.dumps(_clean_attrs(op), sort_keys=True,
                              default=str),
                   json.dumps(sorted((s, len(ns))
                              for s, ns in op.outputs.items())))
            hit = seen.get(key)
            if hit is None:
                seen[key] = (op, i)
                continue
            first, i1 = hit
            if not same_value(op, i1, i):
                continue
            ok = True
            pairs = []
            for slot, names in op.outputs.items():
                fnames = first.outputs.get(slot, [])
                if len(fnames) != len(names):
                    ok = False
                    break
                pairs.extend(zip(names, fnames))
            if not ok:
                continue
            for dup, keep_name in pairs:
                alias[dup] = keep_name
            removed.append(i)
            actions.append({"action": "merge_op", "op_type": op.type,
                            "op_index": i})

        if not removed:
            return []
        for i in reversed(removed):
            del root.ops[i]
        # rename reads program-wide where resolution reaches the root
        # declaration (a same-named sub-block var shadows and stays)
        for blk, _path in iter_blocks(program, ctx.block_idx):
            for op in blk.ops:
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [
                        resolve(n) if n in alias and
                        blk.find_var_recursive(n) is root.vars.get(n)
                        else n
                        for n in names]
                # legacy memory-optimize annotations may pin liveness
                # decisions made before the merge — scrub touched names
                dead = op.attrs.get("__dead_vars__")
                if dead:
                    op.attrs["__dead_vars__"] = [
                        n for n in dead
                        if n not in alias and n not in alias.values()]
        program._bump_version()
        return actions


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------
@register_rewrite_pass
class ConstantFolding(RewritePass):
    """Evaluate ops whose inputs are all literal constants and replace
    them with ``assign_value`` ops carrying the result. Evaluation runs
    the op's own compute rule eagerly (same math the trace would run);
    folding is capped at ``MAX_ELEMS`` output elements so the program
    JSON never bloats."""

    name = "const_fold"

    #: literal producers that seed the constant environment
    SOURCE_OPS = frozenset({"fill_constant", "assign_value", "fill"})
    #: pure shape/arith ops safe to evaluate ahead of time
    FOLDABLE_OPS = frozenset({
        "cast", "scale", "reshape", "reshape2", "transpose",
        "transpose2", "unsqueeze", "squeeze", "concat",
        "elementwise_add", "elementwise_sub", "elementwise_mul",
        "elementwise_div", "elementwise_max", "elementwise_min",
        "elementwise_pow", "sum", "reduce_sum", "reduce_mean",
        "reduce_max", "reduce_min", "reduce_prod", "matmul", "mul",
        "one_hot", "expand", "stack", "relu", "abs", "sign",
        "fill_zeros_like", "fill_constant_like", "equal", "less_than",
        "logical_not", "logical_and", "logical_or", "ones_like",
        "zeros_like",
    })
    MAX_ELEMS = 65536
    _SAFE_DTYPES = frozenset({"float32", "float64", "int32", "int64",
                              "int16", "int8", "uint8", "bool"})

    def apply(self, program, ctx) -> List[Dict]:
        import jax.numpy as jnp

        root = program.blocks[ctx.block_idx]
        writers = _writer_counts(program, ctx.block_idx)
        fetches = set(ctx.fetch_names)
        consts: Dict[str, np.ndarray] = {}
        actions: List[Dict] = []

        def out_ok(name: str) -> bool:
            v = root.find_var_recursive(name)
            return (v is not None and not v.persistable
                    and name not in fetches
                    and writers.get(name, 0) == 1)

        def evaluate(op: ir.OpDesc) -> Optional[np.ndarray]:
            env = {n: jnp.asarray(consts[n]) for n in op.input_names()}
            try:
                outs = run_op(op, env, {})
            except Exception:
                return None
            name = op.output_names()[0]
            if name not in outs:
                return None
            val = np.asarray(outs[name])
            if val.size > self.MAX_ELEMS \
                    or val.dtype.name not in self._SAFE_DTYPES:
                return None
            return val

        for i, op in enumerate(root.ops):
            outs = op.output_names()
            if op.type in self.SOURCE_OPS:
                if len(outs) == 1 and out_ok(outs[0]) \
                        and not op.input_names():
                    val = evaluate(op)
                    if val is not None:
                        consts[outs[0]] = val
                continue
            if op.type not in self.FOLDABLE_OPS or len(outs) != 1 \
                    or not out_ok(outs[0]) or _has_sub_block(op):
                continue
            ins = op.input_names()
            if not ins or any(n not in consts for n in ins):
                continue
            val = evaluate(op)
            if val is None:
                continue
            consts[outs[0]] = val
            root.ops[i] = ir.OpDesc(
                "assign_value", {}, {"Out": [outs[0]]},
                {"shape": list(val.shape),
                 "dtype": ir.canon_dtype(val.dtype.name),
                 "values": val.reshape(-1).tolist(),
                 "__folded_from__": op.type})
            actions.append({"action": "fold_op", "op_type": op.type,
                            "op_index": i})
        if actions:
            program._bump_version()
        return actions


# ---------------------------------------------------------------------------
# dead-gradient pruning
# ---------------------------------------------------------------------------
@register_rewrite_pass
class DeadGradPruning(RewritePass):
    """Trim ``__vjp__`` gradient outputs nobody consumes.

    The generic grad op computes one cotangent per ``in_need_grad=True``
    entry; a gradient that flows to no optimizer, no fetch, and no
    downstream grad op is pure wasted backward compute (the classic
    case: an attention mask built from ``cast(equal(...))`` — the mask
    is float and differentiable, so backward dutifully grinds out
    mask-path gradients that dead-end at the non-differentiable cast).
    Flipping the flag to False removes that cotangent from the vjp; a
    grad op left with NO outputs is deleted, which un-reads ITS
    out-grads and lets the pruning cascade up the dead chain. Besides
    the saved compute, this unblocks fusion outlining on masked
    attention (the outliner refuses sites whose mask needs a
    gradient)."""

    name = "grad_prune"

    def apply(self, program, ctx) -> List[Dict]:
        root = program.blocks[ctx.block_idx]
        fetches = set(ctx.fetch_names)
        attr_names = _attr_referenced_names(program, ctx.block_idx)
        actions: List[Dict] = []
        changed = True
        while changed:
            changed = False
            readers: Dict[str, int] = {}
            for _blk, _path, _i, op in iter_ops(program, ctx.block_idx):
                for n in op.input_names():
                    readers[n] = readers.get(n, 0) + 1
            drop: List[int] = []
            for i, op in enumerate(root.ops):
                if op.type == "sum":
                    # gradient-accumulator sums orphaned by an earlier
                    # trim: removing them un-reads their contributions
                    # so the prune cascades through multi-consumer vars
                    outs = op.output_names()
                    if outs and all(
                            readers.get(n, 0) == 0 and n not in fetches
                            and n not in attr_names
                            and (root.find_var_recursive(n) is None
                                 or not root.find_var_recursive(n)
                                 .persistable)
                            for n in outs):
                        drop.append(i)
                        changed = True
                        actions.append({"action": "remove_op",
                                        "op_type": "sum",
                                        "op_index": i})
                    continue
                if op.type != "__vjp__" or _is_stateful(op):
                    continue
                fwd = ir.OpDesc.from_dict(op.attrs.get("fwd_op") or {})
                entries = fwd.input_names() + list(
                    op.attrs.get("closure_names") or [])
                need = list(op.attrs.get("in_need_grad") or [])
                grads = list(op.outputs.get("InGrad", []))
                if len(entries) != len(need) \
                        or sum(map(bool, need)) != len(grads):
                    continue  # malformed bookkeeping: leave untouched
                gi = 0
                kept: List[str] = []
                pruned = False
                for pos, nd in enumerate(need):
                    if not nd:
                        continue
                    g = grads[gi]
                    gi += 1
                    v = root.find_var_recursive(g)
                    if readers.get(g, 0) == 0 and g not in fetches \
                            and g not in attr_names \
                            and (v is None or not v.persistable):
                        need[pos] = False
                        pruned = True
                        actions.append({"action": "prune_grad",
                                        "op_type": fwd.type, "var": g})
                    else:
                        kept.append(g)
                if not pruned:
                    continue
                changed = True
                if kept:
                    op.outputs["InGrad"] = kept
                    op.attrs["in_need_grad"] = need
                else:
                    # outputless grad op: delete it so its out-grads
                    # become unread and the prune cascades upstream
                    drop.append(i)
                    actions.append({"action": "remove_op",
                                    "op_type": op.type, "op_index": i})
            for i in reversed(drop):
                del root.ops[i]
        if actions:
            program._bump_version()
        return actions


# ---------------------------------------------------------------------------
# subgraph outlining machinery (shared by the attention and SE passes)
# ---------------------------------------------------------------------------
class _Graph:
    """Reader/writer index over one program snapshot."""

    def __init__(self, program: ir.Program, block_idx: int,
                 ctx: RewriteContext):
        self.program = program
        self.block_idx = block_idx
        self.root = program.blocks[block_idx]
        self.fetches = set(ctx.fetch_names)
        self.attr_names = _attr_referenced_names(program, block_idx)
        self.writers: Dict[str, List[ir.OpDesc]] = {}
        self.readers: Dict[str, List[ir.OpDesc]] = {}
        self.nonroot_readers: Dict[str, List[ir.OpDesc]] = {}
        for blk, _path, _i, op in iter_ops(program, block_idx):
            for n in op.output_names():
                self.writers.setdefault(n, []).append(op)
            tgt = self.readers if blk is self.root \
                else self.nonroot_readers
            for n in set(op.input_names()):
                tgt.setdefault(n, []).append(op)

    def sole_root_producer(self, name: str) -> Optional[ir.OpDesc]:
        ws = self.writers.get(name, [])
        if len(ws) != 1:
            return None
        op = ws[0]
        return op if op in self.root.ops else None

    def internal_ok(self, name: str, allowed: Set[int]) -> bool:
        """True when ``name`` is a pure intermediate: declared
        non-persistable, single writer, not fetched or attr-referenced,
        and every reader is in ``allowed`` (a set of id(op))."""
        v = self.root.find_var_recursive(name)
        if v is None or v.persistable or name in self.fetches \
                or name in self.attr_names:
            return False
        if len(self.writers.get(name, [])) != 1:
            return False
        if self.nonroot_readers.get(name):
            return False
        return all(id(r) in allowed for r in self.readers.get(name, []))


def _vjp_of(graph: _Graph, fwd_op: ir.OpDesc) -> Optional[ir.OpDesc]:
    """The __vjp__ op embedding ``fwd_op`` (matched on type + exact
    input/output wiring — attr drift, e.g. builder markers stamped after
    backward ran, is tolerated)."""
    found = None
    for op in graph.root.ops:
        if op.type != "__vjp__":
            continue
        fwd = op.attrs.get("fwd_op") or {}
        if fwd.get("type") == fwd_op.type \
                and fwd.get("inputs") == fwd_op.inputs \
                and fwd.get("outputs") == fwd_op.outputs:
            if found is not None:
                return None  # ambiguous: refuse
            found = op
    return found


def _vjp_grad_map(bop: ir.OpDesc) -> List[Tuple[str, str]]:
    """[(fwd input name, produced grad name)] for one __vjp__ op."""
    fwd = ir.OpDesc.from_dict(bop.attrs["fwd_op"])
    entries = fwd.input_names() + list(
        bop.attrs.get("closure_names") or [])
    need = bop.attrs.get("in_need_grad") or []
    grads = bop.outputs.get("InGrad", [])
    out: List[Tuple[str, str]] = []
    gi = 0
    for name, n in zip(entries, need):
        if n:
            if gi < len(grads):
                out.append((name, grads[gi]))
            gi += 1
    return out


_OUTLINE_UID = [0]


def _outline_subgraph(graph: _Graph, chain: List[ir.OpDesc],
                      mega: ir.OpDesc, out_name: str,
                      interface_in: List[str]) -> bool:
    """Replace ``chain`` (forward ops, dataflow order, last op produces
    ``out_name``) with ``mega``, merging the chain's ``__vjp__`` grad
    ops — when present — into one ``__vjp__`` of ``mega``. Returns False
    (program untouched) when any safety condition fails.

    ``interface_in`` is the mega op's flattened input-name order (the
    order ``mega.input_names()`` yields); duplicates allowed.
    """
    root = graph.root
    program = graph.program
    chain_ids = {id(o) for o in chain}

    # backward set: one vjp per chain op that has one (an ambiguous
    # match resolves to None; the orphaned vjps then trip the
    # intermediate-visibility checks below, refusing the site)
    vjps: Dict[int, ir.OpDesc] = {}
    for op in chain:
        b = _vjp_of(graph, op)
        if b is not None:
            vjps[id(op)] = b
    b_ops = list(vjps.values())
    b_ids = {id(b) for b in b_ops}
    allowed = chain_ids | b_ids

    # chain intermediates must be invisible outside the outlined region
    produced_names = {n for o in chain for n in o.output_names()}
    for name in produced_names:
        if name == out_name:
            continue
        if not graph.internal_ok(name, allowed):
            return False
    # the chain output keeps its name; the mega op writes it
    if len(graph.writers.get(out_name, [])) != 1:
        return False

    merged_vjp = None
    first_b_op = None
    if b_ops:
        last_op = chain[-1]
        tail_vjp = vjps.get(id(last_op))
        if tail_vjp is None:
            return False
        # grads of intermediates must stay inside B; grads of interface
        # inputs are the merged op's outputs
        iface_set = set(interface_in)
        produced_grads: Dict[str, List[str]] = {}
        for b in b_ops:
            for fwd_in, gname in _vjp_grad_map(b):
                if fwd_in in iface_set:
                    produced_grads.setdefault(fwd_in, []).append(gname)
                else:
                    if not graph.internal_ok(gname, allowed):
                        return False
            # every OutGrad must be produced inside B, except the tail's
            for g in b.inputs.get("OutGrad", []):
                ws = graph.writers.get(g, [])
                internal = ws and all(id(w) in b_ids for w in ws)
                if b is tail_vjp:
                    if internal:
                        return False
                elif not internal:
                    return False
        out_grads = tail_vjp.inputs.get("OutGrad", [])
        if len(out_grads) != 1:
            return False
        # mask-style inputs whose grad the original program consumed
        # outside the region are only safe when the merged op also
        # produces them — handled below; inputs with NO produced grad
        # simply get in_need_grad=False.
        grad_out_names: List[str] = []
        in_need: List[bool] = []
        #: (accumulator sum op, contribution names to drop, fresh
        #: merged grad name, source fwd var to copy shape/dtype from)
        sum_edits: List[Tuple[ir.OpDesc, List[str], str, str]] = []
        # a duplicated interface name only carries gradient at its LAST
        # position: the __vjp__ replay binds env[name] sequentially, so
        # earlier positional args of the same name see zero cotangents
        # (backward.py's accumulator sums them away; here we just skip
        # the dead positions)
        last_pos = {n: i for i, n in enumerate(interface_in)}
        for pos, name in enumerate(interface_in):
            if last_pos[name] != pos:
                in_need.append(False)
                continue
            gnames = produced_grads.get(name, [])
            if not gnames:
                in_need.append(False)
                continue
            in_need.append(True)
            if len(gnames) == 1:
                grad_out_names.append(gnames[0])
                continue
            # several internal contributions: they must all feed one
            # accumulator `sum` op — replace them there with one merged
            # contribution
            consumers = [r for g in gnames
                         for r in graph.readers.get(g, [])
                         if id(r) not in b_ids]
            consumer_ids = {id(c) for c in consumers}
            if len(consumer_ids) != 1:
                return False
            acc = consumers[0]
            if acc.type != "sum" or id(acc) in allowed:
                return False
            for g in gnames:
                if graph.nonroot_readers.get(g):
                    return False
            _OUTLINE_UID[0] += 1
            fresh = f"{name}@GRAD@OUTLINED@{_OUTLINE_UID[0]}"
            sum_edits.append((acc, gnames, fresh, name))
            grad_out_names.append(fresh)
        merged_vjp = ir.OpDesc(
            "__vjp__",
            inputs={"FwdIn": list(interface_in),
                    "OutGrad": list(out_grads)},
            outputs={"InGrad": grad_out_names},
            attrs={"fwd_op": mega.to_dict(),
                   "out_has_grad": [True],
                   "in_need_grad": list(in_need),
                   "closure_names": []})
        # mutations start only here, after every validation passed
        for acc, gnames, fresh, src in sum_edits:
            fv = root.find_var_recursive(src)
            root.create_var(fresh,
                            shape=(fv.shape if fv is not None else None),
                            dtype=(fv.dtype if fv is not None
                                   else "float32"))
            xs = [n for n in acc.inputs.get("X", []) if n not in gnames]
            acc.inputs["X"] = [fresh] + xs
        first_b_op = min(b_ops, key=lambda b: root.ops.index(b))

    # single rebuild: replace the tail forward op with the mega op, the
    # earliest backward op with the merged vjp, drop the rest
    replace: Dict[int, ir.OpDesc] = {id(chain[-1]): mega}
    drop: Set[int] = {id(o) for o in chain[:-1]}
    if merged_vjp is not None:
        replace[id(first_b_op)] = merged_vjp
        drop |= {id(b) for b in b_ops if b is not first_b_op}
    root.ops = [replace.get(id(o), o) for o in root.ops
                if id(o) not in drop]
    program._bump_version()
    return True


# ---------------------------------------------------------------------------
# attention outlining
# ---------------------------------------------------------------------------
@register_rewrite_pass
class AttentionOutlining(RewritePass):
    """Outline the composed scaled-dot-product attention chain

        matmul(Q, K, transpose_Y) -> [scale] -> [elementwise_add mask]
            -> softmax(axis=-1) -> matmul(probs, V)

    into one ``scaled_dot_product_attention`` op carrying the chain's
    exact softmax scale as an attr, so the Pallas flash kernel (and its
    flash backward, via the merged ``__vjp__``) applies to any user
    program — not only graphs built through the fused layer. Sites
    where the additive mask itself needs a gradient are skipped
    (flash treats the bias as constant by default; documented in
    KNOWN_GAPS "Rewrite boundaries")."""

    name = "fuse_attention"

    def apply(self, program, ctx) -> List[Dict]:
        actions: List[Dict] = []
        failed: Set[int] = set()  # anchor ids of refused sites
        graph: Optional[_Graph] = None
        while True:
            if graph is None:  # (re)index only after a mutation
                graph = _Graph(program, ctx.block_idx, ctx)
            m = self._find(graph, failed)
            if m is None:
                return actions
            chain, q, k, v, mask, scale, out_name = m
            inputs = {"Q": [q], "K": [k], "V": [v]}
            interface = [q, k, v]
            if mask is not None:
                inputs["Mask"] = [mask]
                interface.append(mask)
            mega = ir.OpDesc(
                "scaled_dot_product_attention", inputs,
                {"Out": [out_name]},
                {"causal": False, "scale": float(scale),
                 "__outlined__": "attention"})
            if not _outline_subgraph(graph, chain, mega, out_name,
                                     interface):
                # this site is unsafe (shared intermediates, odd grad
                # topology, ...) — skip it and keep scanning; later
                # sites in the same program must still outline. A
                # refusal leaves the program untouched: keep the index.
                failed.add(id(chain[-2]))  # the softmax anchor
                continue
            graph = None  # program mutated
            actions.append({"action": "outline",
                            "op_type": "scaled_dot_product_attention",
                            "ops_fused": len(chain)})

    # -- matching -----------------------------------------------------
    @staticmethod
    def _shapes_compatible(root, q, k, v) -> bool:
        sq = (root.find_var_recursive(q) or ir.VarDesc(q)).shape
        sk = (root.find_var_recursive(k) or ir.VarDesc(k)).shape
        sv = (root.find_var_recursive(v) or ir.VarDesc(v)).shape
        if not sq or not sk or not sv:
            return False
        if not (len(sq) == len(sk) == len(sv)) or len(sq) < 3:
            return False
        # equal leading (batch/head) dims; dynamic (-1) matches dynamic
        if sq[:-2] != sk[:-2] or sk[:-2] != sv[:-2]:
            return False
        # K and V share the key sequence length when both are static
        if isinstance(sk[-2], int) and isinstance(sv[-2], int) \
                and sk[-2] > 0 and sv[-2] > 0 and sk[-2] != sv[-2]:
            return False
        # head dim must be static (it anchors the softmax scale)
        return isinstance(sq[-1], int) and sq[-1] > 0 \
            and sq[-1] == sk[-1]

    def _find(self, graph: _Graph, skip: Set[int] = frozenset()):
        root = graph.root
        for sm in root.ops:
            if sm.type != "softmax" \
                    or sm.attrs.get("axis", -1) != -1 \
                    or sm.attrs.get("__outlined__") \
                    or id(sm) in skip:
                continue
            probs = sm.output("Out")
            sm_in = sm.input("X")
            if not probs or not sm_in:
                continue
            probs, sm_in = probs[0], sm_in[0]
            # downstream: the only non-vjp consumer is matmul(probs, V)
            d = None
            for r in graph.readers.get(probs, []):
                if r.type == "matmul" and r.input("X") == [probs]:
                    d = r
            if d is None or d.attrs.get("transpose_X") \
                    or d.attrs.get("transpose_Y") \
                    or d.attrs.get("alpha", 1.0) != 1.0:
                continue
            # upstream: [mask add] <- [scale] <- matmul(Q, K^T)
            chain_tail: List[ir.OpDesc] = []
            cur = sm_in
            mask = None
            prod = graph.sole_root_producer(cur)
            if prod is not None and prod.type == "elementwise_add":
                x_in, y_in = prod.input("X"), prod.input("Y")
                if not x_in or not y_in:
                    continue
                ax = prod.attrs.get("axis", -1)
                if ax != -1:
                    continue
                mask = y_in[0]
                chain_tail.append(prod)
                cur = x_in[0]
                prod = graph.sole_root_producer(cur)
            scale = 1.0
            if prod is not None and prod.type == "scale":
                if prod.attrs.get("bias", 0.0) != 0.0:
                    continue
                scale = float(prod.attrs.get("scale", 1.0))
                chain_tail.append(prod)
                cur = prod.input("X")[0]
                prod = graph.sole_root_producer(cur)
            a = prod
            if a is None or a.type != "matmul" \
                    or not a.attrs.get("transpose_Y") \
                    or a.attrs.get("transpose_X"):
                continue
            scale *= float(a.attrs.get("alpha", 1.0))
            q_in, k_in = a.input("X"), a.input("Y")
            v_in = d.input("Y")
            if not q_in or not k_in or not v_in:
                continue
            q, k, v = q_in[0], k_in[0], v_in[0]
            if not self._shapes_compatible(root, q, k, v):
                continue
            chain = [a] + list(reversed(chain_tail)) + [sm, d]
            out_name = d.output("Out")[0]
            # the additive mask must not need a gradient: the flash
            # kernel treats it as a constant bias
            if mask is not None:
                madd = next((o for o in chain
                             if o.type == "elementwise_add"), None)
                bop = _vjp_of(graph, madd) if madd is not None else None
                if bop is not None:
                    gm = dict(_vjp_grad_map(bop))
                    if mask in gm:
                        continue
            return chain, q, k, v, mask, scale, out_name
        return None


# ---------------------------------------------------------------------------
# SE-block outlining
# ---------------------------------------------------------------------------
@register_rewrite_pass
class SEBlockOutlining(RewritePass):
    """Outline the squeeze-excitation gate

        pool2d(avg, global) -> mul(W1) -> +B1 -> relu -> mul(W2) -> +B2
            -> sigmoid -> reshape([-1, C, 1, 1]) -> elementwise_mul(X, .)

    into one ``se_block`` mega-op (ops/fusion_ops.py) so the whole gate
    is a single op for the cost model, the fusion layer, and —
    eventually — a hand kernel (ROADMAP item 2's SE fusion)."""

    name = "fuse_se"

    def apply(self, program, ctx) -> List[Dict]:
        actions: List[Dict] = []
        failed: Set[int] = set()
        graph: Optional[_Graph] = None
        while True:
            if graph is None:
                graph = _Graph(program, ctx.block_idx, ctx)
            m = self._find(graph, failed)
            if m is None:
                return actions
            chain, x, w1, b1, w2, b2, out_name = m
            mega = ir.OpDesc(
                "se_block",
                {"X": [x], "W1": [w1], "B1": [b1], "W2": [w2],
                 "B2": [b2]},
                {"Out": [out_name]}, {"__outlined__": "se_block"})
            if not _outline_subgraph(graph, chain, mega, out_name,
                                     [x, w1, b1, w2, b2]):
                failed.add(id(chain[0]))  # the pool2d anchor
                continue
            graph = None  # program mutated
            actions.append({"action": "outline", "op_type": "se_block",
                            "ops_fused": len(chain)})

    def _find(self, graph: _Graph, skip: Set[int] = frozenset()):
        root = graph.root

        def sole_consumer(name, types):
            rs = [r for r in graph.readers.get(name, [])
                  if r.type != "__vjp__"]
            if len(rs) == 1 and rs[0].type in types:
                return rs[0]
            return None

        for pool in root.ops:
            if pool.type != "pool2d" \
                    or not pool.attrs.get("global_pooling") \
                    or pool.attrs.get("pooling_type") != "avg" \
                    or id(pool) in skip:
                continue
            x_in = pool.input("X")
            p_out = pool.output("Out")
            if not x_in or not p_out:
                continue
            x, cur = x_in[0], p_out[0]
            mul1 = sole_consumer(cur, {"mul"})
            if mul1 is None or mul1.input("X") != [cur] \
                    or mul1.attrs.get("x_num_col_dims", 1) != 1:
                continue
            add1 = sole_consumer(mul1.output("Out")[0],
                                 {"elementwise_add"})
            if add1 is None:
                continue
            relu = sole_consumer(add1.output("Out")[0], {"relu"})
            if relu is None:
                continue
            mul2 = sole_consumer(relu.output("Out")[0], {"mul"})
            if mul2 is None or mul2.attrs.get("x_num_col_dims", 1) != 1:
                continue
            add2 = sole_consumer(mul2.output("Out")[0],
                                 {"elementwise_add"})
            if add2 is None:
                continue
            sig = sole_consumer(add2.output("Out")[0], {"sigmoid"})
            if sig is None:
                continue
            rshp = sole_consumer(sig.output("Out")[0], {"reshape"})
            if rshp is None:
                continue
            emul = sole_consumer(rshp.output("Out")[0],
                                 {"elementwise_mul"})
            if emul is None or emul.input("X") != [x] \
                    or emul.input("Y") != rshp.output("Out"):
                continue
            # gates must come back as [-1, C, 1, 1]
            shp = rshp.attrs.get("shape")
            xv = root.find_var_recursive(x)
            if not shp or len(shp) != 4 or shp[2:] != [1, 1] \
                    or xv is None or not xv.shape or len(xv.shape) != 4:
                continue
            chain = [pool, mul1, add1, relu, mul2, add2, sig, rshp,
                     emul]
            return (chain, x, mul1.input("Y")[0], add1.input("Y")[0],
                    mul2.input("Y")[0], add2.input("Y")[0],
                    emul.output("Out")[0])
        return None


# ---------------------------------------------------------------------------
# kernel dispatch annotation
# ---------------------------------------------------------------------------
@register_rewrite_pass
class KernelDispatch(RewritePass):
    """Stamp the Pallas-kernel dispatch decision onto eligible ops as a
    program attr, replacing trace-time env sniffing:

    - ``lstm``/``gru`` ops get ``__pallas__`` from the existing
      PADDLE_TPU_PALLAS_LSTM / PADDLE_TPU_PALLAS_GRU policy (the
      compute rules prefer the attr over the env);
    - ``scaled_dot_product_attention`` ops get ``use_flash`` under
      PADDLE_TPU_PALLAS_SDPA: "force" engages the flash kernel anywhere
      (interpret mode off-TPU — the no-TPU test path), "0" pins the
      naive composition; the default "1" leaves the op's measured
      min-seq auto policy in charge.

    Annotation only — no op is added or removed, so this pass is safe
    inside sub-blocks too."""

    name = "kernel_dispatch"

    _STD_LSTM = {"gate_activation": "sigmoid", "cell_activation": "tanh",
                 "candidate_activation": "tanh"}

    def _annotate(self, op_type: str, attrs: Dict,
                  knobs: Dict[str, str]) -> Optional[Tuple[str, str]]:
        """Mutate ``attrs`` with the dispatch decision for ``op_type``;
        returns (attr set, kernel name) or None when nothing changed."""
        if op_type == "lstm":
            knob = knobs["lstm"]
            if knob in ("1", "force") \
                    and not attrs.get("use_peepholes") \
                    and all(attrs.get(k, d) == d
                            for k, d in self._STD_LSTM.items()) \
                    and attrs.get("__pallas__") != knob:
                attrs["__pallas__"] = knob
                return "__pallas__", "fused_lstm"
        elif op_type == "gru":
            knob = knobs["gru"]
            if knob in ("1", "force") \
                    and attrs.get("gate_activation",
                                  "sigmoid") == "sigmoid" \
                    and attrs.get("activation", "tanh") == "tanh" \
                    and attrs.get("__pallas__") != knob:
                attrs["__pallas__"] = knob
                return "__pallas__", "fused_gru"
        elif op_type == "scaled_dot_product_attention":
            knob = knobs["sdpa"]
            if knob in ("force", "0") and not attrs.get("seq_axis"):
                want = knob == "force"
                if attrs.get("use_flash") != want:
                    attrs["use_flash"] = want
                    return "use_flash", "flash_attention"
        return None

    def apply(self, program, ctx) -> List[Dict]:
        actions: List[Dict] = []
        knobs = {
            "lstm": os.environ.get("PADDLE_TPU_PALLAS_LSTM", "1"),
            "gru": os.environ.get("PADDLE_TPU_PALLAS_GRU", "1"),
            "sdpa": os.environ.get("PADDLE_TPU_PALLAS_SDPA", "1"),
        }
        for _blk, _path, _i, op in iter_ops(program, ctx.block_idx):
            hit = self._annotate(op.type, op.attrs, knobs)
            if hit is not None:
                actions.append({"action": "dispatch",
                                "op_type": op.type, "kernel": hit[1]})
            if op.type == "__vjp__":
                # the generic grad op REPLAYS its embedded forward op:
                # annotate the embedded copy too, so the kernel's
                # backward engages (flash bwd, fused scan bwd) — not
                # only the forward instance
                fwd = op.attrs.get("fwd_op") or {}
                fattrs = fwd.get("attrs")
                if isinstance(fattrs, dict):
                    hit = self._annotate(fwd.get("type"), fattrs, knobs)
                    if hit is not None:
                        actions.append({"action": "dispatch",
                                        "op_type":
                                            f"{fwd.get('type')}@vjp",
                                        "kernel": hit[1]})
        if actions:
            program._bump_version()
        return actions


# ---------------------------------------------------------------------------
# in-place buffer reuse
# ---------------------------------------------------------------------------
@register_rewrite_pass
class InplaceBufferReuse(RewritePass):
    """Liveness-driven buffer reuse: rename an op's output var onto a
    same-signature buffer whose live interval already ended, so the
    executor's name-keyed env (and XLA's arena under it) holds ONE
    buffer where the unoptimized program declared two. The classic
    win: backward grads folding into the dead forward activations of
    the same shape (analysis/memory.py's ``peak_bytes`` is exactly the
    number this shrinks).

    Root-block scoped and value-preserving — a pure renaming, so the
    loss-identity gate stays bit-exact. A name participates (as donor
    or target) only when it is root-declared, non-persistable, not a
    parameter, un-initialized, dense (lod 0), single-writer, not fed /
    fetched / attr-referenced / donated rw state, and never referenced
    from a sub-block; targets additionally must not be written by
    plumbing (feed/fetch/print), stateful, or sub-block-carrying ops.
    Signature = exact dtype + exact dims with ``-1`` kept symbolic, so
    two dynamic-batch buffers match only when their runtime sizes are
    equal for every batch. A donor frees AFTER the op holding its last
    reference (never within it), which rules out aliasing an op's
    input to its own output.

    Runs LAST in the pipeline: the outliners match ``__vjp__`` grad
    ops against the forward op's exact input/output names, which
    renaming would break."""

    name = "inplace_reuse"

    def apply(self, program, ctx) -> List[Dict]:
        if os.environ.get("PADDLE_TPU_INPLACE_REUSE", "1") == "0":
            return []
        root = program.blocks[ctx.block_idx]
        writers = _writer_counts(program, ctx.block_idx)
        attr_names = _attr_referenced_names(program, ctx.block_idx)
        fetches = set(ctx.fetch_names)
        feeds = set(ctx.feed_names)
        donated = set(rw_state_names(program, ctx.block_idx))
        # names a sub-block touches read the enclosing scope closure
        # style — renaming them needs a cross-block sweep; stay
        # root-scoped (KNOWN_GAPS: memory-planning boundaries)
        nonroot: Set[str] = set()
        for blk, _path, _i, op in iter_ops(program, ctx.block_idx):
            if blk.idx != root.idx:
                nonroot.update(op.input_names())
                nonroot.update(op.output_names())

        def sig(name: str) -> Optional[Tuple]:
            v = root.vars.get(name)
            if v is None or v.shape is None or v.dtype is None:
                return None
            dims = []
            for d in v.shape:
                if not isinstance(d, int):
                    return None  # symbolic placeholder: size unknowable
                dims.append(int(d))
            return (v.dtype, tuple(dims))

        def static_bytes(s: Tuple) -> int:
            n = 1
            for d in s[1]:
                n *= 1 if d == -1 else d
            return n * _ITEMSIZE.get(s[0], 4)

        def eligible(name: str) -> bool:
            v = root.vars.get(name)
            if v is None or v.persistable or v.is_parameter:
                return False
            if v.initializer is not None or v.lod_level:
                return False
            if v.type != ir.VAR_TYPE_LOD_TENSOR:
                return False
            if name in fetches or name in feeds or name in attr_names \
                    or name in nonroot or name in donated:
                return False
            if writers.get(name, 0) != 1:
                return False
            return sig(name) is not None

        last_ref: Dict[str, int] = {}
        for i, op in enumerate(root.ops):
            for n in op.input_names():
                last_ref[n] = i
            for n in op.output_names():
                last_ref[n] = i
        deaths: Dict[int, List[str]] = {}
        for n, i in last_ref.items():
            if eligible(n):
                deaths.setdefault(i, []).append(n)

        assignments: Dict[str, str] = {}  # renamed name -> buffer name
        free: Dict[Tuple, List[str]] = {}
        actions: List[Dict] = []
        for i, op in enumerate(root.ops):
            if op.type not in _KEEP_OPS and not _has_sub_block(op) \
                    and not _is_stateful(op):
                ins = set(op.input_names())
                for n in op.output_names():
                    if n in assignments or n in ins or not eligible(n):
                        continue
                    s = sig(n)
                    pool = free.get(s)
                    if not pool:
                        continue
                    donor = pool.pop()
                    assignments[n] = donor
                    actions.append({"action": "reuse",
                                    "op_type": op.type, "op_index": i,
                                    "var": n, "into": donor,
                                    "bytes": static_bytes(s)})
            # a buffer whose last reference sits at op i is reusable
            # from op i+1 on; a renamed var's death returns the
            # UNDERLYING buffer to the pool (chained reuse)
            for n in sorted(deaths.get(i, ())):
                free.setdefault(sig(n), []).append(
                    assignments.get(n, n))

        if not assignments:
            return []
        for op in root.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [assignments.get(n, n)
                                   for n in names]
            for slot, names in op.outputs.items():
                op.outputs[slot] = [assignments.get(n, n)
                                    for n in names]
            # legacy memory-optimize annotations pin liveness decisions
            # made before the renaming — scrub touched names
            dead = op.attrs.get("__dead_vars__")
            if dead:
                keep = set(assignments) | set(assignments.values())
                op.attrs["__dead_vars__"] = [n for n in dead
                                             if n not in keep]
        for n in assignments:
            root.vars.pop(n, None)
        program._bump_version()
        return actions


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------
def default_rewrite_passes() -> List[RewritePass]:
    """THE rewrite pipeline, in order: fold and dedup first (cheaper
    graphs for the matchers), prune dead gradients (which unblocks
    outlining on masked attention), outline fusable subgraphs, sweep
    dead ops (including producers orphaned by folding/outlining), stamp
    kernel dispatch, then alias dead buffers (last: the outliners match
    grad ops by exact forward names, which renaming would break)."""
    return [ConstantFolding(), CommonSubexpressionElimination(),
            DeadOpElimination(), DeadGradPruning(),
            AttentionOutlining(), SEBlockOutlining(),
            DeadOpElimination(), KernelDispatch(),
            InplaceBufferReuse()]


class RewriteResult:
    """Outcome of one rewrite pipeline run."""

    def __init__(self, program: ir.Program, changed: bool,
                 actions: List[Dict], aborted: List[str],
                 seconds: float):
        #: the rewritten program (the ORIGINAL desc when changed=False)
        self.program = program
        self.changed = changed
        #: every applied action, each carrying its pass name
        self.actions = actions
        #: passes whose post-verify failed (their changes discarded)
        self.aborted = aborted
        self.seconds = seconds

    def count(self, pass_name: Optional[str] = None,
              action: Optional[str] = None) -> int:
        return sum(1 for a in self.actions
                   if (pass_name is None or a["pass"] == pass_name)
                   and (action is None or a["action"] == action))

    def summary(self) -> Dict:
        per_pass: Dict[str, Dict[str, int]] = {}
        for a in self.actions:
            bucket = per_pass.setdefault(a["pass"], {})
            bucket[a["action"]] = bucket.get(a["action"], 0) + 1
        return {"changed": self.changed, "seconds": self.seconds,
                "aborted": self.aborted, "passes": per_pass,
                "total_actions": len(self.actions)}


# observability: rewrite wall time + per-pass action counts, resolved
# against the CURRENT default registry (identity-checked, the shared
# pattern with the verifier / executor instruments)
_obs_cache = None


def _publish(seconds: float, actions: List[Dict],
             aborted: List[str]) -> None:
    global _obs_cache
    try:
        from ..observability.registry import default_registry
        reg = default_registry()
        if _obs_cache is None or _obs_cache[0] is not reg:
            _obs_cache = (
                reg,
                reg.histogram(
                    "paddle_tpu_rewrite_seconds",
                    "Wall time of one program-rewrite pipeline run "
                    "(executor compile-cache miss or lint_ir "
                    "--optimize)."),
                reg.counter(
                    "paddle_tpu_rewrite_ops_total",
                    "Program-rewrite actions applied, by pass and "
                    "action (remove_op/merge_op/fold_op/outline/"
                    "dispatch/reuse; 'aborted' counts a pass whose "
                    "post-rewrite verification failed and whose "
                    "changes were discarded).",
                    ("pass", "action")),
                reg.counter(
                    "paddle_tpu_memory_reuse_bytes_total",
                    "Static activation bytes the in-place buffer-reuse "
                    "rewrite folded into dead predecessor buffers "
                    "(per adopted pipeline run, by pass).",
                    ("pass",)),
            )
        _, hist, ops_total, reuse_total = _obs_cache
        hist.record(seconds)
        for a in actions:
            ops_total.labels(**{"pass": a["pass"],
                                "action": a["action"]}).inc()
            if a["action"] == "reuse" and a.get("bytes"):
                reuse_total.labels(**{"pass": a["pass"]}).inc(
                    int(a["bytes"]))
        for name in aborted:
            ops_total.labels(**{"pass": name, "action": "aborted"}).inc()
    except Exception:
        pass  # telemetry must never fail a rewrite


def rewrite_program(program, block_idx: int = 0,
                    feed_names: Optional[Sequence[str]] = None,
                    fetch_names: Optional[Sequence[str]] = None,
                    donate: bool = False, async_dispatch: bool = False,
                    passes: Optional[Sequence[RewritePass]] = None,
                    label: str = "program") -> RewriteResult:
    """Run the rewrite pipeline over a CLONE of ``program``.

    Each pass applies to a fresh clone of the last-known-good program
    and is adopted only when the shared ``fast_passes()`` verifier finds
    no error-severity diagnostics afterwards — a broken rewrite is
    discarded (and counted as ``aborted``), never compiled. The original
    program object is never mutated.
    """
    desc = _desc(program)
    ctx = RewriteContext(block_idx, feed_names, fetch_names)
    t0 = time.perf_counter()
    current: Optional[ir.Program] = None  # None = unchanged so far
    candidate: Optional[ir.Program] = None
    actions: List[Dict] = []
    aborted: List[str] = []
    for p in (default_rewrite_passes() if passes is None else passes):
        # an action-less pass contractually leaves its program
        # untouched, so the clone carries over to the next pass — one
        # clone per ADOPTED-or-discarded pass, not one per pass
        if candidate is None:
            candidate = (current if current is not None
                         else desc).clone()
        try:
            pass_actions = p.apply(candidate, ctx)
        except Exception:
            aborted.append(p.name)
            candidate = None  # possibly half-mutated: discard
            continue
        if not pass_actions:
            continue
        report = verify_program(
            candidate, feed_names=ctx.feed_names or None,
            fetch_names=ctx.fetch_names or None, block_idx=block_idx,
            donate=donate, async_dispatch=async_dispatch,
            passes=fast_passes(),
            program_label=f"{label} (post-{p.name})")
        if not report.ok:
            aborted.append(p.name)
            candidate = None
            continue
        current, candidate = candidate, None
        for a in pass_actions:
            a["pass"] = p.name
        actions.extend(pass_actions)
    seconds = time.perf_counter() - t0
    _publish(seconds, actions, aborted)
    return RewriteResult(current if current is not None else desc,
                         current is not None, actions, aborted, seconds)
