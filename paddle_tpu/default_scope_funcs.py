"""Default-scope helpers (reference:
python/paddle/fluid/default_scope_funcs.py — a thread-local scope
stack with enter/leave and a scoped_function decorator). Mapped onto
core.scope's Scope chain: entering pushes a child of the current
scope, leaving pops and discards it."""
from __future__ import annotations

import threading
from typing import Callable

from .core.scope import Scope, global_scope

__all__ = ["get_cur_scope", "enter_local_scope", "leave_local_scope",
           "var", "find_var", "scoped_function"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = [global_scope()]
    # a fresh global scope (tests reset it) restarts the chain
    if _tls.stack[0] is not global_scope():
        _tls.stack = [global_scope()]
    return _tls.stack


def get_cur_scope() -> Scope:
    """Innermost scope of the current thread."""
    return _stack()[-1]


def enter_local_scope() -> Scope:
    child = Scope(parent=get_cur_scope())
    _stack().append(child)
    return child


def leave_local_scope() -> None:
    stack = _stack()
    if len(stack) == 1:
        raise RuntimeError("cannot leave the global scope")
    stack.pop()


def var(name: str):
    """Create (or fetch) `name` in the current scope; returns its
    value slot name — set it with get_cur_scope().set(name, value)."""
    scope = get_cur_scope()
    if not scope.has(name):
        scope.set(name, None)
    return name


def find_var(name: str):
    return get_cur_scope().find(name)


def scoped_function(func: Callable):
    """Run `func` inside a fresh local scope, always leaving it."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
