"""Checkpoint / model IO (reference: python/paddle/fluid/io.py —
save_vars:66, save_params:132, save_persistables:145, load_persistables:234,
save_inference_model:298, load_inference_model:383; save_op.cc/load_op.cc).

TPU-native design: persistable variables live in the Scope as device
arrays; save/load serializes them with numpy .npz (single-file "combine"
form, like save_combine_op) plus the Program JSON for inference models.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .core.scope import global_scope
from .framework import Program, default_main_program

__all__ = ["save_vars", "save_params", "save_persistables", "load_vars",
           "load_params", "load_persistables", "save_inference_model",
           "load_inference_model", "inference_model_specs",
           "get_parameter_value", "set_parameter_value"]


def inference_model_specs(program: Program, feed_names, fetch_names):
    """Per-var feed/fetch metadata {name: {shape, dtype, lod_level}} for a
    frozen program. -1 dims are dynamic (leading -1 is the batch axis) —
    this is what serving's batcher buckets on. Derived from the program's
    VarDescs so it works for models saved before specs were written."""
    # accept the python builder wrapper (global_block is a METHOD there)
    # or the core ir.Program (global_block is a property)
    block = program.global_block() if hasattr(program, "desc") \
        else program.blocks[0]

    def spec(name):
        v = block.var(name)
        v = v.desc if hasattr(v, "desc") else v
        return {"shape": list(v.shape) if v.shape is not None else None,
                "dtype": v.dtype, "lod_level": v.lod_level}

    return ({n: spec(n) for n in feed_names},
            {n: spec(n) for n in fetch_names})


def _vars_of(program: Program, predicate) -> List:
    return [v for v in program.list_vars() if predicate(v.desc)]


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = _vars_of(program, predicate or (lambda v: v.persistable))
    os.makedirs(dirname, exist_ok=True)
    scope = global_scope()
    arrays = {}
    for v in vars:
        val = scope.find(v.name)
        if val is None:
            continue
        arrays[v.name] = np.asarray(val)
    path = os.path.join(dirname, filename or "__params__.npz")
    np.savez(path, **arrays)
    return path


def save_params(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.is_parameter, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return save_vars(executor, dirname, main_program,
                     predicate=lambda v: v.persistable, filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    program = main_program or default_main_program()
    if vars is None:
        vars = _vars_of(program, predicate or (lambda v: v.persistable))
    import jax.numpy as jnp
    path = os.path.join(dirname, filename or "__params__.npz")
    data = np.load(path)
    scope = global_scope()
    for v in vars:
        if v.name in data:
            scope.set(v.name, jnp.asarray(data[v.name]))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.is_parameter, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable, filename=filename)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, model_version=None,
                         generation_spec=None):
    """Freeze program + params for inference (reference: io.py:298 +
    framework/prune.cc pruning). `model_version` is an optional deploy
    identity stamped into the artifact metadata — the serving lifecycle
    (ModelHost hot-swap, the model_version gauge) reports it; absent on
    artifacts saved before versioning existed. `generation_spec` is an
    optional JSON-able dict of token-serving parameters (max_seq_len,
    KV-cache layout, eos id, bucket sets — GenerationSpec.to_dict());
    with it the artifact is self-describing for
    serving.generation.GenerationModel.load."""
    program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    fetch_names = [t.name for t in target_vars]
    pruned = _prune(program, feeded_var_names, fetch_names)
    # inference mode: BN uses running stats, dropout is identity
    # (reference: io.py:259/344 inference_optimize on the pruned program)
    pruned = pruned.inference_optimize()
    # Verify the frozen artifact BEFORE it reaches disk: a broken
    # export (fetch pruned away, dangling input after a bad transpile)
    # should fail the save, not the eventual serving load.
    # PADDLE_TPU_VERIFY=0 opts out.
    from .analysis import verify_enabled, verify_program
    if verify_enabled():
        verify_program(
            pruned, feed_names=list(feeded_var_names),
            fetch_names=fetch_names,
            program_label="frozen inference program",
        ).raise_if_errors(context="save_inference_model")
    # The program itself ships as compact PTIR binary written by the native
    # IR library (native/ir.cc), like the reference's protobuf __model__
    # (reference: io.py:298 writes program.desc.serialize_to_string()).
    meta = dict(pruned.desc.to_dict())  # top-level "blocks" + extras
    meta["feed_names"] = list(feeded_var_names)
    meta["fetch_names"] = fetch_names
    # Feed/fetch shape+dtype metadata, so a serving frontend can bucket
    # batches without reconstructing the program first. Best-effort on
    # disk (the native PTIR writer may drop unknown top-level keys);
    # load_inference_model re-derives it from VarDescs when absent.
    feed_specs, fetch_specs = inference_model_specs(
        pruned, feeded_var_names, fetch_names)
    meta["feed_specs"] = feed_specs
    meta["fetch_specs"] = fetch_specs
    version_path = os.path.join(dirname, "__version__")
    if model_version is not None:
        meta["model_version"] = str(model_version)
        # unlike feed_specs, the deploy identity cannot be re-derived
        # from the program if the native PTIR writer drops the unknown
        # top-level key — a plain-text sidecar guarantees the
        # round-trip on any writer
        with open(version_path, "w") as f:
            f.write(str(model_version))
    elif os.path.exists(version_path):
        # re-freezing WITHOUT a version into a dir that had one: a
        # stale sidecar would stamp the previous artifact's identity
        # onto the new weights
        os.remove(version_path)
    gen_path = os.path.join(dirname, "__generation__.json")
    if generation_spec is not None:
        meta["generation_spec"] = dict(generation_spec)
        # like model_version: not re-derivable from the frozen program
        # (the saved program is the cache-less re-forward baseline), so
        # a JSON sidecar guarantees the round-trip even when the native
        # PTIR writer drops unknown top-level meta keys
        with open(gen_path, "w") as f:
            json.dump(dict(generation_spec), f)
    elif os.path.exists(gen_path):
        os.remove(gen_path)  # same staleness hazard as __version__
    try:
        from .native import ProgramIR
        ProgramIR.from_json(json.dumps(meta)).save(
            os.path.join(dirname, model_filename or "__model__"))
    except Exception:
        # no native toolchain on this host: text-JSON fallback
        with open(os.path.join(dirname,
                               model_filename or "__model__.json"), "w") as f:
            json.dump(meta, f)
    save_persistables(executor, dirname, program,
                      filename=params_filename or "__params__.npz")
    return dirname


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, return_meta=False):
    """Load a frozen model. Returns (program, feed_names, fetch_vars);
    with return_meta=True a 4th element carries bucketing metadata
    {"feed_specs": {...}, "fetch_specs": {...}} (shape/dtype/lod_level
    per var — see `inference_model_specs`)."""
    bin_path = os.path.join(dirname, model_filename or "__model__")
    json_path = os.path.join(dirname, model_filename or "__model__.json")
    meta = None
    if os.path.exists(bin_path):
        with open(bin_path, "rb") as f:
            is_ptir = f.read(4) == b"PTIR"
        if is_ptir:
            from .native import ProgramIR
            meta = json.loads(ProgramIR.load(bin_path).to_json())
        else:  # custom model_filename written by the JSON fallback
            json_path = bin_path
    if meta is None:  # models saved by the JSON fallback (or older versions)
        with open(json_path) as f:
            meta = json.load(f)
        meta = meta.get("program", meta) | {
            k: meta[k] for k in ("feed_names", "fetch_names",
                                 "feed_specs", "fetch_specs",
                                 "model_version", "generation_spec")
            if k in meta}
    from .core import ir
    prog = Program()
    prog.desc = ir.Program.from_dict(meta)
    from .framework import Block
    prog._blocks = [Block(prog, bd) for bd in prog.desc.blocks]
    load_vars(executor, dirname, prog,
              predicate=lambda v: v.persistable,
              filename=params_filename or "__params__.npz")
    fetch_vars = [prog.global_block().var(n) for n in meta["fetch_names"]]
    if not return_meta:
        return prog, meta["feed_names"], fetch_vars
    if "feed_specs" in meta and "fetch_specs" in meta:
        feed_specs, fetch_specs = meta["feed_specs"], meta["fetch_specs"]
    else:  # saved before specs were written, or dropped by the PTIR writer
        feed_specs, fetch_specs = inference_model_specs(
            prog, meta["feed_names"], meta["fetch_names"])
    model_version = meta.get("model_version")
    if model_version is None:
        vpath = os.path.join(dirname, "__version__")
        if os.path.exists(vpath):  # PTIR writer dropped the meta key
            with open(vpath) as f:
                model_version = f.read().strip() or None
    generation_spec = meta.get("generation_spec")
    if generation_spec is None:
        gpath = os.path.join(dirname, "__generation__.json")
        if os.path.exists(gpath):  # PTIR writer dropped the meta key
            with open(gpath) as f:
                generation_spec = json.load(f)
    return prog, meta["feed_names"], fetch_vars, {
        "feed_specs": feed_specs, "fetch_specs": fetch_specs,
        "model_version": model_version,
        "generation_spec": generation_spec}


def _prune(program: Program, feed_names, fetch_names) -> Program:
    """Keep only ops needed to compute fetch_names from feed_names — the
    backward slice runs in the native IR library (native/ir.cc
    prune_program; reference: framework/prune.cc, also C++ there).
    Persistable vars (parameters) are roots: their values come from the
    loaded checkpoint, so their producers (optimizer update ops, which
    *output* the param) must not pull the training graph back in."""
    from .core import ir
    from .framework import Block
    try:
        from .native import ProgramIR
        handle = ProgramIR.from_json(program.desc.to_json())
        pruned_desc = ir.Program.from_json(
            handle.prune(feed_names, fetch_names).to_json())
    except Exception:
        pruned_desc = _prune_py(program, fetch_names)
    pruned = Program()
    pruned.desc = pruned_desc
    pruned._blocks = [Block(pruned, bd) for bd in pruned.desc.blocks]
    return pruned


def _prune_py(program: Program, fetch_names):
    """Pure-Python fallback with identical semantics to native prune."""
    desc = program.desc.clone()
    block = desc.global_block

    def _persistable(name):
        v = block.find_var_recursive(name)
        return v is not None and v.persistable

    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if op.type in ("feed", "fetch"):
            continue
        if any(n in needed for n in op.output_names()):
            keep.append(op)
            for n in op.input_names():
                if not _persistable(n):
                    needed.add(n)
    block.ops = list(reversed(keep))
    desc._bump_version()
    return desc


def get_parameter_value(para, executor=None):
    return np.asarray(global_scope().get(para.name))


def set_parameter_value(para, value, executor=None):
    import jax.numpy as jnp
    global_scope().set(para.name, jnp.asarray(value))
