"""Event-loop Trainer (reference: python/paddle/v2/trainer.py:37 — the
SGD class whose train() pumps a reader through forward/backward and fires
BeginPass/EndPass/BeginIteration/EndIteration events, v2/event.py; the
same loop fluid scripts hand-write around exe.run).

TPU-native: one Executor (or ParallelExecutor over a mesh) runs the
jit-compiled step; the event loop, metrics plumbing, periodic elastic
checkpointing (distributed/checkpoint.py), and test() evaluation live
here on the host."""
from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass:
    def __init__(self, pass_id, metrics=None):
        self.pass_id = pass_id
        self.metrics = metrics or {}


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration:
    """End-of-iteration event. `cost`/`metrics` may be LAZY: when the
    Trainer dispatched the step asynchronously it hands the event a
    StepResult instead of materialized values, and reading `.cost` (or
    `.metrics`) forces the device fetch at that point. A handler that
    skips them on non-logged iterations keeps the pipeline unblocked; a
    handler that always reads them gets the synchronous behaviour,
    values bit-identical either way."""

    def __init__(self, pass_id, batch_id, cost=None, metrics=None,
                 result=None, metric_names=()):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self._cost = cost
        self._metrics = dict(metrics) if metrics is not None else None
        self._result = result
        self._metric_names = tuple(metric_names)

    @property
    def cost(self):
        if self._cost is None and self._result is not None:
            self._cost = _scalar_cost(self._result)
        return self._cost

    @property
    def metrics(self):
        if self._metrics is None:
            if self._result is not None:
                outs = self._result.fetches()
                self._metrics = {k: _dense(v) for k, v in
                                 zip(self._metric_names, outs[1:])}
            else:
                self._metrics = {}
        return self._metrics


class CheckpointConfig:
    """Periodic elastic checkpointing.

    retry:    optional resilience.RetryPolicy for the checkpoint I/O
              (each save's tmp-write phase retries as a unit).
    on_error: "warn" (default) — a save that still fails after retries
              is logged and counted (Trainer.checkpoint_failures) but
              does NOT kill training; the previous valid checkpoint
              remains the resume point. "raise" restores the old
              fail-stop behaviour.
    """

    def __init__(self, dirname: str, every_n_batches: int = 100,
                 max_keep: int = 3, retry=None, on_error: str = "warn"):
        if on_error not in ("warn", "raise"):
            raise ValueError(f"on_error must be 'warn' or 'raise', "
                             f"got {on_error!r}")
        self.dirname = dirname
        self.every_n_batches = every_n_batches
        self.max_keep = max_keep
        self.retry = retry
        self.on_error = on_error


class Trainer:
    """train() pumps reader batches through the program; each yielded
    batch is either a feed dict or a tuple routed through a DataFeeder
    built from `feed_order`."""

    def __init__(self, loss, main_program=None, startup_program=None,
                 executor=None, feed_order: Optional[Sequence] = None,
                 fetch_metrics: Optional[Dict[str, object]] = None,
                 checkpoint_config: Optional[CheckpointConfig] = None,
                 feeder_kwargs: Optional[dict] = None):
        from .framework import (default_main_program,
                                default_startup_program)
        from .executor import Executor

        self.loss = loss
        self.main_program = main_program or default_main_program()
        self.startup_program = startup_program or \
            default_startup_program()
        self.exe = executor or Executor()
        self.fetch_metrics = dict(fetch_metrics or {})
        self.checkpoint_config = checkpoint_config
        self._feeder = None
        if feed_order:
            from .data_feeder import DataFeeder
            vars_ = [self.main_program.global_block().var(n)
                     if isinstance(n, str) else n for n in feed_order]
            self._feeder = DataFeeder(vars_, **(feeder_kwargs or {}))
        self._started = False
        self.step = 0
        self.checkpoint_failures = 0
        self.last_checkpoint_error = None
        # streaming-input integration: the service train() is consuming
        # (cursor checkpointed beside the weights) and a restored
        # cursor waiting to seed the next service passed to train()
        self._input_service = None
        self._service_base = 0
        self._service_consumed = 0
        self._resume_input_state = None

    def _verify_programs(self):
        """Static verification of the (main, startup) pair, once at
        setup — the only gate that sees BOTH programs, so it is where
        uninitialized-persistable detection runs (a param the startup
        program never writes fails here with the var named, instead of
        as a scope KeyError mid-trace). Uses the cheap no-retrace shape
        pass: trainer programs come from the builder, which already
        stamped coverage/conflict markers. PADDLE_TPU_VERIFY=0 opts
        out."""
        from .analysis import verify_enabled, verify_program
        from .analysis.passes import fast_passes
        if not verify_enabled():
            return
        fetch = [self.loss.name] + [getattr(v, "name", str(v))
                                    for v in self.fetch_metrics.values()]
        feeds = [v.name for v in self._feeder.feed_vars] \
            if self._feeder is not None else None
        verify_program(
            self.main_program, startup=self.startup_program,
            feed_names=feeds, fetch_names=fetch,
            donate=getattr(self.exe, "donate_state", False),
            # train() always dispatches sync=False: a donated-fetch
            # hazard in fetch_metrics must fail HERE, not on the first
            # step after startup + checkpoint restore already ran
            async_dispatch=True,
            passes=fast_passes(with_uninit=True),
            program_label="trainer main program",
        ).raise_if_errors(context="Trainer setup")

    # -- lifecycle --------------------------------------------------------
    def start(self, resume: bool = True):
        """Run startup (param init), then restore the newest valid
        checkpoint if configured (elastic resume)."""
        self._verify_programs()
        self.exe.run(self.startup_program)
        if resume and self.checkpoint_config:
            from .distributed.checkpoint import load_checkpoint
            meta = load_checkpoint(self.checkpoint_config.dirname,
                                   main_program=self.main_program,
                                   executor=self.exe,
                                   retry=self.checkpoint_config.retry)
            if meta:
                self.step = int(meta.get("step", 0))
                # a streaming-input cursor saved with the checkpoint is
                # handed to the next StreamingInputService train() gets
                self._resume_input_state = meta.get("input_state")
        self._started = True
        return self

    def _to_feed(self, batch):
        if isinstance(batch, dict):
            return batch
        if self._feeder is None:
            raise ValueError(
                "reader yielded a tuple batch but no feed_order was given")
        return self._feeder.feed(batch)

    def _to_feed_device(self, batch):
        """_to_feed + host->device upload; runs on the prefetcher thread
        so the transfer overlaps the in-flight step's compute."""
        from .core.executor import device_feed
        return device_feed(self._to_feed(batch))

    # -- training loop ----------------------------------------------------
    def train(self, num_passes: int, reader: Callable[[], Iterable],
              event_handler: Optional[Callable] = None,
              steps_per_dispatch: int = 1, log_every: int = 1,
              prefetch: int = 0):
        """Event-loop training. steps_per_dispatch > 1 consumes K
        DISTINCT reader batches per compiled dispatch: the feeds are
        stacked along a leading K axis and Executor.run(iterations=K,
        stacked_feed=True) scans over them, so SGD semantics are
        unchanged from K=1 while per-dispatch overhead is paid once
        per K steps (the win on a high-RTT link). Events fire once per
        DISPATCH with the final batch's cost/metrics; self.step
        advances by the number of batches consumed. A short tail
        (fewer than K batches left in the pass) runs one batch at a
        time. Requires dense ndarray feeds of a fixed batch shape —
        ragged feeds fall back to per-batch dispatches.

        Every step is dispatched asynchronously (Executor.run
        sync=False); `log_every` sets how often the Trainer itself
        materializes cost/metrics. On logged dispatches (every
        `log_every`-th, default every one — the synchronous behaviour)
        EndIteration carries concrete values; in between it carries a
        lazy StepResult handle, the host never blocks on the device,
        and up to `log_every` undelivered results stay in flight.
        Trained weights are bit-identical for any `log_every` — only
        WHERE the host waits changes. `prefetch` > 0 additionally runs
        feed conversion + device upload for batch N+1 on a bounded
        background FeedPrefetcher (depth `prefetch`, 2 = classic
        double buffering) while batch N computes; incompatible with
        steps_per_dispatch > 1 (stacking needs host-side arrays).

        Checkpoint saves insert a device sync barrier first
        (Executor.synchronize), so a snapshot can never tear across an
        in-flight step.

        Observability (skipped entirely while the default
        MetricsRegistry is disabled — the process kill switch): each
        dispatch runs under a StepTrace root span (profiler events
        emitted inside — feed assembly, dispatch, RPC attempts — share
        one trace id per step), and the loop publishes
        paddle_tpu_train_steps_total / _step_seconds / _prefetch_depth
        (LIVE prefetch-queue occupancy; the configured depth is the
        separate _prefetch_depth_config gauge) to the metrics registry.
        step_seconds is host-side dispatch-to-dispatch wall time per
        batch: with async dispatch it measures sustained throughput,
        not device latency.

        `reader` may also be a reader.StreamingInputService: batches
        then come from the sharded multi-process input service, the
        service's delivered-batch cursor is checkpointed beside the
        weights, and a checkpoint resume re-seeds it (mid-epoch exact:
        no record replayed or skipped). Service epochs live in its
        config — call with num_passes=1."""
        from .observability import attribution as obs_attr
        from .observability import trace as obs_trace
        from .observability.registry import default_registry

        if not self._started:
            self.start()
        if getattr(reader, "is_streaming_input_service", False):
            # service-backed input: reader= is a StreamingInputService.
            # Its epochs live in the service config (use num_passes=1);
            # the delivered-batch cursor is checkpointed beside the
            # weights and a checkpoint restore re-seeds it, so resume
            # neither replays nor skips records.
            service = reader
            if self._resume_input_state is not None:
                service.restore(self._resume_input_state)
                self._resume_input_state = None
            reader = service.reader
            self._input_service = service
            self._service_base = service.delivered
            self._service_consumed = 0
        else:
            self._input_service = None
        handler = event_handler or (lambda e: None)
        fetch_names = list(self.fetch_metrics)
        fetch_list = [self.loss] + [self.fetch_metrics[k]
                                    for k in fetch_names]
        k = int(steps_per_dispatch)
        if k < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got {k} — a zero "
                "dispatch would report cost 0.0 while training nothing")
        log_every = int(log_every)
        if log_every < 1:
            raise ValueError(
                f"log_every must be >= 1, got {log_every}")
        prefetch = int(prefetch)
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        if prefetch and k > 1:
            raise ValueError(
                "prefetch and steps_per_dispatch > 1 are mutually "
                "exclusive: stacking K batches needs host-side ndarray "
                "feeds, but the prefetcher uploads each batch to device")
        reg = default_registry()
        obs_on = reg.enabled
        # live attribution rides the SAME kill switches: the disabled
        # registry (process-wide off) or PADDLE_TPU_ATTRIBUTION=0
        attr_on = obs_on and obs_attr.attribution_enabled()
        if obs_on:
            m_steps = reg.counter(
                "paddle_tpu_train_steps_total",
                "Training steps (batches) dispatched by Trainer.train.")
            m_step_s = reg.histogram(
                "paddle_tpu_train_step_seconds",
                "Host-side wall time per training step "
                "(dispatch-to-dispatch / batches per dispatch; under "
                "async dispatch this is throughput, not device latency).")
            m_pref = reg.gauge(
                "paddle_tpu_train_prefetch_depth",
                "LIVE FeedPrefetcher queue occupancy sampled at each "
                "dispatch (0 = the loop is about to block on input — "
                "the starvation signal elastic input scaling watches; "
                "always 0 with prefetch=0 inline feeds).")
            m_pref.set(0)
            reg.gauge(
                "paddle_tpu_train_prefetch_depth_config",
                "Configured prefetch= depth of the current train() "
                "call (0 = inline feed assembly).").set(prefetch)
        if attr_on:
            m_mfu = obs_attr.mfu_gauge(reg, "train")
            m_flops = obs_attr.model_flops_gauge(reg, "train")
            m_phase = obs_attr.phase_histogram(reg)
            # reset the phase window: events from start()/warmup must
            # not leak into the first step's breakdown
            obs_attr.drain_phases()

        def _stackable(feeds):
            if len(feeds) < 2:
                return None
            names = set(feeds[0])
            if any(set(f) != names for f in feeds[1:]):
                return None
            stacked = {}
            for n in names:
                vals = [f[n] for f in feeds]
                if not all(isinstance(v, np.ndarray) for v in vals):
                    return None
                if any(v.shape != vals[0].shape for v in vals[1:]):
                    return None
                stacked[n] = np.stack(vals)
            return stacked

        for pass_id in range(num_passes):
            handler(BeginPass(pass_id))
            costs = []
            # undelivered StepResults, oldest first; bounded at
            # log_every so a huge pass can't pin one fetch buffer per
            # step
            pending = deque()

            def _drain(keep: int):
                while len(pending) > keep:
                    costs.append(_scalar_cost(pending.popleft()))

            dispatch_id = 0
            prefetcher = None
            if prefetch:
                from .reader import FeedPrefetcher
                prefetcher = FeedPrefetcher(iter(reader()),
                                            convert=self._to_feed_device,
                                            depth=prefetch)
                feed_iter = iter(prefetcher)
            else:
                from . import profiler

                def _inline_feeds():
                    # un-prefetched path: reader + conversion run inline
                    # on the loop thread, so the wait is HOST-BLOCKED
                    # time (the A/B benchmark's sync-mode baseline)
                    raw_it = iter(reader())
                    while True:
                        with profiler.RecordEvent(
                                "pipeline::host_blocked",
                                cat=profiler.CAT_PIPELINE):
                            try:
                                batch = self._to_feed(next(raw_it))
                            except StopIteration:
                                return
                        yield batch

                feed_iter = _inline_feeds()
            t_prev = time.monotonic()
            try:
                while True:
                    # one StepTrace root span per dispatch: feed
                    # assembly, the dispatch itself, and any RPCs the
                    # handler issues all share this step's trace id.
                    # Gated with the metrics on the SAME toggle so a
                    # disabled registry is a full telemetry kill
                    # switch — and the overhead benchmark's "off" arm
                    # really is the uninstrumented loop.
                    with (obs_trace.step_trace(self.step) if obs_on
                          else contextlib.nullcontext()) as root:
                        if prefetcher is not None and root is not None:
                            # cross-thread span handoff: producer-side
                            # convert+upload work is stamped with the
                            # CURRENT step's span (the most recent
                            # dispatch — batch N+1 converts while step
                            # N computes)
                            prefetcher.adopt_span(root)
                        group = []
                        for _ in range(k):
                            try:
                                feed = next(feed_iter)
                                if k > 1:
                                    # accumulating K batches: snapshot
                                    # ndarray feeds NOW — readers like
                                    # multiprocess_batch_reader hand
                                    # out shared-memory views the
                                    # producer reuses once the
                                    # consumer advances
                                    feed = {n: (np.array(v) if
                                                isinstance(v, np.ndarray)
                                                else v)
                                            for n, v in feed.items()}
                                group.append(feed)
                            except StopIteration:
                                break
                        if not group:
                            # nothing dispatched: the span covered only
                            # the reader-exhaustion check, so drop its
                            # trace event rather than reporting a
                            # phantom N+1th step per pass
                            if root is not None:
                                root.discard()
                            break
                        handler(BeginIteration(pass_id, dispatch_id))
                        stacked = _stackable(group) if len(group) == k \
                            and k > 1 else None
                        if stacked is not None:
                            res = self.exe.run(self.main_program,
                                               feed=stacked,
                                               fetch_list=fetch_list,
                                               iterations=k,
                                               stacked_feed=True,
                                               sync=False)
                        else:
                            for i, feed in enumerate(group):
                                res = self.exe.run(self.main_program,
                                                   feed=feed,
                                                   fetch_list=fetch_list,
                                                   sync=False)
                                if i < len(group) - 1:
                                    # non-stackable k>1 fallback: only
                                    # the FINAL batch's result feeds
                                    # the event/cost plumbing, so
                                    # materialize the intermediates
                                    # here — fetch-time checks
                                    # (NaN/Inf) must cover every
                                    # batch, as the sync loop did
                                    res.fetches()
                        pending.append(res)
                        self.step += len(group)
                        if self._input_service is not None:
                            self._service_consumed += len(group)
                        logged = (dispatch_id + 1) % log_every == 0
                        ev = EndIteration(pass_id, dispatch_id,
                                          result=res,
                                          metric_names=fetch_names)
                        if logged:
                            ev.cost  # materialize: periodic sync point
                        handler(ev)
                        # logged dispatches flush everything in flight;
                        # others keep at most log_every results pending
                        # — but a checkpoint crossing drains fully
                        # first, so fetch-time checks (CHECK_NAN_INF)
                        # raise BEFORE a poisoned snapshot can publish
                        # as the newest resume point
                        if logged or self._checkpoint_due(len(group)):
                            _drain(0)
                        else:
                            _drain(log_every)
                        self._maybe_checkpoint(advanced=len(group))
                    if obs_on:
                        now = time.monotonic()
                        wall = now - t_prev
                        m_steps.inc(len(group))
                        m_step_s.record(wall / len(group))
                        m_pref.set(prefetcher.occupancy()
                                   if prefetcher is not None else 0)
                        t_prev = now
                        # static peak-HBM plan of THIS dispatch's
                        # executable (same result-not-executor rule as
                        # the cost read below)
                        mem = getattr(res, "memory", None)
                        if mem is not None:
                            from .analysis.memory import publish_peak
                            publish_peak("train", mem.peak_bytes)
                        if attr_on:
                            # phase breakdown: measured host phases
                            # since the last dispatch + the device
                            # residual — the five phases of one step
                            # sum to its wall time (device clamps at 0
                            # when overlapped host work exceeds it)
                            phases = obs_attr.drain_phases()
                            host = sum(phases.values())
                            phases["device"] = max(0.0, wall - host)
                            for ph in obs_attr.PHASES:
                                m_phase.labels(phase=ph).record(
                                    phases.get(ph, 0.0) / len(group))
                            # the dispatch's OWN cost off the result:
                            # exe.last_cost may already belong to a
                            # different program (an event handler
                            # calling trainer.test() runs the pruned
                            # eval clone on this same executor)
                            cost = getattr(res, "cost", None)
                            if cost is not None and cost.flops:
                                step_s = wall / len(group)
                                m_flops.set(float(cost.flops))
                                if step_s > 0:
                                    m_mfu.set(cost.flops
                                              / obs_attr.peak_flops()
                                              / step_s)
                    dispatch_id += 1
                    if len(group) < k:
                        break
            finally:
                if prefetcher is not None:
                    prefetcher.close()
            _drain(0)
            handler(EndPass(pass_id, {
                "mean_cost": float(np.mean(costs)) if costs else None}))

    def _checkpoint_due(self, advanced: int) -> bool:
        """Did the last `advanced` steps cross an every_n_batches
        multiple? ("crossed" rather than "== 0": with
        steps_per_dispatch > 1 the counter advances in strides and may
        never land exactly on a multiple.)"""
        cc = self.checkpoint_config
        return bool(cc) and (self.step // cc.every_n_batches
                             > (self.step - advanced)
                             // cc.every_n_batches)

    def _maybe_checkpoint(self, advanced: int = 1):
        cc = self.checkpoint_config
        if self._checkpoint_due(advanced):
            from .distributed.checkpoint import save_checkpoint
            # (save_checkpoint itself runs the Executor.synchronize
            # barrier before snapshotting, covering every caller)
            try:
                extra = None
                if self._input_service is not None:
                    # cursor of the TRAINED position (consumed count),
                    # not the prefetcher's read-ahead — resume
                    # re-produces the prefetched-but-untrained batches.
                    # Inside the try: a cursor-lookup failure is a
                    # checkpoint failure (warn path), not a run killer
                    extra = {"input_state":
                             self._input_service.state_for(
                                 self._service_base
                                 + self._service_consumed)}
                save_checkpoint(cc.dirname, step=self.step,
                                main_program=self.main_program,
                                executor=self.exe, max_keep=cc.max_keep,
                                extra_meta=extra, retry=cc.retry)
            except Exception as e:
                # checkpointing is off the training math path: a failed
                # save (after retries) must not kill the run — the last
                # valid checkpoint stays the resume point
                self.checkpoint_failures += 1
                self.last_checkpoint_error = e
                from .observability.registry import default_registry
                default_registry().counter(
                    "paddle_tpu_train_checkpoint_failures_total",
                    "Checkpoint saves that failed after retries "
                    "(training continued; previous checkpoint remains "
                    "the resume point).").inc()
                # flight-recorder trigger: the dump carries the events
                # and metrics leading up to the failed save
                from .observability.flight_recorder import record_failure
                record_failure("checkpoint_failure", exc=e,
                               context={"step": self.step,
                                        "dirname": cc.dirname})
                if cc.on_error == "raise":
                    raise
                import warnings
                warnings.warn(
                    f"checkpoint save at step {self.step} failed "
                    f"({e!r}); training continues, resume point is the "
                    "previous valid checkpoint", RuntimeWarning)

    # -- evaluation -------------------------------------------------------
    def test(self, reader: Callable[[], Iterable],
             fetch_list: Optional[List] = None) -> Dict[str, float]:
        """Mean of loss (+ metrics) over a test reader — no optimizer ops
        run because the fetches are computed on an inference-pruned clone
        (reference: v2 SGD.test, trainer.py:209)."""
        from .core.executor import STEP_VAR
        from .core.scope import global_scope
        from .io import _prune

        fetch_list = fetch_list or [self.loss]
        names = [getattr(v, "name", v) for v in fetch_list]
        pruned = _prune(self.main_program, [], names)
        totals = {n: [] for n in names}
        scope = global_scope()
        step_before = scope.find(STEP_VAR)
        try:
            for batch in reader():
                feed = self._to_feed(batch)
                outs = self.exe.run(pruned, feed=feed, fetch_list=names)
                for n, v in zip(names, outs):
                    totals[n].append(
                        np.asarray(_dense(v), np.float64).mean())
        finally:
            # evaluation must not advance the LR-schedule step counter
            if step_before is not None:
                scope.set(STEP_VAR, step_before)
        return {n: float(np.mean(vs)) if vs else float("nan")
                for n, vs in totals.items()}

    def save_params(self, dirname: str):
        from . import io as pt_io
        pt_io.save_params(self.exe, dirname, self.main_program)

    def save_inference_model(self, dirname: str, feed_names, targets):
        from . import io as pt_io
        pt_io.save_inference_model(dirname, feed_names, targets, self.exe,
                                   main_program=self.main_program)


def _dense(v):
    return v.data if hasattr(v, "data") else v


def _scalar_cost(outs) -> float:
    """First fetched value (the loss) as a python float — the one cost
    extraction shared by EndIteration.cost and the pass-mean plumbing."""
    return float(np.asarray(_dense(outs[0])).reshape(-1)[0])
