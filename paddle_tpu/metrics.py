"""Host-side streaming metrics / evaluators (reference:
python/paddle/fluid/evaluator.py + metrics — Accuracy, ChunkEvaluator,
EditDistance accumulation across batches)."""
from __future__ import annotations

import numpy as np

__all__ = ["Accuracy", "EditDistance", "CompositeMetric", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kw):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0

    def update(self, distances, seq_num):
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)

    def eval(self):
        return self.total_distance / max(self.seq_num, 1)


class Auc(MetricBase):
    def __init__(self, name=None, num_thresholds=200):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self.num_thresholds
        self.tp = np.zeros(n)
        self.fp = np.zeros(n)
        self.tn = np.zeros(n)
        self.fn = np.zeros(n)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1]
        thresholds = np.linspace(0.0, 1.0, self.num_thresholds)
        pos = labels > 0
        for i, t in enumerate(thresholds):
            pred_pos = pos_prob >= t
            self.tp[i] += np.sum(pred_pos & pos)
            self.fp[i] += np.sum(pred_pos & ~pos)
            self.fn[i] += np.sum(~pred_pos & pos)
            self.tn[i] += np.sum(~pred_pos & ~pos)

    def eval(self):
        tpr = self.tp / np.maximum(self.tp + self.fn, 1e-12)
        fpr = self.fp / np.maximum(self.fp + self.tn, 1e-12)
        order = np.argsort(fpr)
        fpr, tpr = fpr[order], tpr[order]
        return float(np.trapezoid(tpr, fpr))


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args):
        for m, a in zip(self._metrics, args):
            m.update(*a)

    def eval(self):
        return [m.eval() for m in self._metrics]
