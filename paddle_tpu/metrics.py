"""Host-side streaming metrics / evaluators (reference:
python/paddle/fluid/evaluator.py + metrics — Accuracy, ChunkEvaluator,
EditDistance accumulation across batches)."""
from __future__ import annotations

import numpy as np

__all__ = ["Accuracy", "ChunkEvaluator", "EditDistance",
           "CompositeMetric", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kw):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Streaming chunking P/R/F1 (reference: evaluator.py ChunkEvaluator):
    accumulate the per-batch chunk counts the chunk_eval op emits
    (NumInferChunks / NumLabelChunks / NumCorrectChunks) and report the
    corpus-level precision, recall, F1."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).item())
        self.num_label_chunks += int(np.asarray(num_label_chunks).item())
        self.num_correct_chunks += \
            int(np.asarray(num_correct_chunks).item())

    def eval(self):
        precision = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0

    def update(self, distances, seq_num):
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)

    def eval(self):
        return self.total_distance / max(self.seq_num, 1)


class Auc(MetricBase):
    def __init__(self, name=None, num_thresholds=200):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self.num_thresholds
        self.tp = np.zeros(n)
        self.fp = np.zeros(n)
        self.tn = np.zeros(n)
        self.fn = np.zeros(n)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, -1]
        thresholds = np.linspace(0.0, 1.0, self.num_thresholds)
        pos = labels > 0
        for i, t in enumerate(thresholds):
            pred_pos = pos_prob >= t
            self.tp[i] += np.sum(pred_pos & pos)
            self.fp[i] += np.sum(pred_pos & ~pos)
            self.fn[i] += np.sum(~pred_pos & pos)
            self.tn[i] += np.sum(~pred_pos & ~pos)

    def eval(self):
        tpr = self.tp / np.maximum(self.tp + self.fn, 1e-12)
        fpr = self.fp / np.maximum(self.fp + self.tn, 1e-12)
        order = np.argsort(fpr)
        fpr, tpr = fpr[order], tpr[order]
        return float(np.trapezoid(tpr, fpr))


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args):
        for m, a in zip(self._metrics, args):
            m.update(*a)

    def eval(self):
        return [m.eval() for m in self._metrics]


class DetectionMAP(MetricBase):
    """Streaming detection mean-average-precision (reference:
    evaluator.py DetectionMAP:254 + detection_map_op.cc). Host-side
    accumulation like the other evaluators: update() per batch with the
    static-shape NMS output of layers.detection_output plus ground
    truth; eval() computes per-class AP ('integral' or '11point') and
    returns the mean over classes with ground truth.

    Matching per image/class (SSD/VOC protocol): detections sorted by
    score; each takes its highest-IoU gt (matched or not). IoU >=
    overlap_threshold and the gt unmatched -> TP; already matched -> FP
    (no fallback to the next-best gt); below threshold -> FP. With
    evaluate_difficult=False, difficult gts don't count toward npos and
    detections whose best match is difficult are dropped (neither TP
    nor FP)."""

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral",
                 background_label=0, name=None):
        super().__init__(name)
        assert ap_version in ("integral", "11point")
        self.class_num = class_num
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.background_label = background_label
        self.reset()

    def reset(self):
        # per class: npos (non-difficult gt count) and (score, is_tp) rows
        self._npos = np.zeros(self.class_num, np.int64)
        self._records = [[] for _ in range(self.class_num)]

    @staticmethod
    def _iou_matrix(a, b):
        """[M, 4] x [N, 4] -> [M, N] IoU, vectorized on host."""
        x1 = np.maximum(a[:, None, 0], b[None, :, 0])
        y1 = np.maximum(a[:, None, 1], b[None, :, 1])
        x2 = np.minimum(a[:, None, 2], b[None, :, 2])
        y2 = np.minimum(a[:, None, 3], b[None, :, 3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        area = lambda v: np.maximum(v[:, 2] - v[:, 0], 0) * \
            np.maximum(v[:, 3] - v[:, 1], 0)
        union = area(a)[:, None] + area(b)[None, :] - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)

    def update(self, detections, gt_boxes, gt_labels, difficult=None):
        """One image. detections: [M, 6] rows (label, score, x1, y1, x2,
        y2); padded rows (score < 0, as emitted by the static-shape NMS)
        are ignored. gt_boxes: [N, 4]; gt_labels: [N]; difficult:
        optional [N] bools."""
        det = np.asarray(detections, np.float32).reshape(-1, 6)
        det = det[det[:, 1] >= 0]
        gtb = np.asarray(gt_boxes, np.float32).reshape(-1, 4)
        gtl = np.asarray(gt_labels).reshape(-1).astype(np.int64)
        diff = np.zeros(len(gtl), bool) if difficult is None \
            else np.asarray(difficult).reshape(-1).astype(bool)
        for c in range(self.class_num):
            if c == self.background_label:
                continue
            sel = gtl == c
            cls_gt = gtb[sel]
            cls_diff = diff[sel]
            if self.evaluate_difficult:
                self._npos[c] += len(cls_gt)
            else:
                self._npos[c] += int((~cls_diff).sum())
            cls_det = det[det[:, 0] == c]
            order = np.argsort(-cls_det[:, 1])
            matched = np.zeros(len(cls_gt), bool)
            ious = self._iou_matrix(cls_det[:, 2:6], cls_gt) \
                if len(cls_gt) else np.zeros((len(cls_det), 0))
            for i in order:
                score = cls_det[i, 1]
                if ious.shape[1]:
                    best_j = int(np.argmax(ious[i]))
                    best = float(ious[i, best_j])
                else:
                    best, best_j = 0.0, -1
                if best >= self.overlap_threshold and best_j >= 0:
                    if not self.evaluate_difficult and cls_diff[best_j]:
                        continue            # ignore: neither TP nor FP
                    if not matched[best_j]:
                        matched[best_j] = True
                        self._records[c].append((score, 1))
                    else:
                        self._records[c].append((score, 0))
                else:
                    self._records[c].append((score, 0))

    def _ap(self, recs, npos):
        if npos == 0 or not recs:
            return None
        recs = sorted(recs, key=lambda r: -r[0])
        tp = np.cumsum([r[1] for r in recs])
        fp = np.cumsum([1 - r[1] for r in recs])
        recall = tp / npos
        precision = tp / np.maximum(tp + fp, 1e-12)
        if self.ap_version == "11point":
            ap = 0.0
            # linspace, not arange: arange's 0.3/0.6/0.7 land a ulp high
            # and would empty buckets whose max recall is exactly there
            for t in np.linspace(0.0, 1.0, 11):
                p = precision[recall >= t - 1e-9]
                ap += (p.max() if len(p) else 0.0) / 11.0
            return ap
        # integral (VOC-style): sum precision deltas over recall steps
        ap, prev_r = 0.0, 0.0
        for r, p in zip(recall, precision):
            ap += p * (r - prev_r)
            prev_r = r
        return ap

    def eval(self):
        aps = [self._ap(self._records[c], self._npos[c])
               for c in range(self.class_num)
               if c != self.background_label]
        aps = [a for a in aps if a is not None]
        return float(np.mean(aps)) if aps else 0.0


__all__.append("DetectionMAP")
