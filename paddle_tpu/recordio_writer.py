"""Recordio feed converters (reference:
python/paddle/fluid/recordio_writer.py — convert_reader_to_recordio_file
serializes each feeded batch as one record). The chunked record format
itself lives in recordio.py (Writer/Scanner + the native loader); here
each record is a pickled {var_name: numpy-or-ragged} feed dict, and
`read_recordio_feeds` yields them back ready for Executor.run."""
from __future__ import annotations

import pickle
from typing import Iterator, List

import numpy as np

from .recordio import Scanner, Writer, write_recordio  # noqa: F401

__all__ = ["Writer", "write_recordio",
           "convert_reader_to_recordio_file",
           "convert_reader_to_recordio_files", "read_recordio_feeds"]


def _to_portable(value):
    """Feed value -> picklable host form (ragged pairs/trees become
    plain numpy tuples)."""
    from .core.lod import RaggedNested, RaggedPair, RaggedTree
    if isinstance(value, RaggedPair):
        return ("ragged", np.asarray(value.data),
                np.asarray(value.lengths))
    if isinstance(value, RaggedNested):
        return ("ragged2", np.asarray(value.data),
                np.asarray(value.sub_lengths),
                np.asarray(value.tok_lengths))
    if isinstance(value, RaggedTree):
        return ("raggedk", np.asarray(value.data),
                [np.asarray(l) for l in value.lengths])
    return np.asarray(value)


def _from_portable(value):
    from .core.lod import RaggedNested, RaggedPair, RaggedTree
    if isinstance(value, tuple) and value and value[0] == "ragged":
        return RaggedPair(value[1], value[2])
    if isinstance(value, tuple) and value and value[0] == "ragged2":
        return RaggedNested(value[1], value[2], value[3])
    if isinstance(value, tuple) and value and value[0] == "raggedk":
        return RaggedTree(value[1], tuple(value[2]))
    return value


def convert_reader_to_recordio_file(filename: str, reader_creator,
                                    feeder, max_num_records: int = 1000,
                                    feed_order=None) -> int:
    """Feed every batch from `reader_creator()` through `feeder` and
    write one record per batch; returns the record count (reference
    recordio_writer.py:20)."""
    records = []
    for batch in reader_creator():
        feed = feeder.feed(batch)
        if feed_order is not None:
            feed = {k: feed[k] for k in feed_order}
        records.append(pickle.dumps(
            {k: _to_portable(v) for k, v in feed.items()}))
        if len(records) >= max_num_records:
            break
    write_recordio(records, filename)
    return len(records)


def convert_reader_to_recordio_files(filename: str, batch_per_file: int,
                                     reader_creator, feeder,
                                     max_num_records: int = 1000,
                                     feed_order=None) -> List[str]:
    """Multi-file variant: rotate to `filename-00000`, `-00001`, ...
    every `batch_per_file` records (reference recordio_writer.py:46)."""
    paths: List[str] = []
    records = []

    def flush():
        if not records:
            return
        path = f"{filename}-{len(paths):05d}"
        write_recordio(records, path)
        paths.append(path)
        records.clear()

    n = 0
    for batch in reader_creator():
        feed = feeder.feed(batch)
        if feed_order is not None:
            feed = {k: feed[k] for k in feed_order}
        records.append(pickle.dumps(
            {k: _to_portable(v) for k, v in feed.items()}))
        n += 1
        if len(records) >= batch_per_file:
            flush()
        if n >= max_num_records:
            break
    flush()
    return paths


def read_recordio_feeds(path: str) -> Iterator[dict]:
    """Yield the feed dicts a converter wrote — directly usable as
    Executor.run(feed=...)."""
    scanner = Scanner(path)
    for rec in scanner:
        yield {k: _from_portable(v)
               for k, v in pickle.loads(rec).items()}
