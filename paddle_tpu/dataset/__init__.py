"""Dataset modules (reference: python/paddle/dataset/ — 14 corpora).

Each module exposes creator functions returning readers (zero-arg callables
yielding samples) with the reference's sample schemas; data is synthetic
when the real corpus is not cached locally (see common.py).
"""
from . import (cifar, common, conll05, flowers, image, imdb, imikolov,
               mnist, movielens, mq2007, sentiment, uci_housing, voc2012,
               wmt14, wmt16)

__all__ = [
    "cifar", "common", "conll05", "flowers", "image", "imdb", "imikolov",
    "mnist", "movielens", "mq2007", "sentiment", "uci_housing", "voc2012",
    "wmt14", "wmt16",
]
