"""PTB language-model corpus (reference: python/paddle/dataset/imikolov.py —
n-gram tuples or sequence pairs from Penn Treebank). Parses the real
`simple-examples.tgz` (./data/ptb.train.txt / ptb.valid.txt) from the
cache dir when present (reference imikolov.py:33-100: frequency dict
with min_word_freq, <s>/<e>/<unk> markers, NGRAM windows or SEQ pairs);
otherwise synthesizes Markov-ish id streams over a fixed vocab."""
import os
import tarfile

import numpy as np

from .common import build_freq_dict, cache_path, rng_for

N = 5  # default n-gram order used by the word2vec book chapter
_VOCAB = 2074  # reference build_dict(min_freq=50) size is ~2073 + <unk>


class DataType:
    NGRAM = 1
    SEQ = 2


def _real_archive():
    path = cache_path("imikolov", "simple-examples.tgz")
    return path if os.path.exists(path) else None


def _real_sentences(member_suffix):
    with tarfile.open(_real_archive(), mode="r:*") as tf:
        name = next(n for n in tf.getnames()
                    if n.endswith(member_suffix))
        for line in tf.extractfile(name).read().decode().splitlines():
            words = line.strip().split()
            if words:
                yield words


def build_dict(min_word_freq: int = 50):
    path = _real_archive()
    if path:
        # the PTB text carries literal "<unk>" tokens; the reference
        # drops them from the count and re-appends <unk> at the end
        return build_freq_dict(
            lambda: ([w for w in words if w != "<unk>"]
                     for words in _real_sentences("data/ptb.train.txt")),
            cache_key=("imikolov", path, os.path.getmtime(path),
                       min_word_freq),
            cutoff=min_word_freq)
    return {("w%d" % i): i for i in range(_VOCAB)}


def _real_reader(member_suffix, word_idx, n, data_type):
    def reader():
        idx = word_idx or build_dict()
        unk = idx["<unk>"]
        for words in _real_sentences(member_suffix):
            # reference: sentence wrapped in <s>/<e>; both map through
            # the dict (absent markers fall back to <unk>)
            ids = [idx.get(w, unk)
                   for w in ["<s>"] + words + ["<e>"]]
            if data_type == DataType.NGRAM:
                if len(ids) >= n:
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])
            else:
                yield ids[:-1], ids[1:]
    return reader


def _stream(split, length):
    # order-1 Markov chain over a Zipf-like active vocab => n-grams are
    # genuinely predictive and learnable from a small corpus
    _ACTIVE = 300
    rng = rng_for("imikolov", "trans")
    trans = rng.randint(0, _ACTIVE, (_ACTIVE, 2))
    rng = rng_for("imikolov", split)
    ids = np.empty(length, np.int64)
    ids[0] = rng.randint(_ACTIVE)
    choices = rng.randint(0, 2, length)
    noise = rng.rand(length) < 0.05
    for i in range(1, length):
        ids[i] = rng.randint(_VOCAB) if noise[i] else \
            trans[ids[i - 1] % _ACTIVE, choices[i]]
    return ids


def _make(split, word_idx, n, data_type, total):
    def reader():
        ids = _stream(split, total)
        if data_type == DataType.NGRAM:
            for i in range(len(ids) - n + 1):
                yield tuple(int(w) for w in ids[i:i + n])
        else:
            sent_len = 20
            for i in range(0, len(ids) - sent_len - 1, sent_len):
                src = [int(w) for w in ids[i:i + sent_len]]
                trg = [int(w) for w in ids[i + 1:i + sent_len + 1]]
                yield src, trg
    return reader


def train(word_idx=None, n=N, data_type=DataType.NGRAM):
    if _real_archive():
        return _real_reader("data/ptb.train.txt", word_idx, n, data_type)
    return _make("train", word_idx, n, data_type, 60000)


def test(word_idx=None, n=N, data_type=DataType.NGRAM):
    if _real_archive():
        return _real_reader("data/ptb.valid.txt", word_idx, n, data_type)
    return _make("test", word_idx, n, data_type, 6000)
