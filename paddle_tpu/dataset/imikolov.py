"""PTB language-model corpus (reference: python/paddle/dataset/imikolov.py —
n-gram tuples or sequence pairs from Penn Treebank). Synthetic Markov-ish
id streams over a fixed vocab."""
import numpy as np

from .common import rng_for

N = 5  # default n-gram order used by the word2vec book chapter
_VOCAB = 2074  # reference build_dict(min_freq=50) size is ~2073 + <unk>


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq: int = 50):
    return {("w%d" % i): i for i in range(_VOCAB)}


def _stream(split, length):
    # order-1 Markov chain over a Zipf-like active vocab => n-grams are
    # genuinely predictive and learnable from a small corpus
    _ACTIVE = 300
    rng = rng_for("imikolov", "trans")
    trans = rng.randint(0, _ACTIVE, (_ACTIVE, 2))
    rng = rng_for("imikolov", split)
    ids = np.empty(length, np.int64)
    ids[0] = rng.randint(_ACTIVE)
    choices = rng.randint(0, 2, length)
    noise = rng.rand(length) < 0.05
    for i in range(1, length):
        ids[i] = rng.randint(_VOCAB) if noise[i] else \
            trans[ids[i - 1] % _ACTIVE, choices[i]]
    return ids


def _make(split, word_idx, n, data_type, total):
    def reader():
        ids = _stream(split, total)
        if data_type == DataType.NGRAM:
            for i in range(len(ids) - n + 1):
                yield tuple(int(w) for w in ids[i:i + n])
        else:
            sent_len = 20
            for i in range(0, len(ids) - sent_len - 1, sent_len):
                src = [int(w) for w in ids[i:i + sent_len]]
                trg = [int(w) for w in ids[i + 1:i + sent_len + 1]]
                yield src, trg
    return reader


def train(word_idx=None, n=N, data_type=DataType.NGRAM):
    return _make("train", word_idx, n, data_type, 60000)


def test(word_idx=None, n=N, data_type=DataType.NGRAM):
    return _make("test", word_idx, n, data_type, 6000)
