"""CoNLL-2005 semantic role labeling (reference: python/paddle/dataset/
conll05.py — sample = (word_seq, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
verb_seq, mark_seq, label_seq) for label_semantic_roles). Synthetic
sequences where labels depend on word/verb/mark so the CRF converges."""
import numpy as np

from .common import rng_for

_WORD_VOCAB, _VERB_VOCAB, _NUM_LABELS = 2000, 100, 59  # ref label dict ~59


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_WORD_VOCAB)}
    verb_dict = {("v%d" % i): i for i in range(_VERB_VOCAB)}
    label_dict = {("l%d" % i): i for i in range(_NUM_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = rng_for("conll05", "emb")
    return rng.randn(_WORD_VOCAB, 32).astype(np.float32)


def _make(split, n):
    def reader():
        rng = rng_for("conll05", split)
        label_of = rng_for("conll05", "rule").randint(
            0, _NUM_LABELS, (_WORD_VOCAB, 2))
        active = 400  # Zipf-like active vocab => learnable small corpus
        for _ in range(n):
            length = int(rng.randint(5, 25))
            words = rng.randint(0, active, length)
            verb = int(rng.randint(0, _VERB_VOCAB))
            pred_pos = int(rng.randint(0, length))
            mark = [1 if i == pred_pos else 0 for i in range(length)]
            labels = [int(label_of[w, m]) for w, m in zip(words, mark)]
            ctx = []
            for off in (-2, -1, 0, 1, 2):
                p = min(max(pred_pos + off, 0), length - 1)
                ctx.append([int(words[p])] * length)
            word_seq = [int(w) for w in words]
            verb_seq = [verb] * length
            yield (word_seq, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4],
                   verb_seq, mark, labels)
    return reader


def test():
    return _make("test", 512)


def train():
    return _make("train", 2048)
