"""CoNLL-2005 semantic role labeling (reference: python/paddle/dataset/
conll05.py — sample = (word_seq, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
verb_seq, mark_seq, label_seq) for label_semantic_roles). Parses the
real column-format corpus from the cache dir when present (reference
conll05.py:46-180: words file + bracketed props file, one sample per
predicate column, span tags IOB-ified via the target dict); otherwise
synthesizes sequences where labels depend on word/verb/mark so the CRF
converges."""
import gzip
import os

import numpy as np

from .common import cache_path, rng_for

_WORD_VOCAB, _VERB_VOCAB, _NUM_LABELS = 2000, 100, 59  # ref label dict ~59


def _real_base():
    base = cache_path("conll05")
    return base if os.path.exists(os.path.join(base, "wordDict.txt")) \
        else None


def _open_maybe_gz(base, stem):
    for name in (stem, stem + ".gz"):
        path = os.path.join(base, name)
        if os.path.exists(path):
            if name.endswith(".gz"):
                return gzip.open(path, "rt", encoding="utf-8")
            return open(path, encoding="utf-8")
    raise FileNotFoundError(f"{stem}[.gz] not under {base}")


def _load_real_dict(base, fname):
    with open(os.path.join(base, fname), encoding="utf-8") as f:
        return {ln.strip(): i for i, ln in enumerate(f) if ln.strip()}


def _sentences(fh):
    """Blank-line-separated column sentences."""
    rows = []
    for line in fh:
        line = line.strip()
        if not line:
            if rows:
                yield rows
                rows = []
        else:
            rows.append(line.split())
    if rows:
        yield rows
    fh.close()


def _iob(tags):
    """Bracketed span tags ("(A0*", "*", "*)") -> IOB labels
    (reference conll05.py:104-128 corpus_reader label pass)."""
    labels, cur = [], None
    for tag in tags:
        if tag.startswith("("):
            cur = tag[1:tag.index("*")]
            labels.append("B-" + cur)
        elif cur is not None:
            labels.append("I-" + cur)
        else:
            labels.append("O")
        if tag.endswith(")"):
            cur = None
    return labels


def _real_reader(split):
    def reader():
        base = _real_base()
        word_dict = _load_real_dict(base, "wordDict.txt")
        verb_dict = _load_real_dict(base, "verbDict.txt")
        label_dict = _load_real_dict(base, "targetDict.txt")
        unk = word_dict.get("<unk>", 0)
        words_fh = _open_maybe_gz(base, f"{split}.words")
        props_fh = _open_maybe_gz(base, f"{split}.props")
        for wrows, prows in zip(_sentences(words_fh),
                                _sentences(props_fh)):
            words = [r[0] for r in wrows]
            length = len(words)
            n_pred = len(prows[0]) - 1
            for p in range(n_pred):
                tags = [r[1 + p] for r in prows]
                labels = _iob(tags)
                pred_pos = next(i for i, t in enumerate(tags)
                                if t.startswith("(V"))
                verb = verb_dict.get(prows[pred_pos][0], 0)
                mark = [1 if lab.endswith("-V") else 0 for lab in labels]
                word_ids = [word_dict.get(w.lower(), unk) for w in words]
                ctx = []
                for off in (-2, -1, 0, 1, 2):
                    q = pred_pos + off
                    cid = word_ids[q] if 0 <= q < length else unk
                    ctx.append([cid] * length)
                yield (word_ids, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4],
                       [verb] * length, mark,
                       [label_dict.get(lab, 0) for lab in labels])
    return reader


def get_dict():
    base = _real_base()
    if base:
        return (_load_real_dict(base, "wordDict.txt"),
                _load_real_dict(base, "verbDict.txt"),
                _load_real_dict(base, "targetDict.txt"))
    word_dict = {("w%d" % i): i for i in range(_WORD_VOCAB)}
    verb_dict = {("v%d" % i): i for i in range(_VERB_VOCAB)}
    label_dict = {("l%d" % i): i for i in range(_NUM_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    rng = rng_for("conll05", "emb")
    n_words = len(get_dict()[0])
    return rng.randn(n_words, 32).astype(np.float32)


def _make(split, n):
    def reader():
        rng = rng_for("conll05", split)
        label_of = rng_for("conll05", "rule").randint(
            0, _NUM_LABELS, (_WORD_VOCAB, 2))
        active = 400  # Zipf-like active vocab => learnable small corpus
        for _ in range(n):
            length = int(rng.randint(5, 25))
            words = rng.randint(0, active, length)
            verb = int(rng.randint(0, _VERB_VOCAB))
            pred_pos = int(rng.randint(0, length))
            mark = [1 if i == pred_pos else 0 for i in range(length)]
            labels = [int(label_of[w, m]) for w, m in zip(words, mark)]
            ctx = []
            for off in (-2, -1, 0, 1, 2):
                p = min(max(pred_pos + off, 0), length - 1)
                ctx.append([int(words[p])] * length)
            word_seq = [int(w) for w in words]
            verb_seq = [verb] * length
            yield (word_seq, ctx[0], ctx[1], ctx[2], ctx[3], ctx[4],
                   verb_seq, mark, labels)
    return reader


def test():
    if _real_base():
        return _real_reader("test.wsj")
    return _make("test", 512)


def train():
    if _real_base():
        return _real_reader("train.wsj")
    return _make("train", 2048)
