"""UCI Housing regression dataset (reference:
python/paddle/dataset/uci_housing.py — 13 features, scalar price).
Synthetic: features ~ N(0,1), price = w.x + noise (fixed w), so fit_a_line
converges the same way the real data does."""
import numpy as np

from .common import rng_for

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

_W = np.linspace(-1.0, 1.0, 13).astype(np.float32)


def _make(split: str, n: int):
    rng = rng_for("uci_housing", split)
    x = rng.randn(n, 13).astype(np.float32)
    y = (x @ _W + 0.1 * rng.randn(n)).astype(np.float32).reshape(n, 1)

    def reader():
        for i in range(n):
            yield x[i], y[i]
    return reader


def train():
    return _make("train", 404)


def test():
    return _make("test", 102)
