"""UCI Housing regression dataset (reference:
python/paddle/dataset/uci_housing.py — 13 features, scalar price).
Parses the real whitespace-separated `housing.data` (506x14) from the
cache dir when present, with the reference's feature normalization
(uci_housing.py:49-60: (x - avg) / (max - min)) and 404/102 split;
otherwise synthesizes a linear-regression corpus so fit_a_line
converges the same way."""
import os

import numpy as np

from .common import cache_path, rng_for

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

_W = np.linspace(-1.0, 1.0, 13).astype(np.float32)
_TRAIN_N = 404   # reference: first 404 rows train, rest test


def _real_data():
    path = cache_path("uci_housing", "housing.data")
    if not os.path.exists(path):
        return None
    data = np.loadtxt(path).astype(np.float32)
    maxs, mins, avgs = data.max(0), data.min(0), data.mean(0)
    for i in range(13):
        data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
    return data


def _make(split: str, n: int):
    def reader():
        real = _real_data()
        if real is not None:
            rows = real[:_TRAIN_N] if split == "train" else real[_TRAIN_N:]
            for row in rows:
                yield row[:13], row[13:14]
            return
        rng = rng_for("uci_housing", split)
        x = rng.randn(n, 13).astype(np.float32)
        y = (x @ _W + 0.1 * rng.randn(n)).astype(np.float32).reshape(n, 1)
        for i in range(n):
            yield x[i], y[i]
    return reader


def train():
    return _make("train", 404)


def test():
    return _make("test", 102)
