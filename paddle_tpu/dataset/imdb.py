"""IMDB movie-review sentiment (reference: python/paddle/dataset/imdb.py —
word-id sequence + binary label; word_dict built by frequency over the
aclImdb corpus). Parses the real `aclImdb_v1.tar.gz` from the cache dir
when present (reference imdb.py:36-100: tokenize, build_dict with
cutoff, pos label 0 / neg label 1); otherwise synthesizes two sentiment
word populations so understand_sentiment converges."""
import os
import re
import tarfile

import numpy as np

from .common import build_freq_dict, cache_path, rng_for

_VOCAB = 5149  # reference IMDB cutoff-150 vocab is ~5148 words + <unk>


def _real_archive():
    path = cache_path("imdb", "aclImdb_v1.tar.gz")
    return path if os.path.exists(path) else None


def tokenize(text: str):
    """Reference imdb.py:36 tokenize: lowercase word stream with
    punctuation stripped."""
    return re.findall(r"[a-z']+", text.lower())


def _real_docs(split_re):
    """Stream matching members in ARCHIVE order: a gz-backed tarfile
    re-decompresses from byte 0 on every backward seek, so sorted-name
    random access would cost O(members x archive) per epoch."""
    with tarfile.open(_real_archive(), mode="r:*") as tf:
        for m in tf:
            if m.isfile() and re.search(split_re, m.name):
                text = tf.extractfile(m).read().decode("utf-8", "replace")
                yield tokenize(text)


def word_dict(cutoff: int = 150):
    """Frequency-sorted dict over train+test with a min-count cutoff +
    trailing <unk> (reference imdb.py:60 word_dict = build_dict over
    aclImdb/(train|test)/(pos|neg), cutoff 150)."""
    path = _real_archive()
    if path:
        return build_freq_dict(
            lambda: _real_docs(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$"),
            cache_key=("imdb", path, os.path.getmtime(path), cutoff),
            cutoff=cutoff)
    return {("w%d" % i): i for i in range(_VOCAB)}


def _real_reader(split, word_idx=None):
    def reader():
        idx = word_idx or word_dict()
        unk = idx["<unk>"]
        # pos first (label 0), then neg (label 1), like the reference's
        # chained pos/neg reader creators
        for label, pol in ((0, "pos"), (1, "neg")):
            pat = rf"aclImdb/{split}/{pol}/.*\.txt$"
            for words in _real_docs(pat):
                yield [idx.get(w, unk) for w in words], label
    return reader


def _make(split, n, seq_lo=20, seq_hi=100):
    def reader():
        rng = rng_for("imdb", split)
        half = _VOCAB // 2
        active = 400  # Zipf-like active vocab per sentiment class
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(seq_lo, seq_hi))
            # positive reviews draw mostly from the upper half of the vocab
            main = rng.randint(half, half + active, length) if label else \
                rng.randint(0, active, length)
            noise_mask = rng.rand(length) < 0.1
            noise = rng.randint(0, _VOCAB, length)
            ids = np.where(noise_mask, noise, main).astype(np.int64)
            yield list(map(int, ids)), label
    return reader


def train(word_idx=None):
    if _real_archive():
        return _real_reader("train", word_idx)
    return _make("train", 2048)


def test(word_idx=None):
    if _real_archive():
        return _real_reader("test", word_idx)
    return _make("test", 256)
