"""IMDB movie-review sentiment (reference: python/paddle/dataset/imdb.py —
word-id sequence + binary label; word_dict built by frequency). Synthetic:
two sentiment word populations so understand_sentiment converges."""
import numpy as np

from .common import rng_for

_VOCAB = 5149  # reference IMDB cutoff-150 vocab is ~5148 words + <unk>


def word_dict():
    return {("w%d" % i): i for i in range(_VOCAB)}


def _make(split, n, seq_lo=20, seq_hi=100):
    def reader():
        rng = rng_for("imdb", split)
        half = _VOCAB // 2
        active = 400  # Zipf-like active vocab per sentiment class
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(seq_lo, seq_hi))
            # positive reviews draw mostly from the upper half of the vocab
            main = rng.randint(half, half + active, length) if label else \
                rng.randint(0, active, length)
            noise_mask = rng.rand(length) < 0.1
            noise = rng.randint(0, _VOCAB, length)
            ids = np.where(noise_mask, noise, main).astype(np.int64)
            yield list(map(int, ids)), label
    return reader


def train(word_idx=None):
    return _make("train", 2048)


def test(word_idx=None):
    return _make("test", 256)
