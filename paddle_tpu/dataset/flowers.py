"""Oxford-102 flowers (reference: python/paddle/dataset/flowers.py —
3x224x224 float image + label). Parses the real archive set from the
cache dir when present (reference flowers.py:40-120: `102flowers.tgz`
of jpgs, `imagelabels.mat` 1-based labels, `setid.mat` split ids);
otherwise synthesizes class-separable images."""
import io
import os
import re
import tarfile

import numpy as np

from .common import cache_path, rng_for

_N_CLASSES = 102


def _real_base():
    base = cache_path("flowers")
    need = ("102flowers.tgz", "imagelabels.mat", "setid.mat")
    return base if all(os.path.exists(os.path.join(base, f))
                       for f in need) else None


def _real_reader(setid_key):
    def reader():
        from PIL import Image
        from scipy.io import loadmat
        base = _real_base()
        labels = loadmat(os.path.join(base, "imagelabels.mat"))
        labels = np.asarray(labels["labels"]).reshape(-1)  # 1-based
        ids = loadmat(os.path.join(base, "setid.mat"))[setid_key]
        ids = set(int(i) for i in np.asarray(ids).reshape(-1))
        with tarfile.open(os.path.join(base, "102flowers.tgz"),
                          mode="r:*") as tf:
            for name in sorted(tf.getnames()):
                m = re.search(r"image_(\d+)\.jpg$", name)
                if not m or int(m.group(1)) not in ids:
                    continue
                img = Image.open(io.BytesIO(
                    tf.extractfile(name).read())).convert("RGB")
                img = img.resize((224, 224))
                arr = np.asarray(img, np.float32).transpose(2, 0, 1) / 255.0
                yield arr, int(labels[int(m.group(1)) - 1]) - 1
    return reader


def _make(split, n):
    def reader():
        rng = rng_for("flowers", "templates")
        templates = rng.rand(_N_CLASSES, 3, 8, 8).astype(np.float32)
        rng = rng_for("flowers", split)
        for _ in range(n):
            label = int(rng.randint(0, _N_CLASSES))
            base = np.kron(templates[label], np.ones((1, 28, 28),
                                                     np.float32))
            img = base + 0.1 * rng.randn(3, 224, 224).astype(np.float32)
            yield np.clip(img, 0, 1).astype(np.float32), label
    return reader


def train(mapper=None, buffered_size=None, use_xmap=None):
    if _real_base():
        return _real_reader("trnid")
    return _make("train", 512)


def test(mapper=None, buffered_size=None, use_xmap=None):
    if _real_base():
        return _real_reader("tstid")
    return _make("test", 64)


def valid(mapper=None, buffered_size=None, use_xmap=None):
    if _real_base():
        return _real_reader("valid")
    return _make("valid", 64)
