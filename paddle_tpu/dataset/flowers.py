"""Oxford-102 flowers (reference: python/paddle/dataset/flowers.py —
3x224x224 float image + label). Synthetic class-separable images."""
import numpy as np

from .common import rng_for

_N_CLASSES = 102


def _make(split, n):
    def reader():
        rng = rng_for("flowers", "templates")
        templates = rng.rand(_N_CLASSES, 3, 8, 8).astype(np.float32)
        rng = rng_for("flowers", split)
        for _ in range(n):
            label = int(rng.randint(0, _N_CLASSES))
            base = np.kron(templates[label], np.ones((1, 28, 28),
                                                     np.float32))
            img = base + 0.1 * rng.randn(3, 224, 224).astype(np.float32)
            yield np.clip(img, 0, 1).astype(np.float32), label
    return reader


def train(mapper=None, buffered_size=None, use_xmap=None):
    return _make("train", 512)


def test(mapper=None, buffered_size=None, use_xmap=None):
    return _make("test", 64)


def valid(mapper=None, buffered_size=None, use_xmap=None):
    return _make("valid", 64)
