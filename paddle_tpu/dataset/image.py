"""Image preprocessing utilities (reference: python/paddle/dataset/image.py
— load/resize/crop/flip/transform helpers used by the image-classification
pipelines). The reference uses OpenCV; this implementation uses PIL +
numpy (both baked into the environment) with the same function surface
and HWC-uint8 conventions.
"""
from __future__ import annotations

import io
import tarfile
from typing import Sequence

import numpy as np

__all__ = ["load_image", "load_image_bytes", "resize_short", "to_chw",
           "center_crop", "random_crop", "left_right_flip",
           "simple_transform", "load_and_transform",
           "batch_images_from_tar"]


def _pil():
    from PIL import Image
    return Image


def load_image_bytes(bytes_, is_color: bool = True) -> np.ndarray:
    """Decode an encoded image from memory -> HWC uint8 (or HW if gray)."""
    im = _pil().open(io.BytesIO(bytes_))
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def load_image(file: str, is_color: bool = True) -> np.ndarray:
    im = _pil().open(file)
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Scale so the SHORTER edge becomes `size`, preserving aspect."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    pim = _pil().fromarray(im)
    pim = pim.resize((nw, nh), _pil().BILINEAR)
    return np.asarray(pim)


def to_chw(im: np.ndarray, order: Sequence[int] = (2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (the layout conv2d expects)."""
    if im.ndim == 2:
        im = im[:, :, None]
    return im.transpose(tuple(order))


def center_crop(im: np.ndarray, size: int,
                is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    if h < size or w < size:
        raise ValueError(f"image {h}x{w} smaller than crop {size}")
    top = (h - size) // 2
    left = (w - size) // 2
    return im[top:top + size, left:left + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: np.random.RandomState = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    if h < size or w < size:
        raise ValueError(f"image {h}x{w} smaller than crop {size}")
    top = rng.randint(0, h - size + 1)
    left = rng.randint(0, w - size + 1)
    return im[top:top + size, left:left + size]


def left_right_flip(im: np.ndarray, is_color: bool = True) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True,
                     mean=None) -> np.ndarray:
    """resize_short -> crop (random+flip for train, center for eval) ->
    CHW float32, optionally mean-subtracted (reference: simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2):
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:  # per-channel
            if mean.shape[0] != im.shape[0]:
                raise ValueError(
                    f"per-channel mean has {mean.shape[0]} entries but "
                    f"the image has {im.shape[0]} channel(s)")
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True,
                       mean=None) -> np.ndarray:
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file: str, dataset_name: str,
                          img2label: dict, num_per_batch: int = 1024):
    """Read images from a tar, batch into .npz files next to the tar, and
    return the batch-file list path (reference: batch_images_from_tar,
    which pickles; .npz is the numpy-native equivalent)."""
    import hashlib
    import os
    # cache key covers the label map and batch size — changing either
    # must re-batch rather than serve stale batches
    key = hashlib.md5(repr((sorted(img2label.items()),
                            num_per_batch)).encode()).hexdigest()[:10]
    out_path = f"{data_file}_{dataset_name}_{key}_batch"
    meta_file = os.path.join(out_path, "batch_file_list.txt")
    if os.path.isfile(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    data, labels, files = [], [], []
    n_batch = 0

    def flush():
        nonlocal data, labels, n_batch
        if not data:
            return
        fname = os.path.join(out_path, f"batch_{n_batch}.npz")
        np.savez(fname,
                 data=np.asarray(data, dtype=object),
                 labels=np.asarray(labels))
        files.append(fname)
        data, labels = [], []
        n_batch += 1

    with tarfile.open(data_file) as tf:
        for member in tf.getmembers():
            if not member.isfile() or member.name not in img2label:
                continue
            raw = tf.extractfile(member).read()
            data.append(raw)
            labels.append(img2label[member.name])
            if len(data) == num_per_batch:
                flush()
    flush()
    with open(meta_file, "w") as f:
        f.write("\n".join(files) + "\n")
    return meta_file
