"""MovieLens-1M ratings (reference: python/paddle/dataset/movielens.py —
sample = [user_id, gender, age, job, movie_id, category_ids, title_ids,
rating]). Parses the real `ml-1m.zip` from the cache dir when present
(reference movielens.py:30-190: `::`-separated ratings/users/movies
tables, gender M/F index, age bucket index, genre + title-word dicts);
otherwise synthesizes users/movies with latent-factor ratings so
recommender_system converges."""
import os
import re
import zipfile

import numpy as np

from .common import cache_path, rng_for

_N_USERS, _N_MOVIES = 944, 1683
_N_CATEGORIES, _TITLE_VOCAB = 19, 1512
_N_AGES, _N_JOBS = 7, 21
_DIM = 8

_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


def _real_archive():
    path = cache_path("movielens", "ml-1m.zip")
    return path if os.path.exists(path) else None


_TABLES_CACHE = {}


def _real_tables():
    """(users, movies, ratings, cat_dict, title_dict) from ml-1m.zip.
    Memoized per archive: the metadata accessors and every reader epoch
    would otherwise re-parse ~1M rating lines each."""
    path = _real_archive()
    key = (path, os.path.getmtime(path))
    if key in _TABLES_CACHE:
        return _TABLES_CACHE[key]
    with zipfile.ZipFile(_real_archive()) as zf:
        def lines(suffix):
            name = next(n for n in zf.namelist() if n.endswith(suffix))
            return zf.read(name).decode("latin1").splitlines()

        users = {}
        for ln in lines("users.dat"):
            uid, gender, age, job, _zip = ln.strip().split("::")
            users[int(uid)] = (0 if gender == "M" else 1,
                               _AGE_TABLE.index(int(age)), int(job))
        cat_dict, title_dict = {}, {}
        movies = {}
        for ln in lines("movies.dat"):
            mid, title, genres = ln.strip().split("::")
            cats = []
            for g in genres.split("|"):
                cats.append(cat_dict.setdefault(g, len(cat_dict)))
            words = re.sub(r"\(\d{4}\)", "", title).lower().split()
            tids = [title_dict.setdefault(w, len(title_dict))
                    for w in words]
            movies[int(mid)] = (cats, tids)
        ratings = []
        for ln in lines("ratings.dat"):
            uid, mid, rating, _ts = ln.strip().split("::")
            ratings.append((int(uid), int(mid), float(rating)))
    _TABLES_CACHE[key] = (users, movies, ratings, cat_dict, title_dict)
    return _TABLES_CACHE[key]


def max_user_id():
    if _real_archive():
        return max(_real_tables()[0])
    return _N_USERS - 1


def max_movie_id():
    if _real_archive():
        return max(_real_tables()[1])
    return _N_MOVIES - 1


def max_job_id():
    return _N_JOBS - 1


def age_table():
    return list(_AGE_TABLE)


def movie_categories():
    if _real_archive():
        return dict(_real_tables()[3])
    return {("cat%d" % i): i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    if _real_archive():
        return dict(_real_tables()[4])
    return {("t%d" % i): i for i in range(_TITLE_VOCAB)}


def _real_reader(split):
    def reader():
        users, movies, ratings, _c, _t = _real_tables()
        # reference uses a hash-based train/test split; a deterministic
        # 1-in-10 index split keeps the same 90/10 proportions
        for i, (uid, mid, rating) in enumerate(ratings):
            in_test = (i % 10) == 9
            if in_test != (split == "test"):
                continue
            gender, age, job = users[uid]
            cats, tids = movies[mid]
            yield [uid, gender, age, job, mid, cats, tids, rating]
    return reader


def _latents():
    rng = rng_for("movielens", "latent")
    u = rng.randn(_N_USERS, _DIM).astype(np.float32)
    m = rng.randn(_N_MOVIES, _DIM).astype(np.float32)
    return u, m


def _make(split, n):
    def reader():
        u_lat, m_lat = _latents()
        rng = rng_for("movielens", split)
        meta = rng_for("movielens", "meta")
        genders = meta.randint(0, 2, _N_USERS)
        ages = meta.randint(0, _N_AGES, _N_USERS)
        jobs = meta.randint(0, _N_JOBS, _N_USERS)
        cats = [list(map(int, meta.randint(0, _N_CATEGORIES,
                                           meta.randint(1, 4))))
                for _ in range(_N_MOVIES)]
        titles = [list(map(int, meta.randint(0, _TITLE_VOCAB,
                                             meta.randint(2, 6))))
                  for _ in range(_N_MOVIES)]
        for _ in range(n):
            u = int(rng.randint(_N_USERS))
            m = int(rng.randint(_N_MOVIES))
            score = float(u_lat[u] @ m_lat[m])
            rating = float(np.clip(np.round(3.0 + score), 1, 5))
            yield [u, int(genders[u]), int(ages[u]), int(jobs[u]),
                   m, cats[m], titles[m], rating]
    return reader


def train():
    if _real_archive():
        return _real_reader("train")
    return _make("train", 8192)


def test():
    if _real_archive():
        return _real_reader("test")
    return _make("test", 1024)
