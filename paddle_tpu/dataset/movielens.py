"""MovieLens-1M ratings (reference: python/paddle/dataset/movielens.py —
sample = [user_id, gender, age, job, movie_id, category_ids, title_ids,
rating]). Synthetic users/movies with latent-factor ratings so
recommender_system converges."""
import numpy as np

from .common import rng_for

_N_USERS, _N_MOVIES = 944, 1683
_N_CATEGORIES, _TITLE_VOCAB = 19, 1512
_N_AGES, _N_JOBS = 7, 21
_DIM = 8


def max_user_id():
    return _N_USERS - 1


def max_movie_id():
    return _N_MOVIES - 1


def max_job_id():
    return _N_JOBS - 1


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def movie_categories():
    return {("cat%d" % i): i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {("t%d" % i): i for i in range(_TITLE_VOCAB)}


def _latents():
    rng = rng_for("movielens", "latent")
    u = rng.randn(_N_USERS, _DIM).astype(np.float32)
    m = rng.randn(_N_MOVIES, _DIM).astype(np.float32)
    return u, m


def _make(split, n):
    def reader():
        u_lat, m_lat = _latents()
        rng = rng_for("movielens", split)
        meta = rng_for("movielens", "meta")
        genders = meta.randint(0, 2, _N_USERS)
        ages = meta.randint(0, _N_AGES, _N_USERS)
        jobs = meta.randint(0, _N_JOBS, _N_USERS)
        cats = [list(map(int, meta.randint(0, _N_CATEGORIES,
                                           meta.randint(1, 4))))
                for _ in range(_N_MOVIES)]
        titles = [list(map(int, meta.randint(0, _TITLE_VOCAB,
                                             meta.randint(2, 6))))
                  for _ in range(_N_MOVIES)]
        for _ in range(n):
            u = int(rng.randint(_N_USERS))
            m = int(rng.randint(_N_MOVIES))
            score = float(u_lat[u] @ m_lat[m])
            rating = float(np.clip(np.round(3.0 + score), 1, 5))
            yield [u, int(genders[u]), int(ages[u]), int(jobs[u]),
                   m, cats[m], titles[m], rating]
    return reader


def train():
    return _make("train", 8192)


def test():
    return _make("test", 1024)
