"""Movie-review sentiment via NLTK corpus in the reference (reference:
python/paddle/dataset/sentiment.py). Same schema as imdb: (ids, label)."""
from . import imdb


def get_word_dict():
    return imdb.word_dict()


def train():
    return imdb._make("sentiment-train", 1024)


def test():
    return imdb._make("sentiment-test", 128)
