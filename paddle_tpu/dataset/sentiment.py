"""Movie-review sentiment via NLTK corpus in the reference (reference:
python/paddle/dataset/sentiment.py — the nltk movie_reviews corpus,
pos/neg .txt files). Parses a real extracted corpus from the cache dir
(`sentiment/movie_reviews/{pos,neg}/*.txt`) when present; otherwise
shares imdb's synthetic generator. Same schema as imdb: (ids, label)."""
import os

from . import imdb
from .common import build_freq_dict, cache_path


def _real_dir():
    base = cache_path("sentiment", "movie_reviews")
    return base if os.path.isdir(os.path.join(base, "pos")) else None


def _real_docs(polarity):
    base = _real_dir()
    d = os.path.join(base, polarity)
    for fname in sorted(os.listdir(d)):
        if fname.endswith(".txt"):
            with open(os.path.join(d, fname), encoding="utf-8",
                      errors="replace") as f:
                yield imdb.tokenize(f.read())


def get_word_dict():
    base = _real_dir()
    if base:
        return build_freq_dict(
            lambda: (words for pol in ("pos", "neg")
                     for words in _real_docs(pol)),
            cache_key=("sentiment", base, os.path.getmtime(base)))
    return imdb.word_dict()


def _real_reader(lo_frac, hi_frac):
    """The reference's nltk corpus has no split files; it slices each
    polarity's document list (sentiment.py train/test 80/20)."""
    def reader():
        idx = get_word_dict()
        unk = idx["<unk>"]
        for label, pol in ((0, "pos"), (1, "neg")):
            docs = list(_real_docs(pol))
            lo = int(len(docs) * lo_frac)
            hi = int(len(docs) * hi_frac)
            for words in docs[lo:hi]:
                yield [idx.get(w, unk) for w in words], label
    return reader


def train():
    if _real_dir():
        return _real_reader(0.0, 0.8)
    return imdb._make("sentiment-train", 1024)


def test():
    if _real_dir():
        return _real_reader(0.8, 1.0)
    return imdb._make("sentiment-test", 128)
