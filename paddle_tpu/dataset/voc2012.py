"""Pascal VOC2012 segmentation (reference: python/paddle/dataset/
voc2012.py — (image, segmentation label map) pairs). Synthetic blobs."""
import numpy as np

from .common import rng_for

_N_CLASSES = 21


def _make(split, n, hw=64):
    def reader():
        rng = rng_for("voc2012", split)
        for _ in range(n):
            img = rng.rand(3, hw, hw).astype(np.float32)
            label = np.zeros((hw, hw), np.int32)
            for _ in range(3):
                c = int(rng.randint(1, _N_CLASSES))
                x0, y0 = rng.randint(0, hw - 8, 2)
                w, h = rng.randint(4, 16, 2)
                label[y0:y0 + h, x0:x0 + w] = c
                img[:, y0:y0 + h, x0:x0 + w] += c / _N_CLASSES
            yield np.clip(img, 0, 1), label
    return reader


def train():
    return _make("train", 256)


def test():
    return _make("test", 32)


def val():
    return _make("val", 32)
