"""Pascal VOC2012 segmentation (reference: python/paddle/dataset/
voc2012.py — (image, segmentation label map) pairs). Parses the real
`VOCtrainval_11-May-2012.tar` from the cache dir when present
(reference voc2012.py:30-76: ImageSets/Segmentation split lists,
JPEGImages jpgs, SegmentationClass palette pngs); otherwise
synthesizes labeled blobs."""
import io
import os
import tarfile

import numpy as np

from .common import cache_path, rng_for

_N_CLASSES = 21


def _real_archive():
    path = cache_path("voc2012", "VOCtrainval_11-May-2012.tar")
    return path if os.path.exists(path) else None


def _real_reader(split):
    def reader():
        from PIL import Image
        with tarfile.open(_real_archive(), mode="r:*") as tf:
            members = {m.name: m for m in tf.getmembers()}

            def find(suffix):
                return next(n for n in members if n.endswith(suffix))

            ids = tf.extractfile(find(
                f"ImageSets/Segmentation/{split}.txt")).read() \
                .decode().split()
            jpeg_dir = os.path.dirname(find("JPEGImages/" + ids[0] + ".jpg"))
            seg_dir = os.path.dirname(find(
                "SegmentationClass/" + ids[0] + ".png"))
            for img_id in ids:
                img = Image.open(io.BytesIO(tf.extractfile(
                    f"{jpeg_dir}/{img_id}.jpg").read())).convert("RGB")
                arr = np.asarray(img, np.float32).transpose(2, 0, 1) / 255.0
                seg = Image.open(io.BytesIO(tf.extractfile(
                    f"{seg_dir}/{img_id}.png").read()))
                label = np.asarray(seg, np.int32)  # palette indices
                # VOC marks void/boundary pixels with palette index 255;
                # the module contract is labels in [0, 21), so void maps
                # to background — a 21-class loss would otherwise get
                # out-of-range indices that JAX clamps/zeros silently
                label = np.where(label >= _N_CLASSES, 0, label)
                yield arr, label
    return reader


def _make(split, n, hw=64):
    def reader():
        rng = rng_for("voc2012", split)
        for _ in range(n):
            img = rng.rand(3, hw, hw).astype(np.float32)
            label = np.zeros((hw, hw), np.int32)
            for _ in range(3):
                c = int(rng.randint(1, _N_CLASSES))
                x0, y0 = rng.randint(0, hw - 8, 2)
                w, h = rng.randint(4, 16, 2)
                label[y0:y0 + h, x0:x0 + w] = c
                img[:, y0:y0 + h, x0:x0 + w] += c / _N_CLASSES
            yield np.clip(img, 0, 1), label
    return reader


def train():
    if _real_archive():
        return _real_reader("train")
    return _make("train", 256)


def test():
    if _real_archive():
        return _real_reader("val")   # VOC2012 test labels are withheld;
    return _make("test", 32)         # the reference also evaluates on val


def val():
    if _real_archive():
        return _real_reader("val")
    return _make("val", 32)
