"""WMT14 fr→en translation pairs (reference: python/paddle/dataset/
wmt14.py — sample = (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk>).
Parses the real preprocessed layout from the cache dir when present
(reference wmt14.py:40-110: `src.dict`/`trg.dict` word-per-line files
with <s>/<e>/<unk> leading, and train/ test/ dirs of `src\ttrg` line
files); otherwise synthesizes invertible-mapping pairs so
machine_translation learns."""
import os

from .common import cache_path, rng_for

START, END, UNK = 0, 1, 2
_DICT = 1000  # reference default dict_size=30000; small synthetic vocab


def _real_base():
    base = cache_path("wmt14")
    return base if os.path.exists(os.path.join(base, "src.dict")) else None


def _load_dict(base, which, dict_size):
    with open(os.path.join(base, f"{which}.dict"), encoding="utf-8") as f:
        words = [ln.rstrip("\n") for ln in f if ln.strip()]
    return {w: i for i, w in enumerate(words[:dict_size])}


def _real_reader(subdir, dict_size):
    def reader():
        base = _real_base()
        src_dict = _load_dict(base, "src", dict_size)
        trg_dict = _load_dict(base, "trg", dict_size)
        d = os.path.join(base, subdir)
        for fname in sorted(os.listdir(d)):
            with open(os.path.join(d, fname), encoding="utf-8") as f:
                for line in f:
                    if "\t" not in line:
                        continue
                    src, trg = line.rstrip("\n").split("\t")[:2]
                    src_ids = [src_dict.get(w, UNK) for w in src.split()]
                    trg_ids = [trg_dict.get(w, UNK) for w in trg.split()]
                    yield (src_ids, [START] + trg_ids, trg_ids + [END])
    return reader


def _make(split, n, dict_size):
    def reader():
        rng = rng_for("wmt14", split)
        # deterministic word-to-word mapping = a learnable translation;
        # Zipf-like active vocab keeps the task learnable from a small corpus
        active = min(300, dict_size - 3)
        perm = rng_for("wmt14", "perm").permutation(dict_size - 3) + 3
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, 3 + active, length)
            trg = perm[src - 3]
            src_ids = [int(w) for w in src]
            trg_ids = [START] + [int(w) for w in trg]
            trg_next = [int(w) for w in trg] + [END]
            yield src_ids, trg_ids, trg_next
    return reader


def train(dict_size=_DICT):
    if _real_base():
        return _real_reader("train", dict_size)
    return _make("train", 4096, dict_size)


def test(dict_size=_DICT):
    if _real_base():
        return _real_reader("test", dict_size)
    return _make("test", 512, dict_size)


def get_dict(dict_size=_DICT, reverse=False):
    base = _real_base()
    if base:
        src = _load_dict(base, "src", dict_size)
        trg = _load_dict(base, "trg", dict_size)
    else:
        src = {("s%d" % i): i for i in range(dict_size)}
        trg = {("t%d" % i): i for i in range(dict_size)}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
