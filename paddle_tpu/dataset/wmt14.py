"""WMT14 fr→en translation pairs (reference: python/paddle/dataset/
wmt14.py — sample = (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk>).
Synthetic invertible-mapping pairs so machine_translation learns."""
import numpy as np

from .common import rng_for

START, END, UNK = 0, 1, 2
_DICT = 1000  # reference default dict_size=30000; small synthetic vocab


def _make(split, n, dict_size):
    def reader():
        rng = rng_for("wmt14", split)
        # deterministic word-to-word mapping = a learnable translation;
        # Zipf-like active vocab keeps the task learnable from a small corpus
        active = min(300, dict_size - 3)
        perm = rng_for("wmt14", "perm").permutation(dict_size - 3) + 3
        for _ in range(n):
            length = int(rng.randint(3, 12))
            src = rng.randint(3, 3 + active, length)
            trg = perm[src - 3]
            src_ids = [int(w) for w in src]
            trg_ids = [START] + [int(w) for w in trg]
            trg_next = [int(w) for w in trg] + [END]
            yield src_ids, trg_ids, trg_next
    return reader


def train(dict_size=_DICT):
    return _make("train", 4096, dict_size)


def test(dict_size=_DICT):
    return _make("test", 512, dict_size)


def get_dict(dict_size=_DICT, reverse=False):
    src = {("s%d" % i): i for i in range(dict_size)}
    trg = {("t%d" % i): i for i in range(dict_size)}
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg
