"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py — 3072-dim
float image in [0,1] + int label). Synthetic class-separable images."""
import numpy as np

from .common import rng_for


def _make(name, split, n, num_classes):
    def reader():
        rng = rng_for(name, "templates")
        templates = rng.rand(num_classes, 3072).astype(np.float32)
        rng = rng_for(name, split)
        labels = rng.randint(0, num_classes, n).astype(np.int64)
        images = templates[labels] + 0.2 * rng.randn(n, 3072).astype(np.float32)
        images = np.clip(images, 0, 1).astype(np.float32)
        for i in range(n):
            yield images[i], int(labels[i])
    return reader


def train10():
    return _make("cifar10", "train", 4096, 10)


def test10():
    return _make("cifar10", "test", 512, 10)


def train100():
    return _make("cifar100", "train", 4096, 100)


def test100():
    return _make("cifar100", "test", 512, 100)
