"""CIFAR-10/100 (reference: python/paddle/dataset/cifar.py — 3072-dim
float image in [0,1] + int label). Loads the real pickle-tar archives
(cifar-10-python.tar.gz / cifar-100-python.tar.gz) from the cache dir
when present (reference cifar.py:40-56 reader_creator); otherwise
synthesizes class-separable images."""
import os
import pickle
import re
import tarfile

import numpy as np

from .common import cache_path, rng_for


def _real_archive(archive: str):
    path = cache_path("cifar", f"{archive}-python.tar.gz")
    return path if os.path.exists(path) else None


def _read_real(archive, member_re, label_key):
    """Iterate the real archive: members matching `member_re` are
    pickled dicts of b'data' uint8[N,3072] and a label list."""
    with tarfile.open(_real_archive(archive), mode="r:*") as tf:
        names = sorted(n for n in tf.getnames() if re.search(member_re, n))
        for name in names:
            batch = pickle.load(tf.extractfile(name), encoding="bytes")
            data = np.asarray(batch[b"data"], np.uint8)
            data = data.astype(np.float32) / 255.0
            labels = batch[label_key]
            for i in range(len(labels)):
                yield data[i], int(labels[i])


def _make(name, split, n, num_classes):
    archive = "cifar-10" if num_classes == 10 else "cifar-100"
    if num_classes == 10:
        member_re = r"data_batch" if split == "train" else r"test_batch"
        label_key = b"labels"
    else:
        member_re = r"/train$" if split == "train" else r"/test$"
        label_key = b"fine_labels"

    def reader():
        if _real_archive(archive):
            yield from _read_real(archive, member_re, label_key)
            return
        rng = rng_for(name, "templates")
        templates = rng.rand(num_classes, 3072).astype(np.float32)
        rng = rng_for(name, split)
        labels = rng.randint(0, num_classes, n).astype(np.int64)
        images = templates[labels] + 0.2 * rng.randn(n, 3072).astype(np.float32)
        images = np.clip(images, 0, 1).astype(np.float32)
        for i in range(n):
            yield images[i], int(labels[i])
    return reader


def train10():
    return _make("cifar10", "train", 4096, 10)


def test10():
    return _make("cifar10", "test", 512, 10)


def train100():
    return _make("cifar100", "train", 4096, 100)


def test100():
    return _make("cifar100", "test", 512, 100)
