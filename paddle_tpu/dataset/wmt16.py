"""WMT16 en↔de pairs (reference: python/paddle/dataset/wmt16.py — same
(src, trg, trg_next) schema as wmt14 with configurable language pair
and frequency-built vocabularies). Parses real parallel text
(`wmt16/{split}.{en,de}` line-aligned files in the cache dir, vocab by
descending frequency under the dict-size cap with <s>/<e>/<unk> first,
reference wmt16.py:64-120); otherwise shares wmt14's synthetic
generator."""
import os

from . import wmt14
from .common import build_freq_dict, cache_path

START, END, UNK = wmt14.START, wmt14.END, wmt14.UNK


def _real_base():
    base = cache_path("wmt16")
    return base if os.path.exists(os.path.join(base, "train.en")) else None


def _lines(base, split, lang):
    with open(os.path.join(base, f"{split}.{lang}"),
              encoding="utf-8") as f:
        return [ln.rstrip("\n") for ln in f]


def _build_dict(base, lang, dict_size):
    """<s>/<e>/<unk> then words by descending train-split frequency,
    capped at dict_size (reference wmt16.py:64 __build_dict)."""
    train_path = os.path.join(base, f"train.{lang}")
    return build_freq_dict(
        lambda: (ln.split() for ln in _lines(base, "train", lang)),
        cache_key=("wmt16", train_path, os.path.getmtime(train_path),
                   dict_size),
        leading=("<s>", "<e>", "<unk>"), cap=dict_size, unk=None)


def _real_reader(split, src_dict_size, trg_dict_size, src_lang):
    trg_lang = "de" if src_lang == "en" else "en"

    def reader():
        base = _real_base()
        src_dict = _build_dict(base, src_lang, src_dict_size)
        trg_dict = _build_dict(base, trg_lang, trg_dict_size)
        src_lines = _lines(base, split, src_lang)
        trg_lines = _lines(base, split, trg_lang)
        for src, trg in zip(src_lines, trg_lines):
            if not src.strip() or not trg.strip():
                continue
            src_ids = [src_dict.get(w, UNK) for w in src.split()]
            trg_ids = [trg_dict.get(w, UNK) for w in trg.split()]
            yield (src_ids, [START] + trg_ids, trg_ids + [END])
    return reader


def train(src_dict_size=1000, trg_dict_size=1000, src_lang="en"):
    if _real_base():
        return _real_reader("train", src_dict_size, trg_dict_size,
                            src_lang)
    return wmt14._make("wmt16-train", 4096,
                       min(src_dict_size, trg_dict_size))


def test(src_dict_size=1000, trg_dict_size=1000, src_lang="en"):
    if _real_base():
        return _real_reader("test", src_dict_size, trg_dict_size,
                            src_lang)
    return wmt14._make("wmt16-test", 512,
                       min(src_dict_size, trg_dict_size))


def get_dict(lang, dict_size, reverse=False):
    base = _real_base()
    if base:
        d = _build_dict(base, lang, dict_size)
    else:
        d = {("%s%d" % (lang, i)): i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d
