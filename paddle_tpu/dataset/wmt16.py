"""WMT16 en↔de pairs (reference: python/paddle/dataset/wmt16.py — same
(src, trg, trg_next) schema as wmt14 with configurable language pair)."""
from . import wmt14
from .common import rng_for

START, END, UNK = wmt14.START, wmt14.END, wmt14.UNK


def train(src_dict_size=1000, trg_dict_size=1000, src_lang="en"):
    return wmt14._make("wmt16-train", 4096, min(src_dict_size, trg_dict_size))


def test(src_dict_size=1000, trg_dict_size=1000, src_lang="en"):
    return wmt14._make("wmt16-test", 512, min(src_dict_size, trg_dict_size))


def get_dict(lang, dict_size, reverse=False):
    d = {("%s%d" % (lang, i)): i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d
