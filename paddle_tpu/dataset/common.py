"""Dataset infrastructure.

The reference's dataset modules download public corpora into a home cache
(reference: python/paddle/dataset/common.py — DATA_HOME, download with md5
verification). This environment has no network egress, so every dataset
module here produces *deterministic synthetic data with the real schema*
(same sample structure, dtypes, vocab semantics) unless the real files are
already present under DATA_HOME, in which case they are loaded. Model code
is agnostic to which path produced the samples.
"""
from __future__ import annotations

import hashlib
import os

import numpy as np

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def cache_path(*parts) -> str:
    return os.path.join(DATA_HOME, *parts)


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def rng_for(name: str, split: str) -> np.random.RandomState:
    """Deterministic per-(dataset, split) RNG for synthetic generation."""
    seed = int.from_bytes(hashlib.sha256(
        f"{name}:{split}".encode()).digest()[:4], "little")
    return np.random.RandomState(seed)


_FREQ_DICT_CACHE: dict = {}


def build_freq_dict(docs_fn, cache_key, cutoff: int = 1,
                    leading=(), cap=None, unk="<unk>"):
    """Shared corpus-vocabulary builder (reference: the per-dataset
    build_dict functions in python/paddle/dataset/{imdb,imikolov,
    wmt16}.py all follow this shape): count words over `docs_fn()`
    (an iterable of token lists), keep those with count >= cutoff
    ranked by (-count, word), prefix `leading` specials, cap total size
    at `cap`, and append `unk` if not already present. Memoized by
    `cache_key` — readers rebuild their dicts every epoch, and a corpus
    scan is the expensive part."""
    if cache_key in _FREQ_DICT_CACHE:
        return _FREQ_DICT_CACHE[cache_key]
    freq: dict = {}
    for words in docs_fn():
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    ranked = sorted(((w, c) for w, c in freq.items() if c >= cutoff),
                    key=lambda kv: (-kv[1], kv[0]))
    words = list(leading) + [w for w, _c in ranked]
    if cap is not None:
        words = words[:cap]
    d = {w: i for i, w in enumerate(words)}
    if unk is not None and unk not in d:
        d[unk] = len(d)
    _FREQ_DICT_CACHE[cache_key] = d
    return d
