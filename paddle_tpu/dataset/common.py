"""Dataset infrastructure.

The reference's dataset modules download public corpora into a home cache
(reference: python/paddle/dataset/common.py — DATA_HOME, download with md5
verification). This environment has no network egress, so every dataset
module here produces *deterministic synthetic data with the real schema*
(same sample structure, dtypes, vocab semantics) unless the real files are
already present under DATA_HOME, in which case they are loaded. Model code
is agnostic to which path produced the samples.
"""
from __future__ import annotations

import hashlib
import os

import numpy as np

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def cache_path(*parts) -> str:
    return os.path.join(DATA_HOME, *parts)


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def rng_for(name: str, split: str) -> np.random.RandomState:
    """Deterministic per-(dataset, split) RNG for synthetic generation."""
    seed = int.from_bytes(hashlib.sha256(
        f"{name}:{split}".encode()).digest()[:4], "little")
    return np.random.RandomState(seed)
