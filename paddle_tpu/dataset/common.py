"""Dataset infrastructure.

The reference's dataset modules download public corpora into a home cache
(reference: python/paddle/dataset/common.py — DATA_HOME, download with md5
verification). This environment has no network egress, so every dataset
module here produces *deterministic synthetic data with the real schema*
(same sample structure, dtypes, vocab semantics) unless the real files are
already present under DATA_HOME, in which case they are loaded. Model code
is agnostic to which path produced the samples.
"""
from __future__ import annotations

import hashlib
import os
from typing import Callable, Optional

import numpy as np

from ..resilience import faults
from ..resilience.retry import RetryPolicy

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))

#: downloads are the classic transient-failure I/O: retry a few times
#: with jittered exponential backoff before giving up
DOWNLOAD_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.1,
                             max_delay_s=5.0)


def cache_path(*parts) -> str:
    return os.path.join(DATA_HOME, *parts)


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module: str, md5sum: Optional[str] = None,
             save_name: Optional[str] = None,
             retry: Optional[RetryPolicy] = None,
             fetch: Optional[Callable[[str, str], None]] = None) -> str:
    """Fetch `url` into DATA_HOME/<module>/ and return the cached path
    (reference: python/paddle/dataset/common.py download, rebuilt on the
    unified retry layer).

    Crash/corruption safety: the transfer writes to a `.part` file that
    is md5-verified and then atomically renamed into place, so the cache
    never contains a partial archive; a failed or interrupted attempt
    deletes its `.part` before the next retry, and a cached file that no
    longer matches `md5sum` is discarded and re-fetched rather than
    served corrupt.

    fetch(url, path): injectable transfer fn (tests, mirrors); defaults
    to urllib. retry: RetryPolicy, default `DOWNLOAD_RETRY`.
    """
    dirname = cache_path(module)
    os.makedirs(dirname, exist_ok=True)
    fname = os.path.join(dirname, save_name or url.split("/")[-1])
    if os.path.exists(fname):
        if md5sum is None or md5file(fname) == md5sum:
            return fname
        try:
            os.remove(fname)  # stale/corrupt cache entry
        except FileNotFoundError:
            pass  # a concurrent downloader already removed/replaced it

    def _fetch_once() -> str:
        # unique temp per attempt: concurrent downloaders (multiprocess
        # reader workers on a cold cache) must not interleave into one
        # shared .part file or delete each other's in-progress transfer
        import tempfile
        fd, part = tempfile.mkstemp(
            dir=dirname, prefix=os.path.basename(fname) + ".",
            suffix=".part")
        os.close(fd)
        try:
            faults.fire("dataset.download")
            if fetch is not None:
                fetch(url, part)
            else:
                import urllib.request
                urllib.request.urlretrieve(url, part)
            if md5sum is not None and md5file(part) != md5sum:
                raise IOError(
                    f"downloaded {url} fails md5 verification "
                    f"(expected {md5sum})")
            os.replace(part, fname)
        except BaseException:
            if os.path.exists(part):
                os.remove(part)
            raise
        return fname

    policy = retry if retry is not None else DOWNLOAD_RETRY
    return policy.call(_fetch_once, name="dataset.download")


def rng_for(name: str, split: str) -> np.random.RandomState:
    """Deterministic per-(dataset, split) RNG for synthetic generation."""
    seed = int.from_bytes(hashlib.sha256(
        f"{name}:{split}".encode()).digest()[:4], "little")
    return np.random.RandomState(seed)


_FREQ_DICT_CACHE: dict = {}


def build_freq_dict(docs_fn, cache_key, cutoff: int = 1,
                    leading=(), cap=None, unk="<unk>"):
    """Shared corpus-vocabulary builder (reference: the per-dataset
    build_dict functions in python/paddle/dataset/{imdb,imikolov,
    wmt16}.py all follow this shape): count words over `docs_fn()`
    (an iterable of token lists), keep those with count >= cutoff
    ranked by (-count, word), prefix `leading` specials, cap total size
    at `cap`, and append `unk` if not already present. Memoized by
    `cache_key` — readers rebuild their dicts every epoch, and a corpus
    scan is the expensive part."""
    if cache_key in _FREQ_DICT_CACHE:
        return _FREQ_DICT_CACHE[cache_key]
    freq: dict = {}
    for words in docs_fn():
        for w in words:
            freq[w] = freq.get(w, 0) + 1
    ranked = sorted(((w, c) for w, c in freq.items() if c >= cutoff),
                    key=lambda kv: (-kv[1], kv[0]))
    words = list(leading) + [w for w, _c in ranked]
    if cap is not None:
        words = words[:cap]
    d = {w: i for i, w in enumerate(words)}
    if unk is not None and unk not in d:
        d[unk] = len(d)
    _FREQ_DICT_CACHE[cache_key] = d
    return d
