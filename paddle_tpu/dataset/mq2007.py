"""MQ2007 LETOR learning-to-rank dataset (reference:
python/paddle/dataset/mq2007.py — query-grouped 46-dim feature vectors
with graded relevance 0..2; readers in pointwise / pairwise / listwise /
plain_txt formats).

Zero-egress environment: the default readers serve a deterministic
synthetic corpus with the same schema and the same four generator
formats; `load_from_text` parses the real LETOR svmlight-style format
(`<rel> qid:<id> 1:<v> 2:<v> ... #docid=...`) when a downloaded copy is
available.
"""
from __future__ import annotations

import itertools
from typing import List, Optional

import numpy as np

from .common import rng_for

FEATURE_DIM = 46
__all__ = ["Query", "QueryList", "load_from_text", "train", "test",
           "pointwise", "pairwise", "listwise", "plain_txt",
           "FEATURE_DIM"]


class Query:
    """One judged document of one query."""

    __slots__ = ("query_id", "relevance_score", "feature_vector",
                 "description")

    def __init__(self, query_id: int, relevance_score: int,
                 feature_vector, description: str = ""):
        self.query_id = int(query_id)
        self.relevance_score = int(relevance_score)
        self.feature_vector = np.asarray(feature_vector, np.float32)
        self.description = description


class QueryList:
    """All judged documents of one query id."""

    def __init__(self, query_id: int,
                 queries: Optional[List[Query]] = None):
        self.query_id = int(query_id)
        self.querylist: List[Query] = list(queries or [])

    def append(self, q: Query):
        self.querylist.append(q)

    def __len__(self):
        return len(self.querylist)

    def __iter__(self):
        return iter(self.querylist)


def load_from_text(filepath: str, shuffle: bool = False,
                   fill_missing: float = -1.0) -> List[QueryList]:
    """Parse the LETOR text format into QueryLists (reference:
    mq2007.py load_from_text)."""
    by_qid = {}
    with open(filepath) as f:
        for line in f:
            body = line.split("#", 1)[0].strip()
            if not body:
                continue
            parts = body.split()
            rel = int(parts[0])
            qid = int(parts[1].split(":", 1)[1])
            feats = np.full((FEATURE_DIM,), fill_missing, np.float32)
            for tok in parts[2:]:
                k, v = tok.split(":", 1)
                i = int(k) - 1
                if 0 <= i < FEATURE_DIM:
                    feats[i] = float(v)
            desc = line.split("#", 1)[1].strip() if "#" in line else ""
            ql = by_qid.get(qid)
            if ql is None:
                ql = by_qid[qid] = QueryList(qid)
            ql.append(Query(qid, rel, feats, description=desc))
    out = list(by_qid.values())
    if shuffle:
        np.random.shuffle(out)
    return out


def _synthetic_querylists(split: str, n_queries: int,
                          docs_per_query: int = 8) -> List[QueryList]:
    """Deterministic synthetic LETOR corpus: relevance correlates with a
    fixed linear scoring of the features, so rankers can actually learn."""
    rng = rng_for("mq2007", split)
    w = np.linspace(-1.0, 1.0, FEATURE_DIM).astype(np.float32)
    out = []
    for qid in range(n_queries):
        ql = QueryList(qid)
        x = rng.randn(docs_per_query, FEATURE_DIM).astype(np.float32)
        score = x @ w + 0.3 * rng.randn(docs_per_query)
        # graded relevance by within-query score tercile
        order = np.argsort(np.argsort(score))
        rel = (3 * order // docs_per_query).astype(int)  # 0..2
        for d in range(docs_per_query):
            ql.append(Query(qid, int(rel[d]), x[d]))
        out.append(ql)
    return out


def pointwise(querylists):
    """-> (relevance, feature_vector) per document."""
    def reader():
        for ql in querylists:
            for q in ql:
                yield q.relevance_score, q.feature_vector
    return reader


def pairwise(querylists):
    """-> (label=1, hi_features, lo_features) for each ordered pair with
    different relevance within one query (reference gen_pair)."""
    def reader():
        for ql in querylists:
            for a, b in itertools.combinations(ql, 2):
                if a.relevance_score == b.relevance_score:
                    continue
                hi, lo = (a, b) if a.relevance_score > b.relevance_score \
                    else (b, a)
                yield np.ones((1,), np.float32), hi.feature_vector, \
                    lo.feature_vector
    return reader


def plain_txt(querylists):
    """-> (query_id, relevance, feature_vector) per document (reference
    gen_plain_txt)."""
    def reader():
        for ql in querylists:
            for q in ql:
                yield ql.query_id, q.relevance_score, q.feature_vector
    return reader


def listwise(querylists):
    """-> (relevance_scores [n_docs], features [n_docs, 46]) per query."""
    def reader():
        for ql in querylists:
            rels = np.asarray([q.relevance_score for q in ql], np.float32)
            feats = np.stack([q.feature_vector for q in ql])
            yield rels, feats
    return reader


_FORMATS = {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise, "plain_txt": plain_txt}


def _reader(split: str, format: str, n_queries: int):
    if format not in _FORMATS:
        raise ValueError(f"unknown mq2007 format {format!r}; choose from "
                         f"{sorted(_FORMATS)}")
    return _FORMATS[format](_synthetic_querylists(split, n_queries))


def train(format: str = "pairwise"):
    return _reader("train", format, n_queries=120)


def test(format: str = "pairwise"):
    return _reader("test", format, n_queries=30)
