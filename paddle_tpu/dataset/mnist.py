"""MNIST digits (reference: python/paddle/dataset/mnist.py — 784-dim
float image scaled to [-1, 1] + int label). Loads the real IDX files from
the cache dir when present; otherwise synthesizes class-separable images
(per-class template + noise) so recognize_digits actually converges."""
import gzip
import os

import numpy as np

from .common import cache_path, rng_for

_N_TRAIN, _N_TEST = 8192, 1024


def _real_files(split):
    base = cache_path("mnist")
    img = os.path.join(base, f"{split}-images-idx3-ubyte.gz")
    lab = os.path.join(base, f"{split}-labels-idx1-ubyte.gz")
    return (img, lab) if os.path.exists(img) and os.path.exists(lab) else None


def _read_real(split):
    img_path, lab_path = _real_files(split)
    with gzip.open(img_path, "rb") as f:
        data = f.read()
    n = int.from_bytes(data[4:8], "big")
    images = np.frombuffer(data, np.uint8, offset=16).reshape(n, 784)
    images = images.astype(np.float32) / 127.5 - 1.0
    with gzip.open(lab_path, "rb") as f:
        ldata = f.read()
    labels = np.frombuffer(ldata, np.uint8, offset=8).astype(np.int64)
    return images, labels


def _synthetic(split, n):
    rng = rng_for("mnist", "templates")
    templates = rng.rand(10, 784).astype(np.float32) * 2 - 1
    rng = rng_for("mnist", split)
    labels = rng.randint(0, 10, n).astype(np.int64)
    images = templates[labels] + 0.3 * rng.randn(n, 784).astype(np.float32)
    return np.clip(images, -1, 1).astype(np.float32), labels


def _reader(split, n):
    def reader():
        if _real_files("train" if split == "train" else "t10k"):
            images, labels = _read_real("train" if split == "train" else "t10k")
        else:
            images, labels = _synthetic(split, n)
        for i in range(len(labels)):
            yield images[i], int(labels[i])
    return reader


def train():
    return _reader("train", _N_TRAIN)


def test():
    return _reader("test", _N_TEST)
