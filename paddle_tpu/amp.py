"""Automatic mixed precision for the TPU MXU.

Capability-equivalent of the reference's float16 support
(reference: paddle/fluid/platform/float16.h:64 — a 913-LoC software fp16
type threaded through kernels), redesigned for TPU: the natural reduced
precision is bfloat16, and instead of per-kernel fp16 code paths, a single
global/context switch makes the FLOP-dominant ops (conv, matmul) cast their
operands to bf16 while accumulating in float32 (`preferred_element_type`),
which maps each op onto a single MXU pass. Parameters, optimizer state, and
normalization statistics stay float32 — the standard master-weight recipe.

Enable per process with env PADDLE_TPU_AMP=1, or scoped:

    with paddle_tpu.amp.amp_guard():
        exe.run(main_program, ...)

(The guard must wrap the FIRST run that compiles the program — precision is
baked into the compiled executable, keyed by the amp flag in the executor's
cache key.)
"""
from __future__ import annotations

import os
from contextlib import contextmanager

import jax.numpy as jnp

_state = {"enabled": os.environ.get("PADDLE_TPU_AMP", "0") == "1"}


def amp_enabled() -> bool:
    return _state["enabled"]


def enable(flag: bool = True) -> None:
    _state["enabled"] = bool(flag)


@contextmanager
def amp_guard(enabled: bool = True):
    prev = _state["enabled"]
    _state["enabled"] = bool(enabled)
    try:
        yield
    finally:
        _state["enabled"] = prev


def amp_cast(*arrays):
    """Cast float32 operands to bfloat16 when AMP is on; pass through else.

    Only f32 is downcast — integer/bool/f64/bf16 operands are untouched, so
    ops can call this unconditionally.
    """
    if not _state["enabled"]:
        return arrays if len(arrays) > 1 else arrays[0]
    out = tuple(a.astype(jnp.bfloat16)
                if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                for a in arrays)
    return out if len(out) > 1 else out[0]
