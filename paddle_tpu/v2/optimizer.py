"""v2 optimizers (reference: python/paddle/v2/optimizer.py — wrappers
that carry the update rule + regularization/model-average settings into
the trainer). Each wraps the corresponding paddle_tpu.optimizer."""
from __future__ import annotations

from .. import optimizer as _fluid_opt


class Optimizer:
    def __init__(self, learning_rate=1e-3, regularization=None,
                 model_average=None, gradient_clipping_threshold=None,
                 learning_rate_decay_a=None, learning_rate_decay_b=None,
                 learning_rate_schedule=None, **_kw):
        self.learning_rate = learning_rate
        self.regularization = regularization
        self.model_average = model_average

    def to_fluid(self):
        raise NotImplementedError

    def _kwargs(self):
        kw = {"learning_rate": self.learning_rate}
        if self.regularization is not None:
            kw["regularization"] = self.regularization
        return kw


class Momentum(Optimizer):
    def __init__(self, momentum=0.9, sparse=False, **kw):
        super().__init__(**kw)
        self.momentum = momentum

    def to_fluid(self):
        return _fluid_opt.MomentumOptimizer(momentum=self.momentum,
                                            **self._kwargs())


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(**kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_fluid(self):
        return _fluid_opt.AdamOptimizer(beta1=self.beta1,
                                        beta2=self.beta2,
                                        epsilon=self.epsilon,
                                        **self._kwargs())


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kw):
        super().__init__(**kw)
        self.beta1, self.beta2 = beta1, beta2

    def to_fluid(self):
        return _fluid_opt.AdamaxOptimizer(beta1=self.beta1,
                                          beta2=self.beta2,
                                          **self._kwargs())


class AdaGrad(Optimizer):
    def to_fluid(self):
        return _fluid_opt.AdagradOptimizer(**self._kwargs())


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return _fluid_opt.DecayedAdagradOptimizer(
            decay=self.rho, epsilon=self.epsilon, **self._kwargs())


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return _fluid_opt.AdadeltaOptimizer(
            rho=self.rho, epsilon=self.epsilon, **self._kwargs())


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return _fluid_opt.RMSPropOptimizer(
            rho=self.rho, epsilon=self.epsilon, **self._kwargs())


__all__ = ["Optimizer", "Momentum", "Adam", "Adamax", "AdaGrad",
           "DecayedAdaGrad", "AdaDelta", "RMSProp"]
