"""v2 layer arithmetic (reference: python/paddle/v2/op.py — unary math
ops over layers plus +,-,* operator overloads on Layer).

The reference builds these from mixed/identity_projection/
slope_intercept config layers; here each lowers directly onto the one
Program engine as the equivalent fluid op (scale/elementwise_*), same
user-visible semantics: scalars fold into an affine, equal-size layers
combine elementwise, and a size-1 layer broadcasts (the reference's
repeat/scaling cases).
"""
from __future__ import annotations

from .. import layers as F
from .config_base import Layer

__all__ = []


def _unary(op_name, fn):
    def op(input, name=None):
        node = Layer(op_name, parents=[input], name=name,
                     size=getattr(input, "size", 0))
        node._build = lambda ctx: fn(input.to_var(ctx))
        return node

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


_unary("exp", lambda v: F.exp(v))
_unary("log", lambda v: F.log(v))
_unary("abs", lambda v: F.abs(v))
_unary("sigmoid", lambda v: F.sigmoid(v))
_unary("tanh", lambda v: F.tanh(v))
_unary("square", lambda v: F.square(v))
_unary("relu", lambda v: F.relu(v))
_unary("sqrt", lambda v: F.sqrt(v))
_unary("reciprocal", lambda v: F.elementwise_div(
    F.fill_constant([1], "float32", 1.0), v))
_unary("softmax", lambda v: F.softmax(v))


def _affine(input, slope=1.0, intercept=0.0):
    node = Layer("slope_intercept", parents=[input],
                 size=getattr(input, "size", 0))
    node._build = lambda ctx: F.scale(input.to_var(ctx),
                                      scale=float(slope),
                                      bias=float(intercept))
    return node


def _binary(kind, a, b, fn):
    node = Layer(kind, parents=[a, b],
                 size=max(getattr(a, "size", 0), getattr(b, "size", 0)))
    node._build = lambda ctx: fn(a.to_var(ctx), b.to_var(ctx))
    return node


def _add(self, other):
    if isinstance(other, (int, float)):
        return _affine(self, intercept=other)
    if not isinstance(other, Layer):
        raise TypeError("Layer can only be added with another Layer "
                        "or a number")
    if self.size and other.size and self.size != other.size and \
            1 not in (self.size, other.size):
        raise TypeError(
            f"Two Layers can be added only if they have equal size or "
            f"one of their sizes is 1; sizes are {self.size} and "
            f"{other.size}")
    return _binary("add", self, other, F.elementwise_add)


def _neg(self):
    return _affine(self, slope=-1.0)


def _sub(self, other):
    if isinstance(other, (int, float)):
        return _affine(self, intercept=-other)
    if not isinstance(other, Layer):
        raise TypeError("Layer can only be subtracted with another "
                        "Layer or a number")
    return _add(self, _neg(other))


def _rsub(self, other):
    return _add(_neg(self), other)


def _mul(self, other):
    if isinstance(other, (int, float)):
        return _affine(self, slope=other)
    if not isinstance(other, Layer):
        raise TypeError("Layer can only be multiplied with another "
                        "Layer or a number")
    if 1 not in (self.size, other.size):
        raise TypeError("At least one of the operands of '*' must be "
                        "a number or a Layer with size=1")
    return _binary("scaling", self, other, F.elementwise_mul)


Layer.__add__ = _add
Layer.__radd__ = _add
Layer.__neg__ = _neg
Layer.__sub__ = _sub
Layer.__rsub__ = _rsub
Layer.__mul__ = _mul
Layer.__rmul__ = _mul
