"""v2 pooling objects (reference: python/paddle/v2/pooling.py over
trainer_config_helpers/poolings.py)."""
from __future__ import annotations


class BasePool:
    fluid_name = "max"

    def __repr__(self):
        return f"pooling.{type(self).__name__}()"


class Max(BasePool):
    fluid_name = "max"


class CudnnMax(Max):
    pass


class Avg(BasePool):
    fluid_name = "avg"


class CudnnAvg(Avg):
    pass


class Sum(BasePool):
    fluid_name = "sum"


class SquareRootN(BasePool):
    fluid_name = "sqrt"


__all__ = ["Max", "CudnnMax", "Avg", "CudnnAvg", "Sum", "SquareRootN",
           "BasePool"]
