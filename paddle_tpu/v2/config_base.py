"""v2 config-graph node (reference: python/paddle/v2/config_base.py —
there a Layer wraps a trainer_config_helpers DSL call that emits
ModelConfig protobuf; here a Layer is a lightweight DAG node that
LOWERS onto the fluid-style Program builder (paddle_tpu.layers), so the
legacy layer-object API and the modern program API share one engine —
the SURVEY §0 stance that v2 is a capability surface, not a second
stack)."""
from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional

_counters = itertools.count()

# Active recurrent_group frames (layer.py pushes/pops). Every Layer
# constructed while a frame is active registers itself, so memory()
# name-links can target ANY node built inside the step — including
# secondary-output nodes (get_output of an lstm_step's cell state)
# that are not ancestors of the step's returned output.
RNN_STACK: list = []


class Layer:
    """One node of the v2 layer graph.

    name: user-visible layer name (auto-generated when omitted, in the
    reference's `__{type}_{i}__` style so param names stay readable).
    parents: input Layer nodes (the DAG edges).
    build: fn(ctx) -> fluid var; ctx maps resolved parent vars by node.
    """

    def __init__(self, type_: str, parents: Optional[List["Layer"]] = None,
                 name: Optional[str] = None,
                 build: Optional[Callable] = None, size: int = 0):
        self.type = type_
        self.name = name or f"__{type_}_{next(_counters)}__"
        self.parents = [p for p in (parents or []) if p is not None]
        self._build = build
        self.size = size
        if RNN_STACK:
            RNN_STACK[-1].setdefault("nodes", []).append(self)

    # -- graph walking -------------------------------------------------
    def ancestors(self) -> List["Layer"]:
        """All nodes reachable from self (self last), topologically
        ordered, parents before children."""
        seen: Dict[int, Layer] = {}
        order: List[Layer] = []

        def visit(node: "Layer"):
            if id(node) in seen:
                return
            seen[id(node)] = node
            for p in node.parents:
                visit(p)
            order.append(node)

        visit(self)
        return order

    def to_var(self, ctx: Dict[int, object]):
        """Resolve this node to a fluid var inside the active program
        (memoized per-build in ctx)."""
        if id(self) not in ctx:
            if self._build is None:
                raise NotImplementedError(
                    f"v2 layer {self.type!r} has no lowering")
            ctx[id(self)] = self._build(ctx)
        return ctx[id(self)]

    def __repr__(self):
        return f"<v2.Layer {self.type} {self.name!r}>"
