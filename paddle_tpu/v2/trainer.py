"""v2 SGD trainer (reference: python/paddle/v2/trainer.py:37 — combines
cost topology + Parameters + optimizer; train() pumps a reader through
forward/backward firing events; test() evaluates).

TPU-native: the topology lowers once onto Programs, the jit-compiled
Executor step runs against the Parameters' scope (so the Parameters
object the user holds IS the live state), and the event loop stays on
the host — same engine as the modern API, per SURVEY §0."""
from __future__ import annotations

from typing import Optional

import numpy as np

from . import event as v2_event
from . import optimizer as v2_optimizer
from . import parameters as v2_parameters
from .data_type import DataType, SequenceType
from .topology import Topology, build_feeder, sync_startup_state


class SGD:
    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local=True, pserver_spec=None,
                 use_etcd=True):
        if not isinstance(parameters, v2_parameters.Parameters):
            raise TypeError("parameters should be "
                            "paddle.v2.parameters.Parameters")
        if not isinstance(update_equation, v2_optimizer.Optimizer):
            raise TypeError("update equation parameter must be "
                            "paddle.v2.optimizer.Optimizer")
        import paddle_tpu as pt

        self.__topology__ = Topology(cost, extra_layers=extra_layers)
        self.__parameters__ = parameters
        self.__optimizer__ = update_equation
        self._scope = parameters.scope

        # Lower WITH the optimizer appended; sync any state the
        # trainer's startup creates (optimizer accumulators, BN stats)
        # into the parameters scope without clobbering values the user
        # already holds (reference: Parameters.append_gradient_machine
        # copies user arrays INTO the machine).
        self._main, startup, self._fetches = \
            self.__topology__.programs(optimizer=update_equation)
        parameters.adopt(self._main)
        sync_startup_state(self._scope, startup)
        self._exe = pt.Executor()
        # fetch the LOWERED var (node names are v2-graph names; the
        # fluid vars carry their own auto names)
        self._cost_var = self._fetches[self.__topology__.outputs[0].name]
        self._test_prog = None  # memoized forward-only lowering

    # -- feeding ------------------------------------------------------
    def _feeder(self, feeding: Optional[dict]):
        return build_feeder(self.__topology__, self._main, feeding)

    # -- the event loop (reference trainer.py:137) --------------------
    def train(self, reader, num_passes=1, event_handler=None,
              feeding=None):
        event_handler = event_handler or (lambda e: None)
        feeder = self._feeder(feeding)
        batch_id_total = 0
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            costs = []
            for batch_id, batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                feed = feeder.feed(batch)
                (cost,) = self._exe.run(self._main, feed=feed,
                                        fetch_list=[self._cost_var],
                                        scope=self._scope)
                cost = float(np.asarray(cost).ravel()[0])
                costs.append(cost)
                event_handler(v2_event.EndIteration(
                    pass_id, batch_id, cost, metrics={}))
                batch_id_total += 1
            event_handler(v2_event.EndPass(
                pass_id, metrics={"cost": float(np.mean(costs))
                                  if costs else float("nan")}))

    def test(self, reader, feeding=None) -> v2_event.TestResult:
        """Average cost over the reader WITHOUT updating parameters:
        evaluates through a forward-only, inference-mode lowering (BN
        moving stats, dropout identity) of the same topology against
        the same scope. The lowering is built once and memoized —
        per-pass test() calls must not retrace/recompile."""
        if self._test_prog is None:
            self._test_prog = self.__topology__.programs(is_test=True)
        main, _startup, fetches = self._test_prog
        cost_var = fetches[self.__topology__.outputs[0].name]
        feeder = self._feeder(feeding)
        costs, weights = [], []
        for batch in reader():
            feed = feeder.feed(batch)
            (cost,) = self._exe.run(main, feed=feed,
                                    fetch_list=[cost_var],
                                    scope=self._scope)
            costs.append(float(np.asarray(cost).ravel()[0]))
            weights.append(len(batch))
        avg = (float(np.average(costs, weights=weights))
               if costs else float("nan"))
        return v2_event.TestResult(cost=avg)

    def save_parameter_to_tar(self, f) -> None:
        self.__parameters__.to_tar(f)
