"""v2 composite networks (reference:
python/paddle/v2/networks.py over trainer_config_helpers/networks.py —
the handful of compositions v2 demos actually use)."""
from __future__ import annotations

from . import layer
from . import pooling as _pooling
from .activation import Relu, Sigmoid, Tanh


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, num_channel=None,
                         num_channels=None, padding=0, name=None,
                         pool_type=None, **_kw):
    conv = layer.img_conv(input=input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channels or num_channel,
                          padding=padding, act=act or Relu(),
                          name=name and f"{name}_conv")
    return layer.img_pool(input=conv, pool_size=pool_size,
                          stride=pool_stride,
                          pool_type=pool_type or _pooling.Max(),
                          name=name and f"{name}_pool")


def img_conv_group(input, conv_num_filter, conv_filter_size=3,
                   pool_size=2, pool_stride=2, conv_act=None,
                   conv_padding=1, conv_batchnorm=False,
                   num_channels=None, pool_type=None, **_kw):
    tmp = input
    channels = num_channels
    for nf in conv_num_filter:
        tmp = layer.img_conv(input=tmp, filter_size=conv_filter_size,
                             num_filters=nf, num_channels=channels,
                             padding=conv_padding,
                             act=None if conv_batchnorm
                             else (conv_act or Relu()))
        if conv_batchnorm:
            tmp = layer.batch_norm(input=tmp,
                                   act=conv_act or Relu())
        channels = None
    return layer.img_pool(input=tmp, pool_size=pool_size,
                          stride=pool_stride,
                          pool_type=pool_type or _pooling.Max())


def simple_lstm(input, size, reverse=False, act=None, gate_act=None,
                state_act=None, name=None, **_kw):
    """fc(4*size) + lstmemory — the reference simple_lstm pairing."""
    proj = layer.fc(input=input, size=size * 4, bias_attr=False,
                    name=name and f"{name}_proj")
    return layer.lstmemory(input=proj, reverse=reverse, act=act,
                           gate_act=gate_act, state_act=state_act,
                           name=name)


def bidirectional_lstm(input, size, return_seq=True, name=None, **_kw):
    fwd = simple_lstm(input, size, reverse=False,
                      name=name and f"{name}_fw")
    bwd = simple_lstm(input, size, reverse=True,
                      name=name and f"{name}_bw")
    if return_seq:
        return layer.concat(input=[fwd, bwd])
    return layer.concat(input=[layer.last_seq(fwd),
                               layer.first_seq(bwd)])


def simple_gru(input, size, reverse=False, act=None, gate_act=None,
               name=None, **_kw):
    proj = layer.fc(input=input, size=size * 3, bias_attr=False,
                    name=name and f"{name}_proj")
    return layer.gru(input=proj, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, name=name)


def sequence_conv_pool(input, context_len, hidden_size, act=None,
                       pool_type=None, name=None, **_kw):
    """Context-window sequence convolution + sequence pooling
    (reference text conv: context_projection + fc + pooling; lowered
    onto layers.sequence_conv, which slides a context_len window over
    the ragged sequence)."""
    from .. import layers as F
    from .activation import act_name
    from .config_base import Layer as _Node

    (inp,) = [input] if not isinstance(input, (list, tuple)) else input
    conv = _Node("sequence_conv", parents=[inp],
                 name=name and f"{name}_conv")

    def build(ctx):
        return F.sequence_conv(inp.to_var(ctx),
                               num_filters=hidden_size,
                               filter_size=context_len,
                               act=act_name(act or Tanh()) or None)

    conv._build = build
    return layer.pooling(input=conv,
                         pooling_type=pool_type or _pooling.Max(),
                         name=name)


__all__ = ["simple_img_conv_pool", "img_conv_group", "simple_lstm",
           "bidirectional_lstm", "simple_gru", "sequence_conv_pool"]
