"""v2 composite networks (reference:
python/paddle/v2/networks.py over trainer_config_helpers/networks.py —
the handful of compositions v2 demos actually use)."""
from __future__ import annotations

from . import layer
from . import pooling as _pooling
from .activation import Relu, Sigmoid, Tanh


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, num_channel=None,
                         num_channels=None, padding=0, name=None,
                         pool_type=None, **_kw):
    conv = layer.img_conv(input=input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channels or num_channel,
                          padding=padding, act=act or Relu(),
                          name=name and f"{name}_conv")
    return layer.img_pool(input=conv, pool_size=pool_size,
                          stride=pool_stride,
                          pool_type=pool_type or _pooling.Max(),
                          name=name and f"{name}_pool")


def img_conv_group(input, conv_num_filter, conv_filter_size=3,
                   pool_size=2, pool_stride=2, conv_act=None,
                   conv_padding=1, conv_batchnorm=False,
                   num_channels=None, pool_type=None, **_kw):
    tmp = input
    channels = num_channels
    for nf in conv_num_filter:
        tmp = layer.img_conv(input=tmp, filter_size=conv_filter_size,
                             num_filters=nf, num_channels=channels,
                             padding=conv_padding,
                             act=None if conv_batchnorm
                             else (conv_act or Relu()))
        if conv_batchnorm:
            tmp = layer.batch_norm(input=tmp,
                                   act=conv_act or Relu())
        channels = None
    return layer.img_pool(input=tmp, pool_size=pool_size,
                          stride=pool_stride,
                          pool_type=pool_type or _pooling.Max())


def simple_lstm(input, size, reverse=False, act=None, gate_act=None,
                state_act=None, name=None, **_kw):
    """fc(4*size) + lstmemory — the reference simple_lstm pairing."""
    proj = layer.fc(input=input, size=size * 4, bias_attr=False,
                    name=name and f"{name}_proj")
    return layer.lstmemory(input=proj, reverse=reverse, act=act,
                           gate_act=gate_act, state_act=state_act,
                           name=name)


def bidirectional_lstm(input, size, return_seq=True, name=None, **_kw):
    fwd = simple_lstm(input, size, reverse=False,
                      name=name and f"{name}_fw")
    bwd = simple_lstm(input, size, reverse=True,
                      name=name and f"{name}_bw")
    if return_seq:
        return layer.concat(input=[fwd, bwd])
    return layer.concat(input=[layer.last_seq(fwd),
                               layer.first_seq(bwd)])


def simple_gru(input, size, reverse=False, act=None, gate_act=None,
               name=None, **_kw):
    proj = layer.fc(input=input, size=size * 3, bias_attr=False,
                    name=name and f"{name}_proj")
    return layer.gru(input=proj, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, name=name)


def sequence_conv_pool(input, context_len, hidden_size, act=None,
                       pool_type=None, name=None, **_kw):
    """Context-window sequence convolution + sequence pooling
    (reference text conv: context_projection + fc + pooling; lowered
    onto layers.sequence_conv, which slides a context_len window over
    the ragged sequence)."""
    from .. import layers as F
    from .activation import act_name
    from .config_base import Layer as _Node

    (inp,) = [input] if not isinstance(input, (list, tuple)) else input
    conv = _Node("sequence_conv", parents=[inp],
                 name=name and f"{name}_conv")

    def build(ctx):
        return F.sequence_conv(inp.to_var(ctx),
                               num_filters=hidden_size,
                               filter_size=context_len,
                               act=act_name(act or Tanh()) or None)

    conv._build = build
    return layer.pooling(input=conv,
                         pooling_type=pool_type or _pooling.Max(),
                         name=name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     pool_stride=None, act=None, num_channels=None,
                     conv_padding=0, pool_type=None, name=None, **_kw):
    """conv -> batch_norm -> pool (reference: img_conv_bn_pool,
    trainer_config_helpers/networks.py:231)."""
    conv = layer.img_conv(input=input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channels,
                          padding=conv_padding, act=None,
                          name=name and f"{name}_conv")
    bn = layer.batch_norm(input=conv, act=act or Relu(),
                          name=name and f"{name}_bn")
    return layer.img_pool(input=bn, pool_size=pool_size,
                          stride=pool_stride or pool_size,
                          pool_type=pool_type or _pooling.Max(),
                          name=name and f"{name}_pool")


def img_separable_conv(input, num_channels, num_out_channels,
                       filter_size, stride=1, padding=0, act=None,
                       name=None, **_kw):
    """Depthwise conv (groups == channels) + 1x1 pointwise conv
    (reference: img_separable_conv, networks.py:439)."""
    depthwise = layer.img_conv(input=input, filter_size=filter_size,
                               num_filters=num_channels,
                               num_channels=num_channels,
                               groups=num_channels, stride=stride,
                               padding=padding, act=None,
                               name=name and f"{name}_dw")
    return layer.img_conv(input=depthwise, filter_size=1,
                          num_filters=num_out_channels,
                          num_channels=num_channels, act=act,
                          name=name and f"{name}_pw")


def small_vgg(input_image, num_channels, num_classes):
    """The CIFAR-sized VGG of the reference demos (networks.py:517)."""
    tmp = input_image
    channels = num_channels
    for i, nf in enumerate((64, 128, 256, 512)):
        reps = 2 if i < 2 else 3
        tmp = img_conv_group(input=tmp, conv_num_filter=[nf] * reps,
                             num_channels=channels,
                             conv_batchnorm=True)
        channels = None
    from .activation import Softmax
    tmp = layer.dropout(input=tmp, dropout_rate=0.5)
    tmp = layer.fc(input=tmp, size=512, act=None)
    tmp = layer.batch_norm(input=tmp, act=Relu())
    tmp = layer.dropout(input=tmp, dropout_rate=0.5)
    return layer.fc(input=tmp, size=num_classes, act=Softmax())


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """VGG-16 (reference: networks.py:547)."""
    from .activation import Softmax
    tmp = input_image
    channels = num_channels
    for i, nf in enumerate((64, 128, 256, 512, 512)):
        reps = 2 if i < 2 else 3
        tmp = img_conv_group(input=tmp, conv_num_filter=[nf] * reps,
                             num_channels=channels)
        channels = None
    for _ in range(2):
        tmp = layer.fc(input=tmp, size=4096, act=Relu())
        tmp = layer.dropout(input=tmp, dropout_rate=0.5)
    return layer.fc(input=tmp, size=num_classes, act=Softmax())


# ---------------------------------------------------------------------
# step-level recurrent units/groups (reference: lstmemory_unit:717,
# lstmemory_group:836, gru_unit:940, gru_group:1002, simple_gru2:1163,
# bidirectional_gru:1226) — built on recurrent_group's name-linked
# memory machinery
# ---------------------------------------------------------------------

import itertools as _it

_unit_ids = _it.count()


def lstmemory_unit(input, size, name=None, act=None, gate_act=None,
                   state_act=None, param_attr=None, **_kw):
    """One LSTM step for use INSIDE a recurrent_group step function:
    declares h/c memories, projects [x, h_prev] to 4*size gates, and
    links the next h/c by name (reference: lstmemory_unit)."""
    nm = name or f"__lstm_unit_{next(_unit_ids)}__"
    h_mem = layer.memory(name=f"{nm}_h", size=size)
    c_mem = layer.memory(name=f"{nm}_c", size=size)
    gates = layer.fc(input=[input, h_mem], size=size * 4,
                     param_attr=param_attr, name=f"{nm}_gates")
    h = layer.lstm_step(input=gates, state=c_mem, name=f"{nm}_h",
                        act=act, gate_act=gate_act,
                        state_act=state_act)
    layer.get_output(input=h, arg_name="state", name=f"{nm}_c")
    return h


def lstmemory_group(input, size, name=None, act=None, gate_act=None,
                    state_act=None, reverse=False, **_kw):
    """recurrent_group over lstmemory_unit (reference:
    lstmemory_group)."""
    nm = name or f"__lstm_group_{next(_unit_ids)}__"

    def step(x):
        return lstmemory_unit(input=x, size=size, name=f"{nm}_unit",
                              act=act, gate_act=gate_act,
                              state_act=state_act)

    return layer.recurrent_group(step=step, input=input,
                                 reverse=reverse, name=nm)


def gru_unit(input, size=None, name=None, act=None, gate_act=None,
             param_attr=None, **_kw):
    """One GRU step for use inside a recurrent_group step (reference:
    gru_unit): input already carries the 3*size projection."""
    if not size:
        raise ValueError("gru_unit needs `size` (the hidden width the "
                         "step memory is declared with)")
    nm = name or f"__gru_unit_{next(_unit_ids)}__"
    h_mem = layer.memory(name=f"{nm}_h", size=size)
    return layer.gru_step(input=input, output_mem=h_mem, size=size,
                          act=act, gate_act=gate_act,
                          param_attr=param_attr, name=f"{nm}_h")


def gru_group(input, size=None, name=None, act=None, gate_act=None,
              reverse=False, **_kw):
    nm = name or f"__gru_group_{next(_unit_ids)}__"

    def step(x):
        return gru_unit(input=x, size=size, name=f"{nm}_unit",
                        act=act, gate_act=gate_act)

    return layer.recurrent_group(step=step, input=input,
                                 reverse=reverse, name=nm)


def simple_gru2(input, size, name=None, act=None, gate_act=None,
                reverse=False, **_kw):
    """fc(3*size) + gru_group (reference simple_gru2 — the
    step-composed variant of simple_gru)."""
    proj = layer.fc(input=input, size=size * 3, bias_attr=False,
                    name=name and f"{name}_proj")
    return gru_group(input=proj, size=size, name=name, act=act,
                     gate_act=gate_act, reverse=reverse)


def bidirectional_gru(input, size, return_seq=True, name=None, **_kw):
    fwd = simple_gru(input, size, reverse=False,
                     name=name and f"{name}_fw")
    bwd = simple_gru(input, size, reverse=True,
                     name=name and f"{name}_bw")
    if return_seq:
        return layer.concat(input=[fwd, bwd])
    return layer.concat(input=[layer.last_seq(fwd),
                               layer.first_seq(bwd)])


# ---------------------------------------------------------------------
# attention (reference: simple_attention:1400,
# dot_product_attention:1498, multi_head_attention:1580)
# ---------------------------------------------------------------------

def _node(type_, parents, build, name=None):
    from .config_base import Layer as _Layer
    return _Layer(type_, parents=parents, name=name, build=build)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     name=None, **_kw):
    """Bahdanau-style additive attention: project the decoder state to
    the encoder-projection width (learned, as the reference's
    full_matrix_projection does — so differing state/proj sizes work),
    score each step, softmax within the sequence, weighted sum
    (reference: simple_attention, networks.py:1400)."""
    from .. import layers as F

    def build_w(ctx):
        proj_var = encoded_proj.to_var(ctx)
        state_proj = F.fc(decoder_state.to_var(ctx),
                          size=int(proj_var.shape[-1]),
                          bias_attr=False)
        return state_proj

    state_node = _node("attention_state_proj",
                       [encoded_proj, decoder_state], build_w,
                       name=name and f"{name}_sp")
    expanded = layer.expand(input=state_node, expand_as=encoded_proj)
    both = layer.addto(input=[encoded_proj, expanded], act=Tanh())

    def build_scores(ctx):
        scores = F.fc(both.to_var(ctx), size=1, bias_attr=False)
        return F.sequence_softmax(scores)

    weights = _node("attention_weight", [both], build_scores,
                    name=name and f"{name}_w")
    scaled = layer.scaling(weight=weights, input=encoded_sequence)
    return layer.pooling(input=scaled, pooling_type=_pooling.Sum(),
                         name=name)


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None, **_kw):
    """score = <encoder_step, state> through a learned scalar scale
    (softmax_param_attr names/initializes it, honoring the reference
    signature); softmax; weighted sum of the attended sequence
    (reference: dot_product_attention, networks.py:1498)."""
    from .. import layers as F
    from .layer import _pattr

    expanded = layer.expand(input=transformed_state,
                            expand_as=encoded_sequence)
    nm = name or "dot_product_attention"

    def build_s(ctx):
        prod = F.elementwise_mul(encoded_sequence.to_var(ctx),
                                 expanded.to_var(ctx))
        s = F.reduce_sum(prod, dim=-1, keep_dim=True)
        s = F.fc(s, size=1, bias_attr=False,
                 param_attr=_pattr(softmax_param_attr, f"{nm}.w0"))
        return F.sequence_softmax(s)

    scores = _node("dot_scores", [encoded_sequence, expanded], build_s,
                   name=name and f"{name}_scores")
    scaled = layer.scaling(weight=scores, input=attended_sequence)
    return layer.pooling(input=scaled, pooling_type=_pooling.Sum(),
                         name=name)


def multi_head_attention(query, key, value, head_num, name=None,
                         **_kw):
    """Multi-head attention over RAGGED sequence q/k/v (reference:
    multi_head_attention, networks.py:1580 — the reference's inputs
    are sequences too; attention runs within each sequence's valid
    steps, per sample, never across the batch). One fused ragged op
    (ops 'multihead_seq_attention') keeps the padding masking exact;
    the modern dense transformer path lives in models/transformer.py."""
    from .. import layers as F
    from .layer import _raw_op

    node = _node("multi_head_attention", [query, key, value], None,
                 name=name)
    nm = node.name

    def build(ctx):
        q = query.to_var(ctx)
        k = key.to_var(ctx)
        v = value.to_var(ctx)
        d = int(q.shape[-1])
        if d % head_num:
            raise ValueError(f"d_model {d} not divisible by "
                             f"{head_num} heads")
        ws = {s: F.create_parameter([d, d], "float32",
                                    name=f"{nm}.{s.lower()}")
              for s in ("WQ", "WK", "WV", "WO")}
        return _raw_op("multihead_seq_attention",
                       {"Q": q, "K": k, "V": v, **ws},
                       attrs={"num_heads": head_num},
                       lod_out=("Out",))["Out"]

    node._build = build
    return node


def inputs(layers_, *args):
    """Legacy config marker (reference networks.py:1707): declares the
    data order. The TPU-native Topology derives feeding order from the
    graph, so this is a pass-through kept for script compatibility."""
    return None


def outputs(layers_, *args):
    """Legacy output marker (reference networks.py:1725): in v2 the
    output layers are whatever you hand to Topology/infer — returns
    the input unchanged for script compatibility."""
    return layers_


__all__ = ["simple_img_conv_pool", "img_conv_group", "simple_lstm",
           "bidirectional_lstm", "simple_gru", "sequence_conv_pool",
           "img_conv_bn_pool", "img_separable_conv", "small_vgg",
           "vgg_16_network", "lstmemory_unit", "lstmemory_group",
           "gru_unit", "gru_group", "simple_gru2", "bidirectional_gru",
           "simple_attention", "dot_product_attention",
           "multi_head_attention", "inputs", "outputs"]
