"""v2 activation objects (reference: python/paddle/v2/activation.py over
trainer_config_helpers/activations.py). Each maps onto the fluid-style
activation name the op library serves."""
from __future__ import annotations


class BaseActivation:
    fluid_name: str = ""          # "" = identity

    def __repr__(self):
        return f"activation.{type(self).__name__}()"


class Linear(BaseActivation):
    fluid_name = ""


Identity = Linear


class Sigmoid(BaseActivation):
    fluid_name = "sigmoid"


class Tanh(BaseActivation):
    fluid_name = "tanh"


class Relu(BaseActivation):
    fluid_name = "relu"


class BRelu(BaseActivation):
    fluid_name = "brelu"


class SoftRelu(BaseActivation):
    fluid_name = "soft_relu"


class STanh(BaseActivation):
    fluid_name = "stanh"


class Softmax(BaseActivation):
    fluid_name = "softmax"


class SequenceSoftmax(BaseActivation):
    fluid_name = "sequence_softmax"


class Abs(BaseActivation):
    fluid_name = "abs"


class Square(BaseActivation):
    fluid_name = "square"


class Exp(BaseActivation):
    fluid_name = "exp"


class Log(BaseActivation):
    fluid_name = "log"


class SquareRoot(BaseActivation):
    fluid_name = "sqrt"


class Reciprocal(BaseActivation):
    fluid_name = "reciprocal"


def act_name(act) -> str:
    """Activation object (or None) -> fluid act string ('' = none)."""
    if act is None:
        return ""
    if isinstance(act, str):
        return act
    return act.fluid_name


__all__ = ["BaseActivation", "Linear", "Identity", "Sigmoid", "Tanh",
           "Relu", "BRelu", "SoftRelu", "STanh", "Softmax",
           "SequenceSoftmax", "Abs", "Square", "Exp", "Log",
           "SquareRoot", "Reciprocal"]
