"""v2 training events (reference: python/paddle/v2/event.py). The
names and fields v2 event handlers switch on."""
from __future__ import annotations

from ..trainer import (BeginIteration, BeginPass, EndIteration,  # noqa: F401
                       EndPass)


class TestResult:
    """Result of trainer.test() (reference event.py TestResult)."""

    def __init__(self, evaluator=None, cost=None, metrics=None):
        self.evaluator = evaluator
        self.cost = cost
        self.metrics = metrics or {}


__all__ = ["BeginPass", "EndPass", "BeginIteration", "EndIteration",
           "TestResult"]
