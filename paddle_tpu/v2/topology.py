"""v2 Topology (reference: python/paddle/v2/topology.py:27 — wraps the
ModelConfig proto parsed from the layer graph; data_layers()/data_type()
drive feeding and serialize_for_inference feeds the C inference path).

TPU-native: the topology owns the LOWERING of the v2 layer DAG onto
fluid-style Programs (one engine, SURVEY §0); proto() returns the
ModelConfig-shaped summary and serialize_for_inference emits the same
PTIR + params artifact the modern io.save_inference_model produces."""
from __future__ import annotations

import json
from typing import Dict, List, Optional

from .config_base import Layer


def _listify(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Topology:
    def __init__(self, layers, extra_layers=None):
        self.outputs: List[Layer] = _listify(layers)
        self.extra: List[Layer] = _listify(extra_layers)
        for lay in self.outputs + self.extra:
            if not isinstance(lay, Layer):
                raise ValueError(
                    f"Topology expects v2 config_base.Layer nodes, got "
                    f"{type(lay).__name__}")

    # -- graph ---------------------------------------------------------
    def nodes(self) -> List[Layer]:
        seen: Dict[int, Layer] = {}
        order: List[Layer] = []
        for out in self.outputs + self.extra:
            for n in out.ancestors():
                if id(n) not in seen:
                    seen[id(n)] = n
                    order.append(n)
        return order

    def data_layers(self) -> List[Layer]:
        return [n for n in self.nodes() if n.type == "data"]

    def data_type(self):
        """[(name, InputType)] in feeding order (reference
        topology.py:118)."""
        return [(d.name, d.data_type) for d in self.data_layers()]

    def get_layer(self, name: str) -> Layer:
        for n in self.nodes():
            if n.name == name:
                return n
        raise ValueError(f"no layer named {name!r} in topology")

    # -- lowering ------------------------------------------------------
    def programs(self, optimizer=None, is_test=False):
        """Lower the DAG into fresh (main, startup) Programs; returns
        (main, startup, {layer_name: fluid var}) for the outputs and
        data layers. `optimizer` (a v2 optimizer.Optimizer) appends its
        update pass on the FIRST output (the cost). is_test=True flips
        train-mode ops to inference (BN moving stats, dropout identity)
        via the program-level inference_optimize transform — the same
        mechanism save_inference_model uses."""
        import paddle_tpu as pt
        from ..framework import isolated_name_scope

        main, startup = pt.Program(), pt.Program()
        ctx: Dict[int, object] = {}
        fetches: Dict[str, object] = {}
        # isolated_name_scope: every lowering of this topology (train /
        # test / infer programs) must produce IDENTICAL auto param
        # names, or they could not share one Parameters scope
        with pt.program_guard(main, startup), isolated_name_scope():
            for node in self.outputs + self.extra:
                fetches[node.name] = node.to_var(ctx)
            for d in self.data_layers():
                fetches[d.name] = d.to_var(ctx)
            if optimizer is not None:
                cost_var = fetches[self.outputs[0].name]
                optimizer.to_fluid().minimize(cost_var)
        if is_test:
            main = main.inference_optimize()
        return main, startup, fetches

    # -- artifacts -----------------------------------------------------
    def proto(self) -> dict:
        """ModelConfig-shaped summary of the lowered graph."""
        main, _s, _f = self.programs()
        return {
            "layers": [{"name": n.name, "type": n.type,
                        "inputs": [p.name for p in n.parents]}
                       for n in self.nodes()],
            "parameters": [{"name": p.name, "shape": list(p.shape)}
                           for p in main.all_parameters()],
            "input_layer_names": [d.name for d in self.data_layers()],
            "output_layer_names": [o.name for o in self.outputs],
        }

    def serialize_for_inference(self, stream) -> None:
        """Write the proto summary as JSON (reference writes the binary
        ModelConfig; the PTIR+params inference artifact itself comes
        from io.save_inference_model on the lowered program)."""
        stream.write(json.dumps(self.proto()).encode())


def sync_startup_state(scope, startup) -> None:
    """Run `startup` into a scratch scope and copy every name the
    target scope lacks (optimizer accumulators, BN stats) — without
    clobbering values the user already holds (reference:
    Parameters.append_gradient_machine copies user arrays INTO the
    machine). Shared by trainer.SGD and inference.Inference."""
    import paddle_tpu as pt
    from ..core.scope import Scope

    tmp = Scope()
    pt.Executor().run(startup, scope=tmp)
    for name in list(tmp.local_names()):
        if not scope.has(name):
            scope.set(name, tmp.get(name))


def build_feeder(topology: Topology, main_program, feeding=None):
    """DataFeeder over the topology's data layers, reordered by the v2
    `feeding` dict ({name: sample_index}) when given."""
    from ..data_feeder import DataFeeder

    data_layers = topology.data_layers()
    if feeding:
        by_index = sorted((idx, name) for name, idx in feeding.items())
        order = {d.name: d for d in data_layers}
        data_layers = [order[n] for _i, n in by_index if n in order]
    block = main_program.global_block()
    return DataFeeder([block.var(d.name) for d in data_layers])
