"""v2 evaluators (reference: python/paddle/v2/evaluator.py over the
DSL's evaluator_base — attachable metric nodes). The facade exposes the
two that v2 demos use as extra_layers; each returns a config node
computing the metric in-graph."""
from __future__ import annotations

from .. import layers as F
from .config_base import Layer


def classification_error_evaluator(input, label, name=None, **_kw):
    """1 - accuracy of an (already softmaxed) output vs int labels."""
    node = Layer("classification_error_evaluator",
                 parents=[input, label], name=name)

    def build(ctx):
        acc = F.accuracy(input=input.to_var(ctx),
                         label=label.to_var(ctx))
        return F.elementwise_sub(
            F.fill_constant([1], "float32", 1.0), acc)

    node._build = build
    return node


def auc_evaluator(input, label, name=None, **_kw):
    node = Layer("auc_evaluator", parents=[input, label], name=name)

    def build(ctx):
        auc, _states = F.auc(input.to_var(ctx), label.to_var(ctx))
        return auc

    node._build = build
    return node


__all__ = ["classification_error_evaluator", "auc_evaluator"]
