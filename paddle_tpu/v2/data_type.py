"""v2 input types (reference: python/paddle/v2/data_type.py re-exports
trainer/PyDataProvider2.py's InputType constructors). Each describes one
data layer's per-sample value; the trainer's DataFeeder uses it to
assemble batches (dense -> [b, dim] arrays, sequences -> ragged)."""
from __future__ import annotations


class SequenceType:
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class DataType:
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class InputType:
    def __init__(self, dim, seq_type, type_):
        self.dim = dim
        self.seq_type = seq_type
        self.type = type_

    def __repr__(self):
        return (f"InputType(dim={self.dim}, seq={self.seq_type}, "
                f"type={self.type})")


def dense_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def dense_vector_sequence(dim):
    return dense_vector(dim, SequenceType.SEQUENCE)


def dense_array(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def sparse_binary_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, SequenceType.SEQUENCE)


def sparse_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)


def sparse_vector_sequence(dim):
    return sparse_vector(dim, SequenceType.SEQUENCE)


def integer_value(value_range, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(value_range, seq_type, DataType.Index)


def integer_value_sequence(value_range):
    return integer_value(value_range, SequenceType.SEQUENCE)


def integer_value_sub_sequence(value_range):
    return integer_value(value_range, SequenceType.SUB_SEQUENCE)


__all__ = ["InputType", "SequenceType", "DataType", "dense_vector",
           "dense_vector_sequence", "dense_array",
           "sparse_binary_vector", "sparse_binary_vector_sequence",
           "sparse_vector", "sparse_vector_sequence", "integer_value",
           "integer_value_sequence", "integer_value_sub_sequence"]
