"""v2 layer-object API (reference: python/paddle/v2/layer.py, which
re-exports the trainer_config_helpers DSL as graph-building functions
returning config_base.Layer nodes; Topology walks them and a C++
GradientMachine executes the emitted ModelConfig).

TPU-native realization: each function returns a config_base.Layer whose
`build` lowers onto the fluid-style Program builder (paddle_tpu.layers)
— one op library and one XLA execution engine serve both API
generations (SURVEY §0; the 103-type vocabulary parity is audited by
tests/test_v2_layer_surface.py, and this module makes the most-used
subset RUNNABLE as real v2 layer objects)."""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from .. import layers as F
from ..layer_helper import ParamAttr
from .activation import act_name
from .attr import ParameterAttribute
from .config_base import Layer
from .data_type import DataType, InputType, SequenceType
from . import pooling as _pooling


class AggregateLevel:
    TO_NO_SEQUENCE = "word"
    TO_SEQUENCE = "sequence"
    # legacy aliases
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE


def _listify(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _pattr(attr, default_name):
    """v2 attr.Param / ParamAttr / None -> framework ParamAttr with a
    stable reference-style param name ('{layer}.w0' etc.)."""
    if attr is False:
        return False
    if attr is None:
        return ParamAttr(name=default_name)
    if isinstance(attr, ParameterAttribute):
        pa = attr.to_param_attr()
    elif isinstance(attr, ParamAttr):
        pa = attr
    else:
        raise TypeError(f"bad param attr {attr!r}")
    if pa.name is None:
        pa.name = default_name
    return pa


def _apply_act(var, act):
    name = act_name(act)
    if not name:
        return var
    fn = getattr(F, name, None)
    if fn is None:
        raise NotImplementedError(f"activation {name!r}")
    return fn(var)


def _image_of(node: Layer, var, num_channels: Optional[int]):
    """Resolve a [b, C, H, W] view of `var`: either it is already 4-D,
    or the producing node carries an img_shape, or (C given) H=W is
    inferred from the flat dim — the reference config parser's rule for
    dense image inputs."""
    shape = getattr(node, "img_shape", None)
    if len(var.shape) == 4:
        return var, tuple(var.shape[1:])
    if shape is None:
        if not num_channels:
            raise ValueError(
                f"layer {node.name}: num_channels required to interpret "
                f"a flat input of dim {var.shape[-1]} as an image")
        hw = int(math.isqrt(int(var.shape[-1]) // num_channels))
        shape = (num_channels, hw, hw)
    c, h, w = shape
    return F.reshape(var, [-1, c, h, w]), (c, h, w)


# ---------------------------------------------------------------------
# data
# ---------------------------------------------------------------------

def data(name: str, type: InputType, height=None, width=None, **_kw):
    node = Layer("data", name=name, size=type.dim)
    node.data_type = type
    if height and width:
        node.img_shape = (type.dim // (height * width), height, width)

    def build(ctx):
        if type.type == DataType.Dense:
            shape, dtype = [type.dim], "float32"
        elif type.type == DataType.Index:
            shape, dtype = [1], "int64"
        else:
            raise NotImplementedError(
                "sparse v2 inputs: feed the dense multi-hot form "
                "(the TPU path has no host-side sparse format)")
        lod = {SequenceType.NO_SEQUENCE: 0, SequenceType.SEQUENCE: 1,
               SequenceType.SUB_SEQUENCE: 2}[type.seq_type]
        return F.data(name, shape, dtype=dtype, lod_level=lod)

    node._build = build
    return node


# ---------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------

def fc(input, size, act=None, name=None, param_attr=None,
       bias_attr=None, layer_attr=None):
    inputs = _listify(input)
    node = Layer("fc", parents=inputs, name=name, size=size)

    def build(ctx):
        attrs = param_attr if isinstance(param_attr, (list, tuple)) \
            else [param_attr] * len(inputs)
        if len(attrs) != len(inputs):
            # zip truncation would silently drop surplus inputs; the
            # reference config parser rejects the length mismatch
            raise ValueError(
                f"fc layer {node.name!r}: param_attr list has "
                f"{len(attrs)} entries for {len(inputs)} inputs")
        parts = []
        for i, (inp, pa) in enumerate(zip(inputs, attrs)):
            parts.append(F.fc(
                inp.to_var(ctx), size=size,
                param_attr=_pattr(pa, f"{node.name}.w{i}"),
                bias_attr=False))
        out = parts[0] if len(parts) == 1 else F.sums(parts)
        if bias_attr is not False:
            b = F.create_parameter(
                [size], "float32",
                name=(bias_attr.name if isinstance(
                    bias_attr, ParameterAttribute) and bias_attr.name
                    else f"{node.name}.wbias"),
                default_initializer=None, is_bias=True)
            out = F.elementwise_add(out, b)
        return _apply_act(out, act)

    node._build = build
    return node


def embedding(input, size, param_attr=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("embedding", parents=[inp], name=name, size=size)

    def build(ctx):
        vocab = inp.data_type.dim if hasattr(inp, "data_type") else None
        if vocab is None:
            raise ValueError("v2 embedding needs a data() parent with "
                             "an integer_value type")
        return F.embedding(
            inp.to_var(ctx), size=[vocab, size],
            param_attr=_pattr(param_attr, f"{node.name}.w0"))

    node._build = build
    return node


def img_conv(input, filter_size, num_filters, num_channels=None,
             stride=1, padding=0, act=None, name=None, param_attr=None,
             bias_attr=None, groups=1, filter_size_y=None,
             stride_y=None, padding_y=None, trans=False, **_kw):
    (inp,) = _listify(input)
    node = Layer("img_conv", parents=[inp], name=name, size=num_filters)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        fs = (filter_size, filter_size_y or filter_size)
        st = (stride, stride_y or stride)
        pd = (padding, padding_y if padding_y is not None else padding)
        if trans:
            out = F.conv2d_transpose(
                var, num_filters=num_filters, filter_size=fs,
                stride=st, padding=pd,
                act=act_name(act) or None,
                param_attr=_pattr(param_attr, f"{node.name}.w0"),
                bias_attr=(False if bias_attr is False else _pattr(
                    bias_attr, f"{node.name}.wbias")))
            oh = (h - 1) * st[0] - 2 * pd[0] + fs[0]
            ow = (w - 1) * st[1] - 2 * pd[1] + fs[1]
        else:
            out = F.conv2d(
                var, num_filters=num_filters, filter_size=fs,
                stride=st, padding=pd, groups=groups,
                act=act_name(act) or None,
                param_attr=_pattr(param_attr, f"{node.name}.w0"),
                bias_attr=(False if bias_attr is False else _pattr(
                    bias_attr, f"{node.name}.wbias")))
            oh = (h + 2 * pd[0] - fs[0]) // st[0] + 1
            ow = (w + 2 * pd[1] - fs[1]) // st[1] + 1
        node.img_shape = (num_filters, oh, ow)
        return out

    node._build = build
    return node


def img_pool(input, pool_size, num_channels=None, pool_type=None,
             stride=1, padding=0, name=None, pool_size_y=None,
             stride_y=None, padding_y=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("img_pool", parents=[inp], name=name)
    ptype = (pool_type or _pooling.Max()).fluid_name

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        ks = (pool_size, pool_size_y or pool_size)
        st = (stride, stride_y or stride)
        pd = (padding, padding_y if padding_y is not None else padding)
        out = F.pool2d(var, pool_size=ks, pool_type=ptype,
                       pool_stride=st, pool_padding=pd)
        oh = (h + 2 * pd[0] - ks[0]) // st[0] + 1
        ow = (w + 2 * pd[1] - ks[1]) // st[1] + 1
        node.img_shape = (c, oh, ow)
        return out

    node._build = build
    return node


def batch_norm(input, act=None, num_channels=None, name=None,
               param_attr=None, bias_attr=None, use_global_stats=None,
               moving_average_fraction=0.9, **_kw):
    (inp,) = _listify(input)
    node = Layer("batch_norm", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        if len(var.shape) == 2 and getattr(inp, "img_shape", None):
            var, shape = _image_of(inp, var, num_channels)
            node.img_shape = shape
        return F.batch_norm(
            var, act=act_name(act) or None,
            is_test=bool(use_global_stats),
            momentum=moving_average_fraction,
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            bias_attr=_pattr(bias_attr, f"{node.name}.wbias"))

    node._build = build
    return node


def img_cmrnorm(input, size, scale=0.0128, power=0.75, name=None,
                num_channels=None, **_kw):
    """Cross-map response normalization -> lrn (reference
    CMRProjectionNormLayer; alpha = scale/size per the legacy config
    parser's convention)."""
    (inp,) = _listify(input)
    node = Layer("img_cmrnorm", parents=[inp], name=name)

    def build(ctx):
        var, shape = _image_of(inp, inp.to_var(ctx), num_channels)
        node.img_shape = shape
        # reference config_parser.py:1360: norm_conf.scale /= norm.size
        # for cmrnorm-projection — lrn's alpha is the per-element scale
        return F.lrn(var, n=size, alpha=scale / size, beta=power)

    node._build = build
    return node


def sum_to_one_norm(input, name=None):
    (inp,) = _listify(input)
    node = Layer("sum_to_one_norm", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        denom = F.reduce_sum(var, dim=-1, keep_dim=True)
        return F.elementwise_div(var, denom)

    node._build = build
    return node


def maxout(input, groups, num_channels=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("maxout", parents=[inp], name=name)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        node.img_shape = (c // groups, h, w)
        return F.maxout(var, groups=groups)

    node._build = build
    return node


def spp(input, pyramid_height, num_channels=None, pool_type=None,
        name=None, **_kw):
    """Spatial pyramid pooling: pool at 1x1..2^k x 2^k grids, flatten,
    concat (reference SpatialPyramidPoolLayer)."""
    (inp,) = _listify(input)
    node = Layer("spp", parents=[inp], name=name)
    ptype = (pool_type or _pooling.Max()).fluid_name

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        outs = []
        for lvl in range(pyramid_height):
            bins = 2 ** lvl
            kh, kw = math.ceil(h / bins), math.ceil(w / bins)
            # the reference guarantees a bins x bins grid via ceil-mode
            # pooling; floor-mode pool2d under-produces whenever h or w
            # is not divisible by bins, so pad bottom/right up to
            # kh*bins x kw*bins (-inf identity for max; zeros plus a
            # coverage correction for avg)
            ph, pw = kh * bins - h, kw * bins - w
            src = var
            if ph or pw:
                src = F.pad2d(var, paddings=(0, ph, 0, pw),
                              pad_value=-1e30 if ptype == "max" else 0.0)
            p = F.pool2d(src, pool_size=(kh, kw), pool_type=ptype,
                         pool_stride=(kh, kw))
            if ptype != "max" and (ph or pw):
                # zero-padded avg = sum/(kh*kw); dividing by the
                # window coverage fraction restores the true mean
                ones = F.fill_constant([1, 1, h, w], "float32", 1.0)
                cnt = F.pool2d(
                    F.pad2d(ones, paddings=(0, ph, 0, pw)),
                    pool_size=(kh, kw), pool_type="avg",
                    pool_stride=(kh, kw))
                p = F.elementwise_div(p, cnt)
            outs.append(F.reshape(p, [-1, c * bins * bins]))
        return F.concat(outs, axis=1)

    node._build = build
    return node


def dropout(input, dropout_rate, name=None):
    (inp,) = _listify(input)
    node = Layer("dropout", parents=[inp], name=name)
    node._build = lambda ctx: F.dropout(inp.to_var(ctx),
                                        dropout_prob=dropout_rate)
    return node


def addto(input, act=None, name=None, bias_attr=None, **_kw):
    inputs = _listify(input)
    node = Layer("addto", parents=inputs, name=name)

    def build(ctx):
        out = F.sums([i.to_var(ctx) for i in inputs])
        return _apply_act(out, act)

    node._build = build
    return node


def concat(input, act=None, name=None, **_kw):
    inputs = _listify(input)
    node = Layer("concat", parents=inputs, name=name)

    def build(ctx):
        out = F.concat([i.to_var(ctx) for i in inputs], axis=-1)
        return _apply_act(out, act)

    node._build = build
    return node


def cos_sim(a, b, scale=1, name=None, **_kw):
    node = Layer("cos_sim", parents=[a, b], name=name)
    node._build = lambda ctx: F.scale(
        F.cos_sim(a.to_var(ctx), b.to_var(ctx)), scale=float(scale))
    return node


def conv_shift(a, b, name=None):
    """Circular 1-D correlation (reference ConvShiftLayer /
    conv_shift_op.cc): out[i] = sum_j a[i+j-floor(n/2)] * b[j]."""
    node = Layer("conv_shift", parents=[a, b], name=name)

    def build(ctx):
        from ..layer_helper import LayerHelper
        av, bv = a.to_var(ctx), b.to_var(ctx)
        helper = LayerHelper("conv_shift")
        out = helper.create_tmp_variable("float32")
        helper.append_op(type="conv_shift",
                         inputs={"X": av, "Y": bv},
                         outputs={"Out": out})
        return out

    node._build = build
    return node


def max_id(input, name=None):
    (inp,) = _listify(input)
    node = Layer("max_id", parents=[inp], name=name)
    node._build = lambda ctx: F.argmax(inp.to_var(ctx), axis=-1)
    return node


# ---------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------

def pooling(input, pooling_type=None, agg_level=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("pooling", parents=[inp], name=name)
    ptype = (pooling_type or _pooling.Max()).fluid_name

    node._build = lambda ctx: F.sequence_pool(inp.to_var(ctx),
                                              pool_type=ptype)
    return node


def last_seq(input, agg_level=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("last_seq", parents=[inp], name=name)
    node._build = lambda ctx: F.sequence_last_step(inp.to_var(ctx))
    return node


def first_seq(input, agg_level=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("first_seq", parents=[inp], name=name)
    node._build = lambda ctx: F.sequence_first_step(inp.to_var(ctx))
    return node


def lstmemory(input, name=None, reverse=False, act=None,
              gate_act=None, state_act=None, param_attr=None,
              bias_attr=None, **_kw):
    """LSTM over a sequence of 4h-dim gate pre-activations, like the
    reference LstmLayer (the projection lives in a preceding fc — see
    networks.simple_lstm)."""
    (inp,) = _listify(input)
    node = Layer("lstmemory", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        size = int(var.shape[-1])
        hidden, _cell = F.dynamic_lstm(
            var, size=size, is_reverse=reverse,
            gate_activation=act_name(gate_act) or "sigmoid",
            cell_activation=act_name(state_act) or "tanh",
            candidate_activation=act_name(act) or "tanh",
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            bias_attr=_pattr(bias_attr, f"{node.name}.wbias"))
        return hidden

    node._build = build
    return node


def gru(input, size=None, name=None, reverse=False, act=None,
        gate_act=None, param_attr=None, bias_attr=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("gru", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        sz = size or int(var.shape[-1]) // 3
        return F.dynamic_gru(
            var, size=sz, is_reverse=reverse,
            candidate_activation=act_name(act) or "tanh",
            gate_activation=act_name(gate_act) or "sigmoid",
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            bias_attr=_pattr(bias_attr, f"{node.name}.wbias"))

    node._build = build
    return node


grumemory = gru


def expand(input, expand_as, expand_level=None, name=None, **_kw):
    node = Layer("expand", parents=[input, expand_as], name=name)
    node._build = lambda ctx: F.sequence_expand(
        input.to_var(ctx), expand_as.to_var(ctx))
    return node


# ---------------------------------------------------------------------
# costs
# ---------------------------------------------------------------------

def classification_cost(input, label, weight=None, name=None, **_kw):
    """Cross-entropy on an already-softmaxed input (v2 convention: the
    output layer carries act=Softmax())."""
    parents = [input, label] + _listify(weight)
    node = Layer("classification_cost", parents=parents, name=name)

    def build(ctx):
        ce = F.cross_entropy(input.to_var(ctx), label.to_var(ctx))
        if weight is not None:
            ce = F.elementwise_mul(ce, weight.to_var(ctx))
        return F.mean(ce)

    node._build = build
    return node


def cross_entropy_cost(input, label, name=None, **_kw):
    return classification_cost(input, label, name=name)


def square_error_cost(input, label, name=None, **_kw):
    node = Layer("square_error_cost", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(F.square_error_cost(
        input.to_var(ctx), label.to_var(ctx)))
    return node


mse_cost = square_error_cost
regression_cost = square_error_cost


# ---------------------------------------------------------------------
# parse_network — the reference returns the emitted ModelConfig proto;
# here the equivalent artifact is a summary of the lowered Program.
# ---------------------------------------------------------------------

def parse_network(*outputs):
    """Lower the graphs reachable from `outputs` into a throwaway
    Program and return a ModelConfig-shaped summary dict — exactly
    Topology.proto(), which owns the summary shape."""
    from .topology import Topology

    outs = []
    for o in outputs:
        outs.extend(_listify(o))
    return Topology(outs).proto()


__all__ = [
    "AggregateLevel", "ExpandLevel", "data", "fc", "embedding",
    "img_conv", "img_pool", "batch_norm", "img_cmrnorm",
    "sum_to_one_norm", "maxout", "spp", "dropout", "addto", "concat",
    "cos_sim", "conv_shift", "max_id", "pooling", "last_seq",
    "first_seq", "lstmemory", "gru", "grumemory", "expand",
    "classification_cost", "cross_entropy_cost", "square_error_cost",
    "mse_cost", "regression_cost", "parse_network",
]
