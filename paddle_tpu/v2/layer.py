"""v2 layer-object API (reference: python/paddle/v2/layer.py, which
re-exports the trainer_config_helpers DSL as graph-building functions
returning config_base.Layer nodes; Topology walks them and a C++
GradientMachine executes the emitted ModelConfig).

TPU-native realization: each function returns a config_base.Layer whose
`build` lowers onto the fluid-style Program builder (paddle_tpu.layers)
— one op library and one XLA execution engine serve both API
generations (SURVEY §0; the 103-type vocabulary parity is audited by
tests/test_v2_layer_surface.py, and this module makes the most-used
subset RUNNABLE as real v2 layer objects)."""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from .. import layers as F
from ..layer_helper import ParamAttr
from .activation import act_name
from .attr import ParameterAttribute
from .config_base import Layer
from .data_type import DataType, InputType, SequenceType
from . import pooling as _pooling


class AggregateLevel:
    TO_NO_SEQUENCE = "word"
    TO_SEQUENCE = "sequence"
    # legacy aliases
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE


def _listify(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _pattr(attr, default_name):
    """v2 attr.Param / ParamAttr / None -> framework ParamAttr with a
    stable reference-style param name ('{layer}.w0' etc.)."""
    if attr is False:
        return False
    if attr is None:
        return ParamAttr(name=default_name)
    if isinstance(attr, ParameterAttribute):
        pa = attr.to_param_attr()
    elif isinstance(attr, ParamAttr):
        pa = attr
    else:
        raise TypeError(f"bad param attr {attr!r}")
    if pa.name is None:
        pa.name = default_name
    return pa


def _apply_act(var, act):
    name = act_name(act)
    if not name:
        return var
    fn = getattr(F, name, None)
    if fn is None:
        raise NotImplementedError(f"activation {name!r}")
    return fn(var)


def _image_of(node: Layer, var, num_channels: Optional[int]):
    """Resolve a [b, C, H, W] view of `var`: either it is already 4-D,
    or the producing node carries an img_shape, or (C given) H=W is
    inferred from the flat dim — the reference config parser's rule for
    dense image inputs."""
    shape = getattr(node, "img_shape", None)
    if len(var.shape) == 4:
        return var, tuple(var.shape[1:])
    if shape is None:
        if not num_channels:
            raise ValueError(
                f"layer {node.name}: num_channels required to interpret "
                f"a flat input of dim {var.shape[-1]} as an image")
        hw = int(math.isqrt(int(var.shape[-1]) // num_channels))
        shape = (num_channels, hw, hw)
    c, h, w = shape
    return F.reshape(var, [-1, c, h, w]), (c, h, w)


# ---------------------------------------------------------------------
# data
# ---------------------------------------------------------------------

def data(name: str, type: InputType, height=None, width=None, **_kw):
    node = Layer("data", name=name, size=type.dim)
    node.data_type = type
    if height and width:
        node.img_shape = (type.dim // (height * width), height, width)

    def build(ctx):
        if type.type == DataType.Dense:
            shape, dtype = [type.dim], "float32"
        elif type.type == DataType.Index:
            shape, dtype = [1], "int64"
        else:
            raise NotImplementedError(
                "sparse v2 inputs: feed the dense multi-hot form "
                "(the TPU path has no host-side sparse format)")
        lod = {SequenceType.NO_SEQUENCE: 0, SequenceType.SEQUENCE: 1,
               SequenceType.SUB_SEQUENCE: 2}[type.seq_type]
        return F.data(name, shape, dtype=dtype, lod_level=lod)

    node._build = build
    return node


# ---------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------

def fc(input, size, act=None, name=None, param_attr=None,
       bias_attr=None, layer_attr=None):
    inputs = _listify(input)
    node = Layer("fc", parents=inputs, name=name, size=size)

    def build(ctx):
        attrs = param_attr if isinstance(param_attr, (list, tuple)) \
            else [param_attr] * len(inputs)
        if len(attrs) != len(inputs):
            # zip truncation would silently drop surplus inputs; the
            # reference config parser rejects the length mismatch
            raise ValueError(
                f"fc layer {node.name!r}: param_attr list has "
                f"{len(attrs)} entries for {len(inputs)} inputs")
        parts = []
        for i, (inp, pa) in enumerate(zip(inputs, attrs)):
            parts.append(F.fc(
                inp.to_var(ctx), size=size,
                param_attr=_pattr(pa, f"{node.name}.w{i}"),
                bias_attr=False))
        out = parts[0] if len(parts) == 1 else F.sums(parts)
        if bias_attr is not False:
            b = F.create_parameter(
                [size], "float32",
                name=(bias_attr.name if isinstance(
                    bias_attr, ParameterAttribute) and bias_attr.name
                    else f"{node.name}.wbias"),
                default_initializer=None, is_bias=True)
            out = F.elementwise_add(out, b)
        return _apply_act(out, act)

    node._build = build
    return node


def embedding(input, size, param_attr=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("embedding", parents=[inp], name=name, size=size)

    def build(ctx):
        vocab = inp.data_type.dim if hasattr(inp, "data_type") else None
        if vocab is None:
            raise ValueError("v2 embedding needs a data() parent with "
                             "an integer_value type")
        return F.embedding(
            inp.to_var(ctx), size=[vocab, size],
            param_attr=_pattr(param_attr, f"{node.name}.w0"))

    node._build = build
    return node


def img_conv(input, filter_size, num_filters, num_channels=None,
             stride=1, padding=0, act=None, name=None, param_attr=None,
             bias_attr=None, groups=1, filter_size_y=None,
             stride_y=None, padding_y=None, trans=False, **_kw):
    (inp,) = _listify(input)
    node = Layer("img_conv", parents=[inp], name=name, size=num_filters)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        fs = (filter_size, filter_size_y or filter_size)
        st = (stride, stride_y or stride)
        pd = (padding, padding_y if padding_y is not None else padding)
        if trans:
            out = F.conv2d_transpose(
                var, num_filters=num_filters, filter_size=fs,
                stride=st, padding=pd,
                act=act_name(act) or None,
                param_attr=_pattr(param_attr, f"{node.name}.w0"),
                bias_attr=(False if bias_attr is False else _pattr(
                    bias_attr, f"{node.name}.wbias")))
            oh = (h - 1) * st[0] - 2 * pd[0] + fs[0]
            ow = (w - 1) * st[1] - 2 * pd[1] + fs[1]
        else:
            out = F.conv2d(
                var, num_filters=num_filters, filter_size=fs,
                stride=st, padding=pd, groups=groups,
                act=act_name(act) or None,
                param_attr=_pattr(param_attr, f"{node.name}.w0"),
                bias_attr=(False if bias_attr is False else _pattr(
                    bias_attr, f"{node.name}.wbias")))
            oh = (h + 2 * pd[0] - fs[0]) // st[0] + 1
            ow = (w + 2 * pd[1] - fs[1]) // st[1] + 1
        node.img_shape = (num_filters, oh, ow)
        return out

    node._build = build
    return node


def img_pool(input, pool_size, num_channels=None, pool_type=None,
             stride=1, padding=0, name=None, pool_size_y=None,
             stride_y=None, padding_y=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("img_pool", parents=[inp], name=name)
    ptype = (pool_type or _pooling.Max()).fluid_name

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        ks = (pool_size, pool_size_y or pool_size)
        st = (stride, stride_y or stride)
        pd = (padding, padding_y if padding_y is not None else padding)
        out = F.pool2d(var, pool_size=ks, pool_type=ptype,
                       pool_stride=st, pool_padding=pd)
        oh = (h + 2 * pd[0] - ks[0]) // st[0] + 1
        ow = (w + 2 * pd[1] - ks[1]) // st[1] + 1
        node.img_shape = (c, oh, ow)
        return out

    node._build = build
    return node


def batch_norm(input, act=None, num_channels=None, name=None,
               param_attr=None, bias_attr=None, use_global_stats=None,
               moving_average_fraction=0.9, **_kw):
    (inp,) = _listify(input)
    node = Layer("batch_norm", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        if len(var.shape) == 2 and getattr(inp, "img_shape", None):
            var, shape = _image_of(inp, var, num_channels)
            node.img_shape = shape
        return F.batch_norm(
            var, act=act_name(act) or None,
            is_test=bool(use_global_stats),
            momentum=moving_average_fraction,
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            bias_attr=_pattr(bias_attr, f"{node.name}.wbias"))

    node._build = build
    return node


def img_cmrnorm(input, size, scale=0.0128, power=0.75, name=None,
                num_channels=None, **_kw):
    """Cross-map response normalization -> lrn (reference
    CMRProjectionNormLayer; alpha = scale/size per the legacy config
    parser's convention)."""
    (inp,) = _listify(input)
    node = Layer("img_cmrnorm", parents=[inp], name=name)

    def build(ctx):
        var, shape = _image_of(inp, inp.to_var(ctx), num_channels)
        node.img_shape = shape
        # reference config_parser.py:1360: norm_conf.scale /= norm.size
        # for cmrnorm-projection — lrn's alpha is the per-element scale
        return F.lrn(var, n=size, alpha=scale / size, beta=power)

    node._build = build
    return node


def sum_to_one_norm(input, name=None):
    (inp,) = _listify(input)
    node = Layer("sum_to_one_norm", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        denom = F.reduce_sum(var, dim=-1, keep_dim=True)
        return F.elementwise_div(var, denom)

    node._build = build
    return node


def maxout(input, groups, num_channels=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("maxout", parents=[inp], name=name)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        node.img_shape = (c // groups, h, w)
        return F.maxout(var, groups=groups)

    node._build = build
    return node


def spp(input, pyramid_height, num_channels=None, pool_type=None,
        name=None, **_kw):
    """Spatial pyramid pooling: pool at 1x1..2^k x 2^k grids, flatten,
    concat (reference SpatialPyramidPoolLayer)."""
    (inp,) = _listify(input)
    node = Layer("spp", parents=[inp], name=name)
    ptype = (pool_type or _pooling.Max()).fluid_name

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        outs = []
        for lvl in range(pyramid_height):
            bins = 2 ** lvl
            kh, kw = math.ceil(h / bins), math.ceil(w / bins)
            # the reference guarantees a bins x bins grid via ceil-mode
            # pooling; floor-mode pool2d under-produces whenever h or w
            # is not divisible by bins, so pad bottom/right up to
            # kh*bins x kw*bins (-inf identity for max; zeros plus a
            # coverage correction for avg)
            ph, pw = kh * bins - h, kw * bins - w
            src = var
            if ph or pw:
                src = F.pad2d(var, paddings=(0, ph, 0, pw),
                              pad_value=-1e30 if ptype == "max" else 0.0)
            p = F.pool2d(src, pool_size=(kh, kw), pool_type=ptype,
                         pool_stride=(kh, kw))
            if ptype != "max" and (ph or pw):
                # zero-padded avg = sum/(kh*kw); dividing by the
                # window coverage fraction restores the true mean
                ones = F.fill_constant([1, 1, h, w], "float32", 1.0)
                cnt = F.pool2d(
                    F.pad2d(ones, paddings=(0, ph, 0, pw)),
                    pool_size=(kh, kw), pool_type="avg",
                    pool_stride=(kh, kw))
                p = F.elementwise_div(p, cnt)
            outs.append(F.reshape(p, [-1, c * bins * bins]))
        return F.concat(outs, axis=1)

    node._build = build
    return node


def dropout(input, dropout_rate, name=None):
    (inp,) = _listify(input)
    node = Layer("dropout", parents=[inp], name=name)
    node._build = lambda ctx: F.dropout(inp.to_var(ctx),
                                        dropout_prob=dropout_rate)
    return node


def addto(input, act=None, name=None, bias_attr=None, **_kw):
    inputs = _listify(input)
    node = Layer("addto", parents=inputs, name=name)

    def build(ctx):
        out = F.sums([i.to_var(ctx) for i in inputs])
        return _apply_act(out, act)

    node._build = build
    return node


def concat(input, act=None, name=None, **_kw):
    inputs = _listify(input)
    node = Layer("concat", parents=inputs, name=name)

    def build(ctx):
        out = F.concat([i.to_var(ctx) for i in inputs], axis=-1)
        return _apply_act(out, act)

    node._build = build
    return node


def cos_sim(a, b, scale=1, name=None, **_kw):
    node = Layer("cos_sim", parents=[a, b], name=name)
    node._build = lambda ctx: F.scale(
        F.cos_sim(a.to_var(ctx), b.to_var(ctx)), scale=float(scale))
    return node


def conv_shift(a, b, name=None):
    """Circular 1-D correlation (reference ConvShiftLayer /
    conv_shift_op.cc): out[i] = sum_j a[i+j-floor(n/2)] * b[j]."""
    node = Layer("conv_shift", parents=[a, b], name=name)

    def build(ctx):
        from ..layer_helper import LayerHelper
        av, bv = a.to_var(ctx), b.to_var(ctx)
        helper = LayerHelper("conv_shift")
        out = helper.create_tmp_variable("float32")
        helper.append_op(type="conv_shift",
                         inputs={"X": av, "Y": bv},
                         outputs={"Out": out})
        return out

    node._build = build
    return node


def max_id(input, name=None):
    (inp,) = _listify(input)
    node = Layer("max_id", parents=[inp], name=name)
    node._build = lambda ctx: F.argmax(inp.to_var(ctx), axis=-1)
    return node


# ---------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------

def pooling(input, pooling_type=None, agg_level=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("pooling", parents=[inp], name=name)
    ptype = (pooling_type or _pooling.Max()).fluid_name

    node._build = lambda ctx: F.sequence_pool(inp.to_var(ctx),
                                              pool_type=ptype)
    return node


def last_seq(input, agg_level=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("last_seq", parents=[inp], name=name)
    node._build = lambda ctx: F.sequence_last_step(inp.to_var(ctx))
    return node


def first_seq(input, agg_level=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("first_seq", parents=[inp], name=name)
    node._build = lambda ctx: F.sequence_first_step(inp.to_var(ctx))
    return node


def lstmemory(input, name=None, reverse=False, act=None,
              gate_act=None, state_act=None, param_attr=None,
              bias_attr=None, **_kw):
    """LSTM over a sequence of 4h-dim gate pre-activations, like the
    reference LstmLayer (the projection lives in a preceding fc — see
    networks.simple_lstm)."""
    (inp,) = _listify(input)
    node = Layer("lstmemory", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        size = int(var.shape[-1])
        hidden, _cell = F.dynamic_lstm(
            var, size=size, is_reverse=reverse,
            gate_activation=act_name(gate_act) or "sigmoid",
            cell_activation=act_name(state_act) or "tanh",
            candidate_activation=act_name(act) or "tanh",
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            bias_attr=_pattr(bias_attr, f"{node.name}.wbias"))
        ctx[(id(node), "state")] = _cell  # for get_output(..., 'state')
        return hidden

    node._build = build
    return node


def gru(input, size=None, name=None, reverse=False, act=None,
        gate_act=None, param_attr=None, bias_attr=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("gru", parents=[inp], name=name)

    def build(ctx):
        var = inp.to_var(ctx)
        sz = size or int(var.shape[-1]) // 3
        return F.dynamic_gru(
            var, size=sz, is_reverse=reverse,
            candidate_activation=act_name(act) or "tanh",
            gate_activation=act_name(gate_act) or "sigmoid",
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            bias_attr=_pattr(bias_attr, f"{node.name}.wbias"))

    node._build = build
    return node


grumemory = gru


def expand(input, expand_as, expand_level=None, name=None, **_kw):
    node = Layer("expand", parents=[input, expand_as], name=name)
    node._build = lambda ctx: F.sequence_expand(
        input.to_var(ctx), expand_as.to_var(ctx))
    return node


# ---------------------------------------------------------------------
# costs
# ---------------------------------------------------------------------

def classification_cost(input, label, weight=None, name=None, **_kw):
    """Cross-entropy on an already-softmaxed input (v2 convention: the
    output layer carries act=Softmax())."""
    parents = [input, label] + _listify(weight)
    node = Layer("classification_cost", parents=parents, name=name)

    def build(ctx):
        ce = F.cross_entropy(input.to_var(ctx), label.to_var(ctx))
        if weight is not None:
            ce = F.elementwise_mul(ce, weight.to_var(ctx))
        return F.mean(ce)

    node._build = build
    return node


def cross_entropy_cost(input, label, name=None, **_kw):
    return classification_cost(input, label, name=name)


def square_error_cost(input, label, name=None, **_kw):
    node = Layer("square_error_cost", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(F.square_error_cost(
        input.to_var(ctx), label.to_var(ctx)))
    return node


mse_cost = square_error_cost
regression_cost = square_error_cost


# ---------------------------------------------------------------------
# raw-op plumbing for layers whose op has no fluid-layers wrapper
# ---------------------------------------------------------------------

def _raw_op(op_type, inputs, attrs=None, out_slots=("Out",),
            dtype="float32", lod_out=()):
    """Append one op via LayerHelper; returns {slot: var}."""
    from ..layer_helper import LayerHelper
    helper = LayerHelper(op_type)
    outs = {}
    for s in out_slots:
        outs[s] = helper.create_tmp_variable(
            dtype, lod_level=1 if s in lod_out else 0)
    helper.append_op(type=op_type, inputs=inputs,
                     outputs={s: [v] for s, v in outs.items()},
                     attrs=attrs or {})
    return outs


def _param(shape, attr_or_name, initializer=None):
    """Create a parameter from either a resolved ParamAttr (so user
    initializers/regularizers/shared names are honored) or a default
    name string."""
    if isinstance(attr_or_name, str):
        attr_or_name = ParamAttr(name=attr_or_name)
    return F.create_parameter(list(shape), "float32",
                              attr=attr_or_name,
                              default_initializer=initializer)


# ---------------------------------------------------------------------
# image / feature-map layers
# ---------------------------------------------------------------------

def bilinear_interp(input, out_size_x, out_size_y, num_channels=None,
                    name=None, **_kw):
    """reference: BilinearInterpLayer (bilinear_interp_layer.cpp)."""
    (inp,) = _listify(input)
    node = Layer("bilinear_interp", parents=[inp], name=name)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        node.img_shape = (c, out_size_y, out_size_x)
        return F.bilinear_interp(var, out_shape=[out_size_y, out_size_x])

    node._build = build
    return node


def block_expand(input, block_x, block_y, stride_x=None, stride_y=None,
                 num_channels=None, padding_x=0, padding_y=0, name=None,
                 **_kw):
    """Image -> sequence of flattened patches (reference:
    BlockExpandLayer -> im2sequence_op.cc)."""
    (inp,) = _listify(input)
    node = Layer("blockexpand", parents=[inp], name=name)

    def build(ctx):
        var, _shape = _image_of(inp, inp.to_var(ctx), num_channels)
        return F.im2sequence(var, filter_size=(block_y, block_x),
                             stride=(stride_y or block_y,
                                     stride_x or block_x),
                             padding=(padding_y, padding_x))

    node._build = build
    return node


def clip_layer(input, min, max, name=None):
    (inp,) = _listify(input)
    node = Layer("clip", parents=[inp], name=name)
    node._build = lambda ctx: F.clip(inp.to_var(ctx), float(min),
                                     float(max))
    return node


def conv3d(input, filter_size, num_filters, num_channels=None,
           stride=1, padding=0, act=None, name=None, param_attr=None,
           bias_attr=None, input_shape=None, trans=False, **_kw):
    """3-D convolution (reference: Conv3DLayer / conv3d_op).
    input_shape=(C, D, H, W) interprets a flat dense input."""
    (inp,) = _listify(input)
    node = Layer("deconv3d" if trans else "conv3d", parents=[inp],
                 name=name, size=num_filters)

    def build(ctx):
        var = inp.to_var(ctx)
        if len(var.shape) != 5:
            if input_shape is None:
                raise ValueError("conv3d on a flat input needs "
                                 "input_shape=(C, D, H, W)")
            var = F.reshape(var, [-1] + list(input_shape))
        cin = int(var.shape[1])
        k = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size,) * 3
        # transpose conv keeps the reference's [Cin, Cout, ...] layout
        fshape = [cin, num_filters] if trans else [num_filters, cin]
        w = _param(fshape + list(k),
                   _pattr(param_attr, f"{node.name}.w0"))
        out = _raw_op("conv3d_transpose" if trans else "conv3d",
                      {"Input": var, "Filter": w},
                      attrs={"strides": [stride] * 3,
                             "paddings": [padding] * 3,
                             "dilations": [1, 1, 1],
                             **({} if trans else {"groups": 1})},
                      out_slots=("Output",))["Output"]
        if bias_attr is not False:
            b = F.create_parameter(
                [num_filters], "float32",
                attr=_pattr(bias_attr, f"{node.name}.wbias"),
                is_bias=True)
            out = F.elementwise_add(out, F.reshape(
                b, [1, num_filters, 1, 1, 1]))
        return _apply_act(out, act)

    node._build = build
    return node


def deconv3d(input, filter_size, num_filters, **kw):
    return conv3d(input, filter_size, num_filters, trans=True, **kw)


def pad(input, pad_c=None, pad_h=None, pad_w=None, num_channels=None,
        name=None, **_kw):
    """Zero-pad an image along channel/height/width (reference:
    PadLayer; each pad_* is a [before, after] pair)."""
    (inp,) = _listify(input)
    node = Layer("pad", parents=[inp], name=name)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        pc = pad_c or [0, 0]
        ph = pad_h or [0, 0]
        pw = pad_w or [0, 0]
        node.img_shape = (c + sum(pc), h + sum(ph), w + sum(pw))
        return F.pad(var, [0, 0, pc[0], pc[1], ph[0], ph[1],
                           pw[0], pw[1]])

    node._build = build
    return node


def pool3d(input, pool_size, num_channels=None, pool_type=None,
           stride=1, padding=0, input_shape=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("pool3d", parents=[inp], name=name)
    ptype = (pool_type or _pooling.Max()).fluid_name

    def build(ctx):
        var = inp.to_var(ctx)
        if len(var.shape) != 5:
            if input_shape is None:
                raise ValueError("pool3d on a flat input needs "
                                 "input_shape=(C, D, H, W)")
            var = F.reshape(var, [-1] + list(input_shape))
        return _raw_op("pool3d", {"X": var},
                       attrs={"ksize": [pool_size] * 3,
                              "strides": [stride] * 3,
                              "paddings": [padding] * 3,
                              "pooling_type": ptype,
                              "global_pooling": False,
                              "exclusive": True})["Out"]

    node._build = build
    return node


def rotate(input, height=None, width=None, num_channels=None,
           name=None):
    """Rotate each feature map 90 degrees counter-clockwise
    (reference: RotateLayer: out[h', w'] = in[w, H-1-h'])."""
    (inp,) = _listify(input)
    node = Layer("rotate", parents=[inp], name=name)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        out = F.transpose(var, [0, 1, 3, 2])     # swap H and W
        node.img_shape = (c, w, h)
        return F.reverse(out, [2])               # flip the new H axis

    node._build = build
    return node


def switch_order(input, reshape_order=(0, 2, 3, 1), num_channels=None,
                 name=None, **_kw):
    """NCHW -> NHWC reorder (reference: SwitchOrderLayer)."""
    (inp,) = _listify(input)
    node = Layer("switch_order", parents=[inp], name=name)

    def build(ctx):
        var, _s = _image_of(inp, inp.to_var(ctx), num_channels)
        return F.transpose(var, list(reshape_order))

    node._build = build
    return node


def crop(input, shape=None, offsets=None, num_channels=None, name=None,
         **_kw):
    (inp,) = _listify(input)
    node = Layer("crop", parents=[inp], name=name)

    def build(ctx):
        var, _s = _image_of(inp, inp.to_var(ctx), num_channels)
        return F.crop(var, shape=shape, offsets=offsets)

    node._build = build
    return node


def upsample(input, scale=2, num_channels=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("upsample", parents=[inp], name=name)

    def build(ctx):
        var, (c, h, w) = _image_of(inp, inp.to_var(ctx), num_channels)
        node.img_shape = (c, h * scale, w * scale)
        return F.upsample(var, scale=scale)

    node._build = build
    return node


def resize(input, size, name=None):
    """Reinterpret the minibatch matrix as rows of `size` elements
    (reference: ResizeLayer — a pure reshape, despite the name)."""
    (inp,) = _listify(input)
    node = Layer("resize", parents=[inp], name=name, size=size)
    node._build = lambda ctx: F.reshape(inp.to_var(ctx), [-1, size])
    return node


def scale_sub_region(input, indices, value, num_channels=None,
                     name=None):
    """Scale a per-sample [c1,c2,h1,h2,w1,w2] sub-region by `value`
    (reference: ScaleSubRegionLayer)."""
    node = Layer("scale_sub_region", parents=[input, indices], name=name)

    def build(ctx):
        var, _s = _image_of(input, input.to_var(ctx), num_channels)
        return _raw_op("scale_sub_region",
                       {"X": var, "Indices": indices.to_var(ctx)},
                       attrs={"value": float(value)})["Out"]

    node._build = build
    return node


def prelu(input, param_attr=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("prelu", parents=[inp], name=name)
    node._build = lambda ctx: F.prelu(
        inp.to_var(ctx), mode="all",
        param_attr=_pattr(param_attr, f"{node.name}.w0"))
    return node


# ---------------------------------------------------------------------
# projections / algebra layers
# ---------------------------------------------------------------------

mixed = fc  # mixed_layer sums full-matrix projections; fc(input=[...])
            # is exactly that realization (reference: MixedLayer.cpp)


def dot_prod(a, b, name=None):
    """Row-wise dot product (reference: DotProdLayer)."""
    node = Layer("dot_prod", parents=[a, b], name=name)
    node._build = lambda ctx: F.reduce_sum(
        F.elementwise_mul(a.to_var(ctx), b.to_var(ctx)),
        dim=-1, keep_dim=True)
    return node


def out_prod(a, b, name=None):
    """Row-wise outer product flattened to [bs, m*n] (reference:
    OuterProdLayer)."""
    node = Layer("out_prod", parents=[a, b], name=name)

    def build(ctx):
        av, bv = a.to_var(ctx), b.to_var(ctx)
        m, n = int(av.shape[-1]), int(bv.shape[-1])
        prod = F.matmul(F.reshape(av, [-1, m, 1]),
                        F.reshape(bv, [-1, 1, n]))
        return F.reshape(prod, [-1, m * n])

    node._build = build
    return node


def l2_distance(a, b, name=None):
    node = Layer("l2_distance", parents=[a, b], name=name)

    def build(ctx):
        d = F.elementwise_sub(a.to_var(ctx), b.to_var(ctx))
        return F.sqrt(F.reduce_sum(F.square(d), dim=-1, keep_dim=True))

    node._build = build
    return node


def linear_comb(weights, vectors, size, name=None):
    """Convex/linear combination: weights [bs, M] over vectors
    [bs, M*size] -> [bs, size] (reference: LinearCombLayer, type
    'convex_comb')."""
    node = Layer("convex_comb", parents=[weights, vectors], name=name,
                 size=size)

    def build(ctx):
        w = weights.to_var(ctx)
        v = vectors.to_var(ctx)
        m = int(w.shape[-1])
        v3 = F.reshape(v, [-1, m, size])
        return F.reshape(
            F.matmul(F.reshape(w, [-1, 1, m]), v3), [-1, size])

    node._build = build
    return node


def interpolation(input, weight, name=None):
    """w*a + (1-w)*b with per-sample scalar w (reference:
    InterpolationLayer)."""
    a, b = _listify(input)
    node = Layer("interpolation", parents=[a, b, weight], name=name)

    def build(ctx):
        w = weight.to_var(ctx)
        av, bv = a.to_var(ctx), b.to_var(ctx)
        return F.elementwise_add(
            F.elementwise_mul(av, w),
            F.elementwise_mul(bv, F.scale(w, scale=-1.0, bias=1.0)))

    node._build = build
    return node


def scaling(weight, input, name=None):
    """Row-wise scale of input by a per-sample scalar (reference:
    ScalingLayer)."""
    node = Layer("scaling", parents=[weight, input], name=name)
    node._build = lambda ctx: F.elementwise_mul(input.to_var(ctx),
                                                weight.to_var(ctx))
    return node


def scale_shift(input, param_attr=None, bias_attr=None, name=None):
    """y = w*x + b with SCALAR learnable w, b (reference:
    ScaleShiftLayer)."""
    (inp,) = _listify(input)
    node = Layer("scale_shift", parents=[inp], name=name)

    def build(ctx):
        w = _param([1], _pattr(param_attr, f"{node.name}.w0"))
        b = _param([1], _pattr(bias_attr, f"{node.name}.wbias"))
        return F.elementwise_add(
            F.elementwise_mul(inp.to_var(ctx), w), b)

    node._build = build
    return node


def slope_intercept(input, slope=1.0, intercept=0.0, name=None):
    (inp,) = _listify(input)
    node = Layer("slope_intercept", parents=[inp], name=name)
    node._build = lambda ctx: F.scale(inp.to_var(ctx),
                                      scale=float(slope),
                                      bias=float(intercept))
    return node


def power(input, weight, name=None):
    """x ** w with per-sample scalar exponent (reference: PowerLayer).
    Realized as exp(w * log(x)) so the exponent can be a tensor —
    requires x > 0, as the reference's layer does in practice."""
    node = Layer("power", parents=[input, weight], name=name)

    def build(ctx):
        x = input.to_var(ctx)
        w = weight.to_var(ctx)
        return F.exp(F.elementwise_mul(F.log(x), w))

    node._build = build
    return node


def trans(input, name=None):
    """Transpose the whole minibatch matrix (reference: TransLayer)."""
    (inp,) = _listify(input)
    node = Layer("trans", parents=[inp], name=name)
    node._build = lambda ctx: F.transpose(inp.to_var(ctx), [1, 0])
    return node


def tensor_layer(a, b, size, param_attr=None, bias_attr=None, act=None,
                 name=None, **_kw):
    """out_k = a . W_k . b^T (reference: TensorLayer ->
    bilinear_tensor_product_op)."""
    node = Layer("tensor", parents=[a, b], name=name, size=size)

    def build(ctx):
        av, bv = a.to_var(ctx), b.to_var(ctx)
        da, db = int(av.shape[-1]), int(bv.shape[-1])
        w = _param([size, da, db],
                   _pattr(param_attr, f"{node.name}.w0"))
        bias = _param([1, size], _pattr(bias_attr, f"{node.name}.wbias"))
        out = _raw_op("bilinear_tensor_product",
                      {"X": av, "Y": bv, "Weight": w, "Bias": bias})
        return _apply_act(out["Out"], act)

    node._build = build
    return node


def selective_fc(input, select, size, act=None, param_attr=None,
                 bias_attr=None, name=None, **_kw):
    """fc whose output is masked by a per-sample 0/1 selection matrix
    (reference: SelectiveFullyConnectedLayer)."""
    inputs = _listify(input)
    node = Layer("selective_fc", parents=inputs + [select], name=name,
                 size=size)

    def build(ctx):
        dense = fc(inputs, size, act=act, param_attr=param_attr,
                   bias_attr=bias_attr, name=f"{node.name}_fc")
        return F.elementwise_mul(dense.to_var(ctx), select.to_var(ctx))

    node._build = build
    return node


def factorization_machine(input, factor_size, param_attr=None,
                          name=None, **_kw):
    """Second-order FM term: 0.5 * sum_k[(x.V_k)^2 - (x^2).(V_k^2)]
    (reference: FactorizationMachineLayer.cpp)."""
    (inp,) = _listify(input)
    node = Layer("factorization_machine", parents=[inp], name=name)

    def build(ctx):
        x = inp.to_var(ctx)
        d = int(x.shape[-1])
        v = _param([d, factor_size],
                   _pattr(param_attr, f"{node.name}.w0"))
        sum_sq = F.square(F.matmul(x, v))              # (x.V)^2
        sq_sum = F.matmul(F.square(x), F.square(v))     # (x^2).(V^2)
        return F.scale(F.reduce_sum(
            F.elementwise_sub(sum_sq, sq_sum), dim=-1, keep_dim=True),
            scale=0.5)

    node._build = build
    return node


def data_norm(input, name=None, **_kw):
    """Normalization by learned-then-frozen per-feature stats
    (reference: DataNormLayer; z-score form)."""
    (inp,) = _listify(input)
    node = Layer("data_norm", parents=[inp], name=name)

    def build(ctx):
        x = inp.to_var(ctx)
        d = int(x.shape[-1])
        from ..initializer import ConstantInitializer
        mean = _param([d], f"{node.name}.mean")
        std = _param([d], f"{node.name}.std",
                     initializer=ConstantInitializer(1.0))
        return F.elementwise_div(F.elementwise_sub(x, mean), std)

    node._build = build
    return node


# ---------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------

def seq_concat(a, b, name=None):
    node = Layer("seqconcat", parents=[a, b], name=name)
    node._build = lambda ctx: _raw_op(
        "sequence_concat", {"X": [a.to_var(ctx), b.to_var(ctx)]},
        lod_out=("Out",))["Out"]
    return node


def seq_slice(input, offsets, sizes, name=None):
    node = Layer("seq_slice", parents=[input, offsets, sizes], name=name)
    node._build = lambda ctx: _raw_op(
        "sequence_slice", {"X": input.to_var(ctx),
                           "Offset": offsets.to_var(ctx),
                           "Length": sizes.to_var(ctx)},
        lod_out=("Out",))["Out"]
    return node


def sub_seq(input, offsets, sizes, name=None):
    """reference: SubSequenceLayer — same contract as seq_slice."""
    node = seq_slice(input, offsets, sizes, name=name)
    node.type = "subseq"
    return node


def seq_reshape(input, reshape_size, name=None):
    node = Layer("seqreshape", parents=[input], name=name,
                 size=reshape_size)
    node._build = lambda ctx: F.sequence_reshape(input.to_var(ctx),
                                                 reshape_size)
    return node


def sub_nested_seq(input, name=None):
    """Flatten the outer nesting level of a 2-level sequence
    (reference: SubNestedSequenceLayer's underlying access pattern)."""
    (inp,) = _listify(input)
    node = Layer("sub_nested_seq", parents=[inp], name=name)
    node._build = lambda ctx: _raw_op(
        "nested_sequence_flatten", {"X": inp.to_var(ctx)},
        lod_out=("Out",))["Out"]
    return node


def kmax_seq_score(input, beam_size=1, name=None):
    """Indices of the k max scores (reference: KmaxSeqScoreLayer)."""
    (inp,) = _listify(input)
    node = Layer("kmax_seq_score", parents=[inp], name=name)

    def build(ctx):
        outs = _raw_op("top_k", {"X": inp.to_var(ctx)},
                       attrs={"k": beam_size},
                       out_slots=("Out", "Indices"))
        return outs["Indices"]

    node._build = build
    return node


def eos(input, eos_id, name=None):
    """1.0 where the input id equals end-of-sequence (reference:
    EosIdCheckLayer, type 'eos_id')."""
    (inp,) = _listify(input)
    node = Layer("eos_id", parents=[inp], name=name)

    def build(ctx):
        x = inp.to_var(ctx)
        ref = F.fill_constant_batch_size_like(x, list(x.shape), "int64",
                                              eos_id)
        return F.cast(F.equal(x, ref), "float32")

    node._build = build
    return node


def mdlstmemory(input, size, height, width, name=None, param_attr=None,
                **_kw):
    """2-D multi-dimensional LSTM (reference: MDLstmLayer). The input
    carries 5*size gate pre-activations per grid cell."""
    (inp,) = _listify(input)
    node = Layer("mdlstmemory", parents=[inp], name=name, size=size)

    def build(ctx):
        x = F.reshape(inp.to_var(ctx), [-1, height, width, 5 * size])
        wl = _param([size, 5 * size],
                    _pattr(param_attr, f"{node.name}.wl"))
        # second recurrent weight keeps its own name (sharing a
        # user-named attr across both would silently tie them)
        wt = _param([size, 5 * size], f"{node.name}.wt")
        out = _raw_op("mdlstm", {"X": x, "WeightLeft": wl,
                                 "WeightTop": wt})["Out"]
        return F.reshape(out, [-1, height * width * size])

    node._build = build
    return node


def lstm_step(input, state, name=None, act=None, gate_act=None,
              state_act=None, **_kw):
    """One LSTM cell update from precomputed gate pre-activations
    [bs, 4h] and the previous cell state [bs, h] (reference:
    LstmStepLayer: the recurrent projection already lives in `input`).
    The new cell state is exposed for get_output(..., 'state').
    gate_act gates i/f/o, act squashes the candidate, state_act
    squashes the cell on the way out (reference defaults)."""
    node = Layer("lstm_step", parents=[input, state], name=name)

    def _act(var, which, default):
        nm = act_name(which)
        fn = getattr(F, nm, None) if nm else None
        return fn(var) if fn else default(var)

    def build(ctx):
        x = input.to_var(ctx)
        c_prev = state.to_var(ctx)
        h4 = int(x.shape[-1])
        h = h4 // 4
        i, f, g, o = (F.slice(x, [1], [k * h], [(k + 1) * h])
                      for k in range(4))
        c_new = F.elementwise_add(
            F.elementwise_mul(_act(f, gate_act, F.sigmoid), c_prev),
            F.elementwise_mul(_act(i, gate_act, F.sigmoid),
                              _act(g, act, F.tanh)))
        hid = F.elementwise_mul(_act(o, gate_act, F.sigmoid),
                                _act(c_new, state_act, F.tanh))
        ctx[(id(node), "state")] = c_new
        return hid

    node._build = build
    return node


def gru_step(input, output_mem, size=None, act=None, gate_act=None,
             name=None, param_attr=None, bias_attr=None, **_kw):
    """One GRU cell update (reference: GruStepLayer -> gru_unit)."""
    node = Layer("gru_step", parents=[input, output_mem], name=name)

    def build(ctx):
        x = input.to_var(ctx)
        prev = output_mem.to_var(ctx)
        sz = size or int(prev.shape[-1])
        hidden, _, _ = F.gru_unit(
            x, prev, sz * 3,
            param_attr=_pattr(param_attr, f"{node.name}.w0"),
            activation=act_name(act) or "tanh",
            gate_activation=act_name(gate_act) or "sigmoid")
        return hidden

    node._build = build
    return node


def get_output(input, arg_name, name=None):
    """Select a named secondary output of a layer (reference:
    GetOutputLayer; e.g. the 'state' of an lstm_step)."""
    node = Layer("get_output", parents=[input], name=name)

    def build(ctx):
        input.to_var(ctx)  # ensure the parent has built its outputs
        key = (id(input), arg_name)
        if key not in ctx:
            raise ValueError(
                f"layer {input.name!r} exposes no output {arg_name!r}")
        return ctx[key]

    node._build = build
    return node


# ---------------------------------------------------------------------
# recurrent groups (reference: trainer_config_helpers recurrent_group +
# memory; the agent/gather_agent/scatter_agent/recurrent_layer_group
# machinery the config parser emits for them)
# ---------------------------------------------------------------------

class StaticInput:
    """Non-sequence input visible unchanged at every step."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq
        self.size = size


from .config_base import RNN_STACK as _RNN_STACK  # shared with
# config_base so Layer.__init__ can register in-step nodes


def _in_parent_block(build_fn, ctx):
    """Build a sub-graph in the PARENT of the current block: vars
    consumed by the outer dynamic_rnn op (memory boots, static inputs)
    must have their producing ops outside the step sub-block."""
    from ..framework import default_main_program
    prog = default_main_program()
    saved = prog._current_block_idx
    prog._current_block_idx = prog.current_block().desc.parent_idx
    try:
        return build_fn(ctx)
    finally:
        prog._current_block_idx = saved


def memory(name, size, boot_layer=None, **_kw):
    """Declare a step memory linked BY NAME to the layer that produces
    its next value inside the step (reference: memory() in
    trainer_config_helpers; the 'agent'/'scatter_agent' plumbing)."""
    node = Layer("memory")
    node.link_name = name
    node.size = size

    def build(ctx):
        if not _RNN_STACK:
            raise ValueError("memory() is only usable inside a "
                             "recurrent_group step function")
        frame = _RNN_STACK[-1]
        # boot graphs belong to the block OUTSIDE the scan
        init = _in_parent_block(boot_layer.to_var, ctx) \
            if boot_layer is not None else None
        mem = frame["drnn"].memory(init=init, shape=[size])
        frame["memories"].append((name, mem))
        return mem

    node._build = build
    return node


def recurrent_group(step, input, reverse=False, name=None):
    """Run `step` over each timestep of the sequence inputs
    (reference: recurrent_group -> RecurrentLayerGroup; realized on
    the DynamicRNN masked scan). `step` receives one node per input
    (step slice for sequences, the unchanged var for StaticInput) and
    returns the step's output layer; memories declared via memory()
    are linked to same-named layers in the step graph. reverse=True
    runs right-to-left (sequence_reverse in, sequence_reverse out)."""
    inputs = _listify(input)
    parents = [i.input if isinstance(i, StaticInput) else i
               for i in inputs]
    node = Layer("recurrent_layer_group", parents=parents, name=name)

    def build(ctx):
        # resolve EVERY input graph before entering the step block —
        # ops built inside drnn.block() land in the sub-block and the
        # outer dynamic_rnn op could not see their results
        resolved = []
        for i in inputs:
            if isinstance(i, StaticInput):
                resolved.append(("static", i.input.to_var(ctx)))
            else:
                v = i.to_var(ctx)
                if reverse:
                    v = F.sequence_reverse(v)
                resolved.append(("seq", v))
        drnn = F.DynamicRNN()
        frame = {"drnn": drnn, "memories": []}
        with drnn.block():
            args = []
            for kind, v in resolved:
                sv = drnn.static_input(v) if kind == "static" \
                    else drnn.step_input(v)
                wrap = Layer("agent")
                wrap._build = (lambda _ctx, _v=sv: _v)
                args.append(wrap)
            _RNN_STACK.append(frame)
            try:
                out_node = step(*args)
                if isinstance(out_node, (list, tuple)):
                    raise NotImplementedError(
                        "multi-output recurrent_group: return a single "
                        "layer (concat inside the step if needed)")
                out_var = out_node.to_var(ctx)
                for link_name, mem_var in frame["memories"]:
                    target = None
                    candidates = frame.get("nodes", []) + \
                        out_node.ancestors()
                    for n in candidates:
                        if n.name == link_name:
                            target = n
                    if target is None:
                        raise ValueError(
                            f"memory {link_name!r}: no layer of that "
                            "name in the step graph")
                    drnn.update_memory(mem_var, target.to_var(ctx))
                drnn.output(out_var)
            finally:
                _RNN_STACK.pop()
        out = drnn()
        return F.sequence_reverse(out) if reverse else out

    node._build = build
    return node


def recurrent(input, act=None, reverse=False, name=None,
              param_attr=None, **_kw):
    """Simple full-matrix recurrence h_t = act(x_t + h_{t-1} W)
    (reference: RecurrentLayer, type 'recurrent'). reverse=True scans
    right-to-left via sequence_reverse on both sides."""
    (inp,) = _listify(input)
    node = Layer("recurrent", parents=[inp], name=name)

    def build(ctx):
        x = inp.to_var(ctx)
        if reverse:
            x = F.sequence_reverse(x)
        d = int(x.shape[-1])
        drnn = F.DynamicRNN()
        with drnn.block():
            step = drnn.step_input(x)
            prev = drnn.memory(shape=[d], value=0.0)
            proj = F.fc(prev, size=d, bias_attr=False,
                        param_attr=_pattr(param_attr,
                                          f"{node.name}.w0"))
            h = _apply_act(F.elementwise_add(step, proj),
                           act) if act else F.tanh(
                F.elementwise_add(step, proj))
            drnn.update_memory(prev, h)
            drnn.output(h)
        out = drnn()
        return F.sequence_reverse(out) if reverse else out

    node._build = build
    return node


# ---------------------------------------------------------------------
# output / decode layers
# ---------------------------------------------------------------------

def multiplex(input, name=None):
    """input = [index_layer, candidate0, candidate1, ...]; picks row i
    from candidate[index[i]] (reference: MultiplexLayer)."""
    nodes = _listify(input)
    node = Layer("multiplex", parents=nodes, name=name)

    def build(ctx):
        ids = nodes[0].to_var(ctx)
        xs = [n.to_var(ctx) for n in nodes[1:]]
        return _raw_op("multiplex", {"Ids": ids, "X": xs})["Out"]

    node._build = build
    return node


def sampling_id(input, name=None):
    (inp,) = _listify(input)
    node = Layer("sampling_id", parents=[inp], name=name)
    node._build = lambda ctx: _raw_op(
        "sampling_id", {"X": inp.to_var(ctx)}, dtype="int64")["Out"]
    return node


def print_layer(input, message="", name=None):
    (inp,) = _listify(input)
    node = Layer("print", parents=[inp], name=name)
    node._build = lambda ctx: _raw_op(
        "print", {"X": inp.to_var(ctx)},
        attrs={"message": message or node.name})["Out"]
    return node


def row_l2_norm(input, name=None):
    (inp,) = _listify(input)
    node = Layer("row_l2_norm", parents=[inp], name=name)
    node._build = lambda ctx: F.l2_normalize(inp.to_var(ctx), axis=1)
    return node


def row_conv(input, context_len, param_attr=None, act=None, name=None):
    (inp,) = _listify(input)
    node = Layer("row_conv", parents=[inp], name=name)
    node._build = lambda ctx: F.row_conv(
        inp.to_var(ctx), context_len,
        param_attr=_pattr(param_attr, f"{node.name}.w0"),
        act=act_name(act) or None)
    return node


def roi_pool(input, rois, pooled_width, pooled_height, spatial_scale,
             num_channels=None, name=None):
    node = Layer("roi_pool", parents=[input, rois], name=name)

    def build(ctx):
        var, _s = _image_of(input, input.to_var(ctx), num_channels)
        return F.roi_pool(var, rois.to_var(ctx),
                          pooled_height=pooled_height,
                          pooled_width=pooled_width,
                          spatial_scale=spatial_scale)

    node._build = build
    return node


def priorbox(input, image, min_size, max_size=None, aspect_ratio=(1.0,),
             variance=(0.1, 0.1, 0.2, 0.2), num_channels=None,
             name=None):
    """SSD prior boxes; the variances tensor is exposed for
    get_output(.., 'variances') and consumed directly by
    detection_output/multibox_loss (reference: PriorBoxLayer)."""
    node = Layer("priorbox", parents=[input, image], name=name)

    def build(ctx):
        var, _s = _image_of(input, input.to_var(ctx), num_channels)
        img, _si = _image_of(image, image.to_var(ctx), None)
        boxes, variances = F.prior_box(
            var, img, min_sizes=list(_listify(min_size)),
            max_sizes=list(_listify(max_size)) if max_size else None,
            aspect_ratios=tuple(aspect_ratio),
            variance=tuple(variance))
        b2 = F.reshape(boxes, [-1, 4])
        ctx[(id(node), "variances")] = F.reshape(variances, [-1, 4])
        return b2

    node._build = build
    return node


def _prior_pair(ctx, pb):
    boxes = pb.to_var(ctx)
    return boxes, ctx[(id(pb), "variances")]


def detection_output(input_loc, input_conf, priorbox, num_classes=2,
                     name=None, **kw):
    """reference: DetectionOutputLayer -> detection_output op. Flat
    [bs, num_priors*4] loc and [bs, num_priors*C] conf inputs are
    reshaped against the priorbox count."""
    node = Layer("detection_output",
                 parents=[input_loc, input_conf, priorbox], name=name)

    def build(ctx):
        boxes, pvar = _prior_pair(ctx, priorbox)
        n_priors = int(boxes.shape[0])
        loc = F.reshape(input_loc.to_var(ctx), [-1, n_priors, 4])
        conf = F.reshape(input_conf.to_var(ctx),
                         [-1, n_priors, num_classes])
        return F.detection_output(loc, conf, boxes, pvar, **kw)

    node._build = build
    return node


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("hsigmoid", parents=[inp, label], name=name)
    node._build = lambda ctx: F.hsigmoid(
        inp.to_var(ctx), label.to_var(ctx), num_classes,
        param_attr=_pattr(param_attr, f"{node.name}.w0"))
    return node


def nce(input, label, num_classes, num_neg_samples=10, param_attr=None,
        bias_attr=None, name=None, **_kw):
    (inp,) = _listify(input)
    node = Layer("nce", parents=[inp, label], name=name)
    node._build = lambda ctx: F.nce(
        inp.to_var(ctx), label.to_var(ctx), num_classes,
        num_neg_samples=num_neg_samples,
        param_attr=_pattr(param_attr, f"{node.name}.w0"))
    return node


# ---------------------------------------------------------------------
# cost layers
# ---------------------------------------------------------------------

def crf(input, label, size=None, param_attr=None, name=None, **_kw):
    """Linear-chain CRF negative log-likelihood (reference: CRFLayer
    -> linear_chain_crf_op)."""
    node = Layer("crf", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(F.linear_chain_crf(
        input.to_var(ctx), label.to_var(ctx),
        param_attr=_pattr(param_attr, f"{node.name}.w0")))
    return node


def crf_decoding(input, size=None, label=None, param_attr=None,
                 name=None, **_kw):
    node = Layer("crf_decoding", parents=[input] + _listify(label),
                 name=name)
    node._build = lambda ctx: F.crf_decoding(
        input.to_var(ctx),
        param_attr=_pattr(param_attr, f"{node.name}.w0"),
        label=label.to_var(ctx) if label is not None else None)
    return node


def ctc(input, label, size=None, blank=0, norm_by_times=False,
        name=None, **_kw):
    """CTC cost (reference: CTCLayer / warp_ctc)."""
    node = Layer("ctc", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(F.warpctc(
        input.to_var(ctx), label.to_var(ctx), blank=blank,
        norm_by_times=norm_by_times))
    return node


def warp_ctc(input, label, size=None, blank=0, norm_by_times=False,
             name=None, **_kw):
    node = ctc(input, label, size=size, blank=blank,
               norm_by_times=norm_by_times, name=name)
    node.type = "warp_ctc"
    return node


def hinge_loss_cost(input, label, name=None):
    """reference: HuberTwoClassification sibling hinge family — kept
    for completeness of the cost vocabulary."""
    node = Layer("hinge_loss", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(_raw_op(
        "hinge_loss", {"Logits": input.to_var(ctx),
                       "Labels": label.to_var(ctx)},
        out_slots=("Loss",))["Loss"])
    return node


def huber_classification_cost(input, label, name=None, **_kw):
    """Margin-based two-class Huber (reference:
    HuberTwoClassification, CostLayer.cpp): with y = 2*label-1 and
    z = y*f: 0 when z >= 1, (1-z)^2 when -1 < z < 1, -4z when
    z <= -1 (continuous at z = -1)."""
    node = Layer("huber_classification", parents=[input, label],
                 name=name)

    def build(ctx):
        f = input.to_var(ctx)
        y = F.scale(label.to_var(ctx), scale=2.0, bias=-1.0)
        z = F.elementwise_mul(y, f)
        quad = F.square(F.relu(F.scale(z, scale=-1.0, bias=1.0)))
        lin = F.scale(z, scale=-4.0)
        neg_one = F.fill_constant_batch_size_like(z, list(z.shape),
                                                  "float32", -1.0)
        is_lin = F.cast(F.less_than(z, neg_one), "float32")
        loss = F.elementwise_add(
            F.elementwise_mul(is_lin, lin),
            F.elementwise_mul(F.scale(is_lin, scale=-1.0, bias=1.0),
                              quad))
        return F.mean(loss)

    node._build = build
    return node


def huber_regression_cost(input, label, delta=1.0, name=None, **_kw):
    """Huber regression with threshold `delta` (reference:
    HuberRegressionLoss): 0.5 d^2 for |d| <= delta, else
    delta*(|d| - 0.5*delta) — the huber_loss op implements exactly
    this."""
    node = Layer("huber_regression", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(F.huber_loss(
        input.to_var(ctx), label.to_var(ctx), delta=delta))
    return node


def smooth_l1_cost(input, label, name=None, **_kw):
    node = Layer("smooth_l1", parents=[input, label], name=name)
    node._build = lambda ctx: F.mean(F.smooth_l1(input.to_var(ctx),
                                                 label.to_var(ctx)))
    return node


def multi_binary_label_cross_entropy(input, label, name=None, **_kw):
    """Element-wise binary CE on sigmoid outputs (reference:
    MultiBinaryLabelCrossEntropy; v2 convention: input is already
    sigmoid-activated)."""
    node = Layer("multi_binary_label_cross_entropy",
                 parents=[input, label], name=name)

    def build(ctx):
        p = F.clip(input.to_var(ctx), 1e-7, 1.0 - 1e-7)
        y = label.to_var(ctx)
        pos = F.elementwise_mul(y, F.log(p))
        neg = F.elementwise_mul(F.scale(y, scale=-1.0, bias=1.0),
                                F.log(F.scale(p, scale=-1.0, bias=1.0)))
        return F.mean(F.scale(F.elementwise_add(pos, neg), scale=-1.0))

    node._build = build
    return node


def soft_binary_class_cross_entropy(input, label, name=None, **_kw):
    node = multi_binary_label_cross_entropy(input, label, name=name)
    node.type = "soft_binary_class_cross_entropy"
    return node


def multi_class_cross_entropy_with_selfnorm(
        input, label, softmax_selfnorm_alpha=0.1, name=None, **_kw):
    """CE + alpha * mean(log Z ^ 2) self-normalization penalty
    (reference: MultiClassCrossEntropyWithSelfNorm); input is raw
    logits here."""
    node = Layer("multi_class_cross_entropy_with_selfnorm",
                 parents=[input, label], name=name)

    def build(ctx):
        logits = input.to_var(ctx)
        ce = F.mean(F.softmax_with_cross_entropy(logits,
                                                 label.to_var(ctx)))
        log_z = F.log(F.reduce_sum(F.exp(logits), dim=-1,
                                   keep_dim=True))
        return F.elementwise_add(
            ce, F.scale(F.mean(F.square(log_z)),
                        scale=float(softmax_selfnorm_alpha)))

    node._build = build
    return node


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None):
    """Pairwise learning-to-rank cost over padded per-query score
    lists (reference: LambdaCost / LambdaRank). Pair (i, j) with
    yi > yj contributes |2^yi - 2^yj| / idealDCG * log(1+exp(sj-si)),
    where idealDCG sums the top-NDCG_num label gains at positions
    1..NDCG_num — the reference's NDCG truncation, computed in-graph
    via top_k instead of its host-side sort."""
    node = Layer("lambda_cost", parents=[input, score], name=name)

    def build(ctx):
        s = input.to_var(ctx)     # [bs, L] model scores
        y = score.to_var(ctx)     # [bs, L] relevance labels
        l = int(s.shape[-1])
        k = max(1, min(NDCG_num, l))
        s_i = F.reshape(s, [-1, l, 1])
        s_j = F.reshape(s, [-1, 1, l])
        y_i = F.reshape(y, [-1, l, 1])
        y_j = F.reshape(y, [-1, 1, l])
        # log(1 + exp(-(si - sj))) for pairs with yi > yj
        diff = F.elementwise_sub(s_i, s_j)
        pair_loss = F.log(F.scale(F.exp(F.scale(diff, scale=-1.0)),
                                  bias=1.0))
        ln2 = float(np.log(2.0))
        gain = F.abs(F.elementwise_sub(
            F.exp(F.scale(y_i, scale=ln2)),
            F.exp(F.scale(y_j, scale=ln2))))
        order = F.cast(F.greater_than(y_i, y_j), "float32")
        weighted = F.elementwise_mul(F.elementwise_mul(pair_loss, gain),
                                     order)
        # ideal DCG over the top-k labels: sum (2^y - 1) / log2(pos+2)
        y_top = _raw_op("top_k", {"X": y}, attrs={"k": k},
                        out_slots=("Out", "Indices"))["Out"]
        disc = F.assign(np.asarray(
            [1.0 / np.log2(p + 2.0) for p in range(k)], np.float32))
        idcg = F.reduce_sum(F.elementwise_mul(
            F.scale(F.exp(F.scale(y_top, scale=ln2)), bias=-1.0), disc),
            dim=-1, keep_dim=True)
        per_query = F.elementwise_div(
            F.reduce_sum(weighted, dim=[1, 2], keep_dim=False),
            F.scale(F.reshape(idcg, [-1]), bias=1e-6))
        return F.mean(per_query)

    node._build = build
    return node


def cross_entropy_over_beam(input, label, name=None, **_kw):
    """Beam-level cross-entropy: -log softmax over candidate scores at
    the gold index (reference: CrossEntropyOverBeam — realized on the
    padded per-sample beam-score matrix; the reference's multi-pass
    beam expansion is subsumed by beam_search + this cost)."""
    node = Layer("cross_entropy_over_beam", parents=[input, label],
                 name=name)
    node._build = lambda ctx: F.mean(F.softmax_with_cross_entropy(
        input.to_var(ctx), label.to_var(ctx)))
    return node


def multibox_loss(input_loc, input_conf, priorbox, label_box,
                  label_class, num_classes=2, name=None, **kw):
    """SSD MultiBox loss (reference: MultiBoxLossLayer -> ssd_loss).
    Flat v2 inputs are reshaped against the priorbox count: loc
    [bs, P*4], conf [bs, P*C], gt boxes [bs, G*4], gt labels [bs, G]."""
    node = Layer("multibox_loss",
                 parents=[input_loc, input_conf, priorbox,
                          label_box, label_class], name=name)

    def build(ctx):
        boxes, pvar = _prior_pair(ctx, priorbox)
        n_priors = int(boxes.shape[0])
        loc = F.reshape(input_loc.to_var(ctx), [-1, n_priors, 4])
        conf = F.reshape(input_conf.to_var(ctx),
                         [-1, n_priors, num_classes])
        gt_flat = label_box.to_var(ctx)
        n_gt = int(gt_flat.shape[-1]) // 4
        gt = F.reshape(gt_flat, [-1, n_gt, 4])
        gl = F.reshape(label_class.to_var(ctx), [-1, n_gt])
        return F.mean(F.ssd_loss(loc, conf, gt, gl, boxes, pvar, **kw))

    node._build = build
    return node


def sum_cost(input, name=None):
    (inp,) = _listify(input)
    node = Layer("sum_cost", parents=[inp], name=name)
    node._build = lambda ctx: F.reduce_sum(inp.to_var(ctx))
    return node


# ---------------------------------------------------------------------
# the full 103-type vocabulary -> runnable constructor map (audited by
# tests/test_v2_layer_types_runnable.py; reference REGISTER_LAYER names)
# ---------------------------------------------------------------------

LAYER_TYPE_CONSTRUCTORS = {
    "addto": addto, "agent": recurrent_group, "average": pooling,
    "batch_norm": batch_norm, "bilinear_interp": bilinear_interp,
    "blockexpand": block_expand, "clip": clip_layer, "concat": concat,
    "concat2": concat, "conv3d": conv3d, "conv_shift": conv_shift,
    "convex_comb": linear_comb, "cos": cos_sim, "cos_vm": cos_sim,
    "crf": crf, "crf_decoding": crf_decoding, "crop": crop,
    "cross_entropy_over_beam": cross_entropy_over_beam, "ctc": ctc,
    "cudnn_batch_norm": batch_norm, "cudnn_conv": img_conv,
    "cudnn_convt": img_conv, "data": data, "data_norm": data_norm,
    "deconv3d": deconv3d, "detection_output": detection_output,
    "dot_prod": dot_prod, "eos_id": eos, "exconv": img_conv,
    "exconvt": img_conv, "expand": expand,
    "factorization_machine": factorization_machine, "fc": fc,
    "featmap_expand": expand, "gated_recurrent": gru,
    "gather_agent": recurrent_group, "get_output": get_output,
    "gru_step": gru_step, "hsigmoid": hsigmoid,
    "huber_classification": huber_classification_cost,
    "huber_regression": huber_regression_cost,
    "interpolation": interpolation, "kmax_seq_score": kmax_seq_score,
    "l2_distance": l2_distance, "lambda_cost": lambda_cost,
    "lstm_step": lstm_step, "lstmemory": lstmemory, "max": pooling,
    "maxid": max_id, "maxout": maxout, "mdlstmemory": mdlstmemory,
    "mixed": mixed, "mkl_packed_recurrent": recurrent,
    "mkldnn_addto": addto, "mkldnn_batch_norm": batch_norm,
    "mkldnn_concat": concat, "mkldnn_conv": img_conv,
    "mkldnn_fc": fc, "mkldnn_lrn": img_cmrnorm,
    "mkldnn_pool": img_pool,
    "multi_binary_label_cross_entropy": multi_binary_label_cross_entropy,
    "multi_class_cross_entropy_with_selfnorm":
        multi_class_cross_entropy_with_selfnorm,
    "multibox_loss": multibox_loss, "multiplex": multiplex, "nce": nce,
    "out_prod": out_prod, "pad": pad, "pool3d": pool3d,
    "power": power, "prelu": prelu, "print": print_layer,
    "priorbox": priorbox, "recurrent": recurrent,
    "recurrent_layer_group": recurrent_group, "resize": resize,
    "roi_pool": roi_pool, "rotate": rotate, "row_conv": row_conv,
    "row_l2_norm": row_l2_norm, "sampling_id": sampling_id,
    "scale_shift": scale_shift,
    "scale_sub_region": scale_sub_region, "scaling": scaling,
    "scatter_agent": recurrent_group, "selective_fc": selective_fc,
    "seq_slice": seq_slice, "seqconcat": seq_concat,
    "seqlastins": last_seq, "seqreshape": seq_reshape,
    "slope_intercept": slope_intercept, "smooth_l1": smooth_l1_cost,
    "soft_binary_class_cross_entropy": soft_binary_class_cross_entropy,
    "spp": spp, "square_error": square_error_cost,
    "sub_nested_seq": sub_nested_seq, "subseq": sub_seq,
    "sum_cost": sum_cost, "sum_to_one_norm": sum_to_one_norm,
    "switch_order": switch_order, "tensor": tensor_layer,
    "trans": trans, "upsample": upsample, "warp_ctc": warp_ctc,
}


# ---------------------------------------------------------------------
# parse_network — the reference returns the emitted ModelConfig proto;
# here the equivalent artifact is a summary of the lowered Program.
# ---------------------------------------------------------------------

def parse_network(*outputs):
    """Lower the graphs reachable from `outputs` into a throwaway
    Program and return a ModelConfig-shaped summary dict — exactly
    Topology.proto(), which owns the summary shape."""
    from .topology import Topology

    outs = []
    for o in outputs:
        outs.extend(_listify(o))
    return Topology(outs).proto()


__all__ = [
    "AggregateLevel", "ExpandLevel", "data", "fc", "embedding",
    "img_conv", "img_pool", "batch_norm", "img_cmrnorm",
    "sum_to_one_norm", "maxout", "spp", "dropout", "addto", "concat",
    "cos_sim", "conv_shift", "max_id", "pooling", "last_seq",
    "first_seq", "lstmemory", "gru", "grumemory", "expand",
    "classification_cost", "cross_entropy_cost", "square_error_cost",
    "mse_cost", "regression_cost", "parse_network",
    # full-vocabulary constructors (round 5)
    "bilinear_interp", "block_expand", "clip_layer", "conv3d",
    "deconv3d", "pad", "pool3d", "rotate", "switch_order", "crop",
    "upsample", "resize", "scale_sub_region", "prelu", "mixed",
    "dot_prod", "out_prod", "l2_distance", "linear_comb",
    "interpolation", "scaling", "scale_shift", "slope_intercept",
    "power", "trans", "tensor_layer", "selective_fc",
    "factorization_machine", "data_norm", "seq_concat", "seq_slice",
    "sub_seq", "seq_reshape", "sub_nested_seq", "kmax_seq_score",
    "eos", "mdlstmemory", "lstm_step", "gru_step", "get_output",
    "StaticInput", "memory", "recurrent_group", "recurrent",
    "multiplex", "sampling_id", "print_layer", "row_l2_norm",
    "row_conv", "roi_pool", "priorbox", "detection_output",
    "hsigmoid", "nce", "crf", "crf_decoding", "ctc", "warp_ctc",
    "huber_classification_cost", "huber_regression_cost",
    "smooth_l1_cost", "multi_binary_label_cross_entropy",
    "soft_binary_class_cross_entropy",
    "multi_class_cross_entropy_with_selfnorm", "lambda_cost",
    "cross_entropy_over_beam", "multibox_loss", "sum_cost",
    "hinge_loss_cost", "LAYER_TYPE_CONSTRUCTORS",
]
