"""v2 Parameters (reference: python/paddle/v2/parameters.py:44 — a
dict-like view of the GradientMachine's parameter blocks with numpy
get/set and tar (de)serialization).

TPU-native: Parameters owns a private Scope holding the initialized
jax arrays; the trainer and inference run programs against that scope,
so numpy reads/writes here are reads/writes of the live training
state. to_tar/from_tar keep the reference's "parameters travel as one
archive" capability (numpy .npy members inside a tar)."""
from __future__ import annotations

import io as _io
import tarfile
from typing import Dict, List

import numpy as np


def create(layers):
    """parameters.create(cost_or_output_layers) (reference
    parameters.py:27)."""
    from .topology import Topology
    topo = layers if isinstance(layers, Topology) else Topology(layers)
    return Parameters(topo)


class Parameters:
    def __init__(self, topology=None):
        from ..core.scope import Scope
        self._scope = Scope()
        self._shapes: Dict[str, tuple] = {}
        if topology is not None:
            import paddle_tpu as pt
            main, startup, _ = topology.programs()
            pt.Executor().run(startup, scope=self._scope)
            for p in main.all_parameters():
                self._shapes[p.name] = tuple(p.shape)

    # -- dict-like surface (reference parameters.py:108-271) ----------
    def keys(self) -> List[str]:
        return list(self._shapes)

    def names(self) -> List[str]:
        return self.keys()

    def has_key(self, key) -> bool:
        return key in self._shapes

    def __contains__(self, key) -> bool:
        return key in self._shapes

    def __iter__(self):
        return iter(self.keys())

    def __len__(self):
        return len(self._shapes)

    def __getitem__(self, key) -> np.ndarray:
        if key not in self._shapes:
            raise ValueError(f"no parameter {key!r}")
        return np.asarray(self._scope.get(key))

    def get(self, key) -> np.ndarray:
        return self[key]

    def get_shape(self, key):
        if key not in self._shapes:
            raise ValueError(f"no parameter {key!r}")
        return self._shapes[key]

    def __setitem__(self, key, value) -> None:
        value = np.asarray(value, dtype=np.float32)
        shape = self._shapes.get(key)
        if shape is not None and tuple(value.shape) != tuple(shape):
            raise ValueError(
                f"shape mismatch for {key!r}: expected {shape}, got "
                f"{value.shape}")
        self._shapes.setdefault(key, tuple(value.shape))
        self._scope.set(key, value)

    def set(self, key, value) -> None:
        self[key] = value

    # -- serialization (reference to_tar/from_tar, parameters.py:328) --
    def to_tar(self, f) -> None:
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.keys():
                buf = _io.BytesIO()
                np.save(buf, self[name], allow_pickle=False)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name + ".npy")
                info.size = len(data)
                tar.addfile(info, _io.BytesIO(data))

    @staticmethod
    def from_tar(f) -> "Parameters":
        params = Parameters()
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                if not member.name.endswith(".npy"):
                    continue
                arr = np.load(
                    _io.BytesIO(tar.extractfile(member).read()),
                    allow_pickle=False)
                params[member.name[:-4]] = arr
        return params

    def init_from_tar(self, f, exclude_params=()) -> None:
        other = Parameters.from_tar(f)
        for name in other.keys():
            if name in exclude_params:
                continue
            self[name] = other[name]

    # -- trainer integration ------------------------------------------
    @property
    def scope(self):
        return self._scope

    def adopt(self, main_program) -> None:
        """Record any parameters of `main_program` not yet tracked
        (e.g. when the trainer lowers a wider graph than create saw)."""
        for p in main_program.all_parameters():
            self._shapes.setdefault(p.name, tuple(p.shape))
