"""v2 attribute objects (reference: python/paddle/v2/attr.py re-exports
ParameterAttribute/ExtraLayerAttribute). Param carries the fields v2
scripts actually set; it converts to the framework ParamAttr."""
from __future__ import annotations

from ..param_attr import ParamAttr
from ..initializer import NormalInitializer, UniformInitializer
from ..regularizer import L1DecayRegularizer, L2DecayRegularizer


class ParameterAttribute:
    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=1.0,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, initializer=None):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.sparse_update = sparse_update
        self.initializer = initializer

    def to_param_attr(self) -> ParamAttr:
        init = self.initializer
        if init is None and (self.initial_std is not None
                             or self.initial_mean is not None):
            init = NormalInitializer(loc=self.initial_mean or 0.0,
                                     scale=self.initial_std
                                     if self.initial_std is not None
                                     else 0.01)
        elif init is None and (self.initial_max is not None
                               or self.initial_min is not None):
            init = UniformInitializer(low=self.initial_min or -1.0,
                                      high=self.initial_max or 1.0)
        if self.l1_rate and self.l2_rate:
            raise NotImplementedError(
                "simultaneous l1_rate and l2_rate on one parameter is "
                "not supported — ParamAttr carries one regularizer; "
                "pick one (the reference applies both)")
        reg = (L1DecayRegularizer(self.l1_rate) if self.l1_rate
               else L2DecayRegularizer(self.l2_rate)
               if self.l2_rate else None)
        return ParamAttr(name=self.name, initializer=init,
                         learning_rate=self.learning_rate,
                         regularizer=reg,
                         trainable=not self.is_static)


class ExtraLayerAttribute:
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


Param = ParameterAttribute
Extra = ExtraLayerAttribute
Hook = object  # reference HookAttribute placeholder (pruning hooks)

__all__ = ["Param", "Extra", "Hook", "ParameterAttribute",
           "ExtraLayerAttribute"]
