"""paddle.v2-compatible API facade (reference: python/paddle/v2/
__init__.py — layer-object graphs + Topology + Parameters + SGD event
trainer + infer, the OTHER of the two coexisting stacks).

TPU-native stance (SURVEY §0): v2 is a capability surface, not a second
engine. Every v2 layer lowers onto the same Program/XLA pipeline the
fluid-style API uses; Parameters is a scope view; the trainer is the
same jit-compiled Executor step behind the reference's event loop.
"""
from __future__ import annotations

import os

from . import activation  # noqa: F401
from . import attr  # noqa: F401
from . import config_base  # noqa: F401
from . import data_type  # noqa: F401
from . import evaluator  # noqa: F401
from . import event  # noqa: F401
from . import inference  # noqa: F401
from . import layer  # noqa: F401
from . import minibatch  # noqa: F401
from . import networks  # noqa: F401
from . import op  # noqa: F401  (registers Layer arithmetic operators)
from . import optimizer  # noqa: F401
from . import parameters  # noqa: F401
from . import pooling  # noqa: F401
from . import topology  # noqa: F401
from . import trainer  # noqa: F401

# data plumbing is shared with the modern API (one implementation)
from .. import dataset  # noqa: F401
from .. import reader  # noqa: F401
from .. import data_feeder  # noqa: F401
from ..dataset import image  # noqa: F401
from ..debug import Ploter  # noqa: F401
# the reference's v2 __all__ also re-exports the program getters
from ..framework import default_main_program  # noqa: F401
from ..framework import default_startup_program  # noqa: F401


class _PlotModule:
    Ploter = Ploter


plot = _PlotModule()


class _MasterModule:
    """v2.master.client (reference: python/paddle/v2/master/client.py
    — ctypes client of the Go master). The TPU-native master service
    lives in distributed/master.py; its client class is re-exported
    here."""
    try:
        from ..distributed.master import MasterClient as client
    except ImportError:  # pragma: no cover
        client = None


master = _MasterModule()

infer = inference.infer
batch = minibatch.batch


def init(**kwargs) -> None:
    """paddle.v2.init(use_gpu=..., trainer_count=...) (reference:
    v2/__init__.py:127 — boots the legacy C++ runtime). The XLA runtime
    needs no boot; PADDLE_INIT_* env vars keep their meaning for the
    distributed contract (distributed/multihost.py reads them)."""
    for ek, ev in os.environ.items():
        if ek.startswith("PADDLE_INIT_"):
            kwargs.setdefault(ek.replace("PADDLE_INIT_", "").lower(),
                              ev)
    # accepted-and-recorded; nothing to boot
    init.last_args = dict(kwargs)


__all__ = [
    "optimizer", "layer", "activation", "parameters", "init",
    "trainer", "event", "data_type", "attr", "pooling", "dataset",
    "reader", "topology", "networks", "infer", "plot", "evaluator",
    "image", "master", "batch",
]
