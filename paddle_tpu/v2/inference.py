"""v2 inference (reference: python/paddle/v2/inference.py — Inference
wraps a forward-only GradientMachine over a Topology + Parameters;
infer() feeds batches and concatenates outputs)."""
from __future__ import annotations

from typing import Optional

import numpy as np

from .parameters import Parameters
from .topology import Topology, build_feeder, sync_startup_state


class Inference:
    def __init__(self, output_layer, parameters: Parameters):
        import paddle_tpu as pt
        self._topology = Topology(output_layer)
        self._parameters = parameters
        # inference mode: BN moving stats, dropout identity
        self._main, startup, self._fetches = \
            self._topology.programs(is_test=True)
        # materialize any non-parameter persistables (e.g. BN stats)
        # the forward graph needs but the tar didn't carry
        sync_startup_state(parameters.scope, startup)
        self._exe = pt.Executor()

    def _feeder(self, feeding: Optional[dict]):
        return build_feeder(self._topology, self._main, feeding)

    def infer(self, input, feeding=None) -> np.ndarray:
        feeder = self._feeder(feeding)
        outs = []
        fetch_vars = [self._fetches[o.name]
                      for o in self._topology.outputs]
        for batch in _batches(input):
            res = self._exe.run(self._main, feed=feeder.feed(batch),
                                fetch_list=fetch_vars,
                                scope=self._parameters.scope)
            outs.append([_to_array(r) for r in res])
        if len(fetch_vars) == 1:
                return np.concatenate([o[0] for o in outs], axis=0)
        # multiple output layers: tuple of concatenated arrays
        return tuple(np.concatenate([o[i] for o in outs], axis=0)
                     for i in range(len(fetch_vars)))


def _to_array(r) -> np.ndarray:
    """Fetched value -> ndarray: LoDTensor fetches (ragged outputs)
    yield their flat step rows; scalar costs become 1-element rows so
    per-batch results stay concatenatable."""
    if hasattr(r, "data") and hasattr(r, "lod"):   # LoDTensor
        return np.asarray(r.data)
    return np.atleast_1d(np.asarray(r))


def _batches(input):
    """v2 infer() takes the WHOLE input as a list of samples; run it as
    one batch (callers wanting batching pass an iterable of lists).
    len() instead of truthiness: bool(ndarray) raises for >1 element."""
    if callable(input):
        yield from input()
    elif isinstance(input, np.ndarray):
        # a 2-D array is a batch of dense rows; wrap each row as a
        # one-slot sample tuple (bool(ndarray) raises, so arrays never
        # reach the list-shaped checks below)
        yield [(row,) for row in input]
    elif len(input) and isinstance(input[0], (list, tuple)) \
            and len(input[0]) and \
            isinstance(input[0][0], (list, tuple, np.ndarray, float, int)):
        yield input
    else:
        yield input


def infer(output_layer, parameters, input, feeding=None, field="value"):
    if field != "value":
        raise NotImplementedError(
            "field='value' is the supported v2 infer field (ids come "
            "from max_id layers)")
    return Inference(output_layer, parameters).infer(input,
                                                     feeding=feeding)
