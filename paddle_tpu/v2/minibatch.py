"""v2 minibatch (reference: python/paddle/v2/minibatch.py:18)."""
from __future__ import annotations

from ..reader import batch  # noqa: F401  (same semantics, one impl)

__all__ = ["batch"]
