"""Streaming input plane: a sharded multi-process input service.

The TensorFlow-paper input-pipeline story (PAPERS.md) rebuilt on this
repo's reader/resilience/observability stack: recordio shards are
divided across N worker PROCESSES (decode + block shuffle off the
trainer host path), finished fixed-shape batches stream back through
shared-memory ring slots (the `multiprocess.py` transport), and the
consumer performs an exact deterministic merge so the delivered stream
is **bit-identical to a single-process reader** — across worker counts,
elastic rescales, worker crashes, and mid-epoch checkpoint/restore.

Determinism contract
--------------------
Every shard yields a deterministic batch stream: records are read
sequentially in blocks of ``shuffle_block_batches * batch_size``
records, each block is shuffled with a seed derived from
``(seed, shard, epoch, block)``, and consecutive ``batch_size`` groups
become batches (the trailing partial batch of a shard-epoch is
dropped — fixed shapes only). The global stream is the k-way merge of
all shard streams ordered by ``(epoch, batch_no, shard)``. Workers
produce their shards' batches in exactly that order restricted to their
shards, and the consumer delivers in the full order — so
``iter_stream(cfg)`` (single process, no workers) and
``StreamingInputService(cfg).reader()`` yield identical sequences.

That ordering is also the liveness argument: a worker's
produced-but-undelivered slots are always the globally-next batches of
its own shards, so the consumer can always deliver the earliest of them
and hand the slot back — bounded memory (``slots_per_worker`` per
worker), no deadlock.

Cursors and resume
------------------
The delivery state is one pointer per shard — ``(epoch, next_batch)``
— plus the learned per-shard batch totals. ``state_for(k)`` returns the
state after ``k`` delivered batches (the Trainer checkpoints it beside
the weights via ``CheckpointConfig``; the FeedPrefetcher may have
pulled further ahead — snapshots are kept per delivery so the
checkpoint records the *trained* position). ``restore(state)`` seeds a
fresh service (or the single-process ``iter_stream``) to continue the
stream with no replayed and no skipped record.

Elasticity and resilience
-------------------------
The pool scales from live delivery stats: a window where more than
``scale_up_starved`` of deliveries found the queue dry spawns a worker;
a window with zero starvation and a full queue retires one. A rescale
is a pool restart from the delivered cursor (shards are repartitioned),
invisible in the delivered stream. A worker that dies — crash, OOM,
injected ``reader.shard`` fault — is detected, its ring is salvaged,
and it is respawned from the delivered cursor (at most ``max_respawns``
times service-wide); batches already in flight are deduplicated, so the
stream stays exact.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import queue as _queue
import threading
import time
import traceback
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from multiprocessing import connection as mp_connection

from .multiprocess import (_EscapedSegment, ensure_resource_tracker,
                           new_shm_segment)

__all__ = ["StreamingConfig", "StreamingInputService", "iter_stream",
           "RawDecoder"]


class RawDecoder:
    """Picklable fixed-layout record decoder: splits each record into
    consecutive fixed-shape fields (e.g. ``[((1,), "int64"),
    ((3, 224, 224), "uint8")]`` for an 8-byte label followed by a raw
    CHW image). Works under the "spawn" start method — instances pickle
    by value, so no module-level decode function is needed."""

    def __init__(self, fields):
        self.fields = [(tuple(s), np.dtype(d)) for s, d in fields]
        self.record_bytes = sum(
            int(np.prod(s, dtype=np.int64)) * d.itemsize
            for s, d in self.fields)

    def __call__(self, rec: bytes):
        if len(rec) != self.record_bytes:
            raise ValueError(
                f"record is {len(rec)} bytes but this decoder's layout "
                f"needs exactly {self.record_bytes}")
        out, off = [], 0
        for shape, dt in self.fields:
            n = int(np.prod(shape, dtype=np.int64))
            out.append(np.frombuffer(rec, dt, count=n,
                                     offset=off).reshape(shape))
            off += n * dt.itemsize
        return tuple(out)


def _env(name: str, default):
    """Registered-flag read coerced to the default's type (every name
    passed here is in flags.FLAGS; flags.get is the shared resolver)."""
    from .. import flags
    return type(default)(flags.get(name))


class StreamingConfig:
    """Picklable configuration shared by the service, its worker
    processes, and the single-process reference stream.

    decode:  module-level callable ``record_bytes -> sample`` (a tuple
             of fixed-shape ndarrays, or one ndarray). Must be
             picklable by reference under the "spawn" start method.
    collate: optional ``list-of-samples -> tuple-of-batched-ndarrays``;
             default stacks each field.
    feed_names: when set, delivered batches are feed DICTS
             ``{name: array}`` (the Trainer path); otherwise tuples.
    shuffle_block_batches: records are shuffled within blocks of this
             many batches (0 = sequential). Blocks are the resume
             granularity: restoring mid-block re-reads the block and
             skips already-delivered batches.
    """

    def __init__(self, shards: Sequence[str], batch_size: int,
                 decode: Callable, collate: Optional[Callable] = None,
                 feed_names: Optional[Sequence[str]] = None,
                 epochs: int = 1, seed: int = 0,
                 shuffle_block_batches: int = 0,
                 workers: Optional[int] = None,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 slots_per_worker: Optional[int] = None,
                 method: Optional[str] = None,
                 scale_interval_s: Optional[float] = None,
                 scale_up_starved: Optional[float] = None,
                 max_respawns: Optional[int] = None,
                 respawn_delay_s: float = 0.05):
        if not shards:
            raise ValueError("StreamingConfig needs at least one shard")
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.shards = [str(p) for p in shards]
        self.batch_size = int(batch_size)
        self.decode = decode
        self.collate = collate
        self.feed_names = tuple(feed_names) if feed_names else None
        self.epochs = int(epochs)
        self.seed = int(seed)
        self.shuffle_block_batches = int(shuffle_block_batches)
        self.workers = int(workers if workers is not None
                           else _env("PADDLE_TPU_INPUT_WORKERS", 2))
        self.min_workers = int(min_workers if min_workers is not None
                               else _env("PADDLE_TPU_INPUT_MIN_WORKERS", 1))
        self.max_workers = int(max_workers if max_workers is not None
                               else _env("PADDLE_TPU_INPUT_MAX_WORKERS", 4))
        self.slots_per_worker = int(
            slots_per_worker if slots_per_worker is not None
            else _env("PADDLE_TPU_INPUT_SLOTS", 4))
        self.method = str(method if method is not None
                          else _env("PADDLE_TPU_INPUT_START_METHOD",
                                    "spawn"))
        self.scale_interval_s = float(
            scale_interval_s if scale_interval_s is not None
            else _env("PADDLE_TPU_INPUT_SCALE_INTERVAL_S", 2.0))
        self.scale_up_starved = float(
            scale_up_starved if scale_up_starved is not None
            else _env("PADDLE_TPU_INPUT_SCALE_UP_STARVED", 0.25))
        self.max_respawns = int(max_respawns if max_respawns is not None
                                else _env("PADDLE_TPU_INPUT_MAX_RESPAWNS",
                                          3))
        self.respawn_delay_s = float(respawn_delay_s)
        if self.min_workers < 1 or self.max_workers < self.min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{self.min_workers}..{self.max_workers}")
        if self.slots_per_worker < 2:
            # one slot being written while one is undelivered is the
            # minimum for any overlap at all
            raise ValueError("slots_per_worker must be >= 2")


# -- deterministic per-shard stream (shared by workers and reference) -------

def _block_rng(seed: int, shard: int, epoch: int, block: int):
    h = zlib.crc32(f"{seed}:{shard}:{epoch}:{block}".encode())
    return np.random.RandomState(h & 0x7FFFFFFF)


def _default_collate(samples):
    first = samples[0]
    if not isinstance(first, tuple):
        return (np.stack(samples),)
    return tuple(np.stack([s[i] for s in samples])
                 for i in range(len(first)))


def _shard_stream(cfg: StreamingConfig, shard: int,
                  start_epoch: int = 0, start_batch: int = 0):
    """Deterministic batch stream of one shard: yields
    ``("batch", epoch, batch_no, arrays)`` in order, and
    ``("eof", epoch, total_batches)`` after each epoch's last batch.
    Resumable at any ``(epoch, batch)``: fully-consumed shuffle blocks
    are skipped without decoding; a partially-delivered block is
    re-read and its delivered batches skipped."""
    from .. import recordio
    from ..resilience import faults

    bs = cfg.batch_size
    bb = max(1, cfg.shuffle_block_batches)
    block_recs = bb * bs
    path = cfg.shards[shard]
    for epoch in range(start_epoch, cfg.epochs):
        sb = start_batch if epoch == start_epoch else 0
        skip_blocks = sb // bb
        bno = skip_blocks * bb
        block_no = skip_blocks
        with recordio.Scanner(path) as sc:
            if skip_blocks:
                sc.skip(skip_blocks * block_recs)
            it = iter(sc)
            while True:
                recs = list(itertools.islice(it, block_recs))
                if not recs:
                    break
                if cfg.shuffle_block_batches > 0:
                    order = _block_rng(cfg.seed, shard, epoch,
                                       block_no).permutation(len(recs))
                    recs = [recs[i] for i in order]
                for j in range(len(recs) // bs):
                    if bno < sb:
                        bno += 1
                        continue
                    samples = [cfg.decode(r)
                               for r in recs[j * bs:(j + 1) * bs]]
                    arrays = (cfg.collate(samples) if cfg.collate
                              else _default_collate(samples))
                    faults.fire("reader.shard")
                    yield ("batch", epoch, bno, arrays)
                    bno += 1
                block_no += 1
                if len(recs) < block_recs:
                    break  # final partial block: trailing partial batch dropped
        yield ("eof", epoch, bno)


def _merged(cfg: StreamingConfig, starts: Dict[int, Tuple[int, int]]):
    """k-way merge of the given shards' streams by (epoch, batch, shard)
    — THE global delivery order. ``starts`` maps shard -> (epoch,
    batch); shards past cfg.epochs are omitted by the caller."""
    gens, pending = {}, {}
    for s, (e0, b0) in starts.items():
        if e0 >= cfg.epochs:
            continue
        g = _shard_stream(cfg, s, e0, b0)
        item = next(g, None)
        if item is not None:
            gens[s], pending[s] = g, item
    while pending:
        s = min(pending, key=lambda t: (pending[t][1], pending[t][2], t))
        yield s, pending[s]
        nxt = next(gens[s], None)
        if nxt is None:
            del gens[s], pending[s]
        else:
            pending[s] = nxt


def _as_feed(cfg: StreamingConfig, arrays):
    if cfg.feed_names is not None:
        if len(cfg.feed_names) != len(arrays):
            raise ValueError(
                f"decode produced {len(arrays)} fields but feed_names "
                f"has {len(cfg.feed_names)} entries")
        return dict(zip(cfg.feed_names, arrays))
    return arrays


def _starts_from_state(cfg: StreamingConfig,
                       state: Optional[dict]) -> Dict[int, Tuple[int, int]]:
    starts = {s: (0, 0) for s in range(len(cfg.shards))}
    if state:
        _check_state(cfg, state)
        for s_str, (e, b) in state["shards"].items():
            starts[int(s_str)] = (int(e), int(b))
    return starts


def _check_state(cfg: StreamingConfig, state: dict):
    want = {"nshards": len(cfg.shards), "batch_size": cfg.batch_size,
            "seed": cfg.seed,
            "shuffle_block_batches": cfg.shuffle_block_batches,
            "epochs": cfg.epochs}
    got = state.get("config", {})
    for k, v in want.items():
        if got.get(k) != v:
            raise ValueError(
                f"input-state mismatch: checkpoint has {k}={got.get(k)!r}"
                f" but this config has {v!r} — the cursor is only valid "
                "for the stream parameters it was taken under")


def iter_stream(cfg: StreamingConfig, state: Optional[dict] = None):
    """Single-process reference stream: yields EXACTLY the batches, in
    exactly the order, the multi-process service delivers — the
    bit-identity baseline and the no-worker fallback."""
    for _s, item in _merged(cfg, _starts_from_state(cfg, state)):
        if item[0] == "batch":
            yield _as_feed(cfg, item[3])


# -- worker process ---------------------------------------------------------

def _service_worker_main(wid, specs, cfg, slots, free_q, out_q, stop_ev,
                         consumer_pid):
    """One worker: produce the merged stream of its shards (delivery
    order restricted to them) into a shared-memory ring. specs:
    [(shard, start_epoch, start_batch)].

    Each worker OWNS its result queue: a worker SIGKILLed mid-put can
    wedge only its own queue's write lock, never the siblings' — the
    consumer simply stops reading a retired incarnation's queue."""
    shms: List = []
    layout = None
    try:
        starts = {s: (e0, b0) for s, e0, b0 in specs}
        for s, item in _merged(cfg, starts):
            if stop_ev.is_set():
                return
            if item[0] == "eof":
                out_q.put(("eof", wid, s, item[1], item[2]))
                continue
            _, epoch, bno, batch = item
            arrays = tuple(np.ascontiguousarray(a) for a in batch)
            lay = [(a.shape, str(a.dtype)) for a in arrays]
            if layout is None:
                layout = lay
                total = sum(a.nbytes for a in arrays)
                shms = [new_shm_segment(total, consumer_pid)
                        for _ in range(slots)]
                out_q.put(("meta", wid,
                            [m.name for m in shms], layout))
                for i in range(slots):
                    free_q.put(i)
            elif lay != layout:
                raise ValueError(
                    f"shard {s} produced batch layout {lay} but this "
                    f"service's ring is sized for {layout}: all shards "
                    "of one service must share a fixed batch schema")
            while True:
                try:
                    slot = free_q.get(timeout=0.2)
                    break
                except _queue.Empty:
                    if stop_ev.is_set():
                        return
            buf = shms[slot].buf
            off, dst = 0, None
            for a in arrays:
                dst = np.frombuffer(buf, dtype=a.dtype, count=a.size,
                                    offset=off).reshape(a.shape)
                np.copyto(dst, a)
                off += a.nbytes
            del dst, buf  # live exports block shm.close() later
            out_q.put(("batch", wid, s, epoch, bno, slot))
    except BaseException:  # noqa: BLE001 — surfaced via respawn/raise
        try:
            out_q.put(("error", wid, traceback.format_exc()[-4000:]))
        except BaseException:
            pass
    finally:
        try:
            # hold the ring until every slot id is back (the consumer
            # releases each slot as it delivers its batch)
            returned = 0
            while shms and returned < slots and not stop_ev.is_set():
                try:
                    free_q.get(timeout=0.2)
                    returned += 1
                except _queue.Empty:
                    if stop_ev.is_set():
                        break
            for m in shms:
                try:
                    m.close()
                except BufferError:
                    pass
                try:
                    m.unlink()
                except FileNotFoundError:
                    pass
        except BaseException:
            pass
        try:
            out_q.put(("done", wid))
        except BaseException:
            pass


# -- the service ------------------------------------------------------------

class StreamingInputService:
    """Sharded multi-process input service (module docstring has the
    full story). Single consumer: one `reader()` iteration at a time.
    Lifecycle: lazily starts its worker pool on first `reader()` pull;
    `stop()` (or the context manager) tears it down; `restore(state)`
    must run before the pool starts."""

    #: Trainer.train duck-types on this to route reader= through the
    #: service path (cursor checkpointing, live input metrics).
    is_streaming_input_service = True

    def __init__(self, config: Optional[StreamingConfig] = None, **kw):
        self.cfg = config if config is not None else StreamingConfig(**kw)
        n = len(self.cfg.shards)
        self._e = {s: 0 for s in range(n)}      # per-shard epoch pointer
        self._b = {s: 0 for s in range(n)}      # per-shard next batch
        self._fin: set = set()                  # shards past cfg.epochs
        self._totals: Dict[int, int] = {}       # learned batches/epoch
        self._delivered = 0
        # cursor reconstruction: per delivery we log only the CHANGED
        # shard pointer (delivered_no, shard, prev_epoch, prev_batch) —
        # state_for(k) rebuilds the k-delivery state by walking the
        # tail of this log backwards from the live pointers, so the
        # hot path never materializes a full O(n_shards) snapshot
        self._snap_log: deque = deque(maxlen=4096)
        self._snap_lock = threading.Lock()
        self._started = False
        self._stopped = False
        self._fatal: Optional[BaseException] = None
        self._respawns = 0
        self._scale_events = {"up": 0, "down": 0}
        self._next_wid = 0
        self._workers: Dict[int, dict] = {}
        self._rings: Dict[int, tuple] = {}      # wid -> (shms, views, label)
        self._buffer: Dict[tuple, tuple] = {}   # (e,b,s) -> entry
        self._ctx = None
        self._stop_ev = None
        self._last_liveness = 0.0
        # elastic-scaling window
        self._win_t0 = time.monotonic()
        self._win_deliv = 0
        self._win_starved = 0
        self._win_min_occ = None
        self._metrics = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "StreamingInputService":
        if self._stopped:
            raise RuntimeError("service already stopped")
        if self._started:
            return self
        ensure_resource_tracker()
        self._ctx = mp.get_context(self.cfg.method)
        self._stop_ev = self._ctx.Event()
        self._init_metrics()
        self._spawn_pool(self.cfg.workers)
        self._started = True
        return self

    def stop(self, timeout: float = 5.0):
        """Stop workers, reclaim rings, unlink shared memory. Idempotent."""
        if not self._started or self._stopped:
            self._stopped = True
            return
        self._stop_ev.set()
        for w in self._workers.values():
            w["proc"].join(timeout)
        for w in self._workers.values():
            if w["proc"].is_alive():
                w["proc"].terminate()
                w["proc"].join(1.0)
        # pull whatever made it into the queues so stale metas get
        # attached and unlinked rather than leaked
        for w in list(self._workers.values()):
            self._drain_worker_queue(w)
        for wid in list(self._rings):
            self._retire_ring(wid)
        for w in self._workers.values():
            w["out_q"].close()
            w["out_q"].cancel_join_thread()
        self._workers.clear()
        self._stopped = True
        if self._metrics:
            self._metrics["workers"].set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def wait_ready(self, timeout: float = 30.0) -> bool:
        """Block until every live worker has announced its shared-memory
        ring — i.e. decoded its first batch and started prefilling
        slots. Keeps cold-start cost (spawn-method child imports, first
        decode) out of a latency-sensitive or measured first step.
        Returns False on timeout."""
        if not self._started:
            self.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._sweep()
            if all(w["finished"] or wid in self._rings
                   for wid, w in self._workers.items()):
                return True
            self._check_liveness()
            time.sleep(0.02)
        return False

    # -- cursor state --------------------------------------------------
    def _build_state(self, delivered: int, shards: dict,
                     totals: dict) -> dict:
        return {
            "v": 1,
            "delivered": delivered,
            "shards": {str(s): [e, b] for s, (e, b) in shards.items()},
            "totals": {str(s): t for s, t in totals.items()},
            "config": {"nshards": len(self.cfg.shards),
                       "batch_size": self.cfg.batch_size,
                       "seed": self.cfg.seed,
                       "shuffle_block_batches":
                           self.cfg.shuffle_block_batches,
                       "epochs": self.cfg.epochs},
        }

    def snapshot(self) -> dict:
        """Cursor state as of the last DELIVERED batch."""
        with self._snap_lock:
            return self._build_state(
                self._delivered,
                {s: (self._e[s], self._b[s])
                 for s in range(len(self.cfg.shards))},
                dict(self._totals))

    def state_for(self, delivered: int) -> dict:
        """Cursor state as of `delivered` batches handed out by THIS
        service instance — the Trainer checkpoints the state of its
        consumed count, which trails the prefetcher's pulls. The state
        is rebuilt by walking the per-delivery pointer log backwards
        from the live cursor; learned shard totals are time-invariant
        facts, so carrying them back is exact."""
        with self._snap_lock:
            now = self._delivered
            base = {s: (self._e[s], self._b[s])
                    for s in range(len(self.cfg.shards))}
            log = list(self._snap_log)
            totals = dict(self._totals)
        oldest = log[0][0] if log else now + 1
        if delivered > now or delivered < oldest - 1:
            raise KeyError(
                f"no reconstructable input state for "
                f"delivered={delivered} (current={now}, log reaches "
                f"back to {oldest - 1}; the last "
                f"{self._snap_log.maxlen} deliveries are retained)")
        for d, s, pe, pb in reversed(log):
            if d <= delivered:
                break
            base[s] = (pe, pb)
        return self._build_state(delivered, base, totals)

    def restore(self, state: dict):
        """Seed the delivery cursor from a checkpointed state. Must be
        called before the worker pool starts (i.e. before the first
        `reader()` pull)."""
        if self._started:
            raise RuntimeError(
                "restore() must run before the service starts — build a "
                "fresh StreamingInputService for a checkpoint resume")
        _check_state(self.cfg, state)
        for s_str, (e, b) in state["shards"].items():
            s = int(s_str)
            self._e[s], self._b[s] = int(e), int(b)
        self._totals = {int(s): int(t)
                        for s, t in state.get("totals", {}).items()}
        self._fin.clear()
        for s in range(len(self.cfg.shards)):
            if self._e[s] >= self.cfg.epochs:
                self._fin.add(s)
            self._advance(s)

    # -- delivery ------------------------------------------------------
    def reader(self):
        """Zero-arg reader (paddle convention): returns the iterator of
        remaining batches. Content/order are bit-identical to
        ``iter_stream`` at the same cursor, for any worker count."""
        if not self._started:
            self.start()
        return self._deliver()

    def _deliver(self):
        cfg = self.cfg
        nshards = len(cfg.shards)
        while True:
            if self._fatal is not None:
                raise self._fatal
            live = [s for s in range(nshards) if s not in self._fin]
            if not live:
                return
            s = min(live, key=lambda t: (self._e[t], self._b[t], t))
            tot = self._totals.get(s)
            if tot is not None and self._b[s] >= tot:
                with self._snap_lock:
                    self._advance(s)
                continue
            # ingest everything already readable so the occupancy the
            # scaler sees is the PRODUCED depth, not just what past
            # waits happened to pull in
            self._sweep()
            key = (self._e[s], self._b[s], s)
            starved = key not in self._buffer
            while key not in self._buffer:
                tot = self._totals.get(s)
                if tot is not None and self._b[s] >= tot:
                    break  # eof arrived while waiting: recompute shard
                self._pull()
            if key not in self._buffer:
                continue
            occ = len(self._buffer)
            arrays = self._materialize(self._buffer.pop(key))
            # pointer advance + delta log are atomic vs a concurrent
            # state_for() (the Trainer checkpoints from its own thread
            # while this generator runs on the prefetcher's)
            with self._snap_lock:
                prev = (self._e[s], self._b[s])
                self._b[s] += 1
                self._advance(s)
                self._delivered += 1
                self._snap_log.append(
                    (self._delivered, s, prev[0], prev[1]))
            self._account(starved, occ)
            yield _as_feed(cfg, arrays)

    def _materialize(self, entry):
        if entry[0] == "data":
            return entry[1]
        _, wid, slot = entry
        _shms, views, _label = self._rings[wid]
        arrays = tuple(np.array(v) for v in views[slot])
        w = self._workers.get(wid)
        if w is not None:
            w["free_q"].put(slot)
        return arrays

    def _advance(self, s):
        while s not in self._fin:
            tot = self._totals.get(s)
            if tot is None or self._b[s] < tot:
                return
            self._e[s] += 1
            self._b[s] = 0
            if self._e[s] >= self.cfg.epochs or tot == 0:
                self._fin.add(s)

    # -- queue plumbing ------------------------------------------------
    def _pull(self, timeout: float = 0.5):
        """Receive from every unfinished worker's own result queue.
        connection.wait on the queues' read pipes gives a blocking
        multi-queue select; a finished ("done" received) worker's queue
        is complete and dropped from the poll set, so its EOF'd pipe
        can't busy-spin the wait."""
        polled = {w["out_q"]._reader: w["out_q"]
                  for w in self._workers.values() if not w["finished"]}
        got = False
        if polled:
            for r in mp_connection.wait(list(polled), timeout):
                q = polled[r]
                while True:
                    try:
                        msg = q.get_nowait()
                    except (_queue.Empty, EOFError, OSError, ValueError):
                        # ValueError: _handle routed an "error" to
                        # _crash, which retired and closed this queue
                        break
                    got = True
                    self._handle(msg)
        else:
            time.sleep(min(timeout, 0.05))
        if not got or time.monotonic() - self._last_liveness > 1.0:
            self._check_liveness()

    def _sweep(self):
        """Non-blocking ingest of every unfinished worker's queue."""
        for w in list(self._workers.values()):
            if w["finished"]:
                continue
            while True:
                try:
                    msg = w["out_q"].get_nowait()
                except (_queue.Empty, EOFError, OSError, ValueError):
                    break
                self._handle(msg)

    def _drain_worker_queue(self, w, timeout: float = 0.05):
        """Process everything currently readable on one worker's queue
        (used before retiring its ring, so already-shipped batches are
        salvaged instead of re-decoded)."""
        while True:
            try:
                self._handle(w["out_q"].get(timeout=timeout))
            except (_queue.Empty, EOFError, OSError, ValueError):
                return

    def _handle(self, msg):
        kind, wid = msg[0], msg[1]
        if kind == "meta":
            _, _, names, layout = msg
            from multiprocessing import shared_memory
            shms = [shared_memory.SharedMemory(name=n) for n in names]
            if wid not in self._workers:
                # stale incarnation's ring: adopt only to unlink it
                for m in shms:
                    try:
                        m.unlink()
                    except FileNotFoundError:
                        pass
                    m.close()
                return
            views = []
            for m in shms:
                off, vs = 0, []
                for shape, dtype in layout:
                    a = np.frombuffer(
                        m.buf, dtype=np.dtype(dtype),
                        count=int(np.prod(shape, dtype=np.int64)),
                        offset=off).reshape(shape)
                    a.flags.writeable = False
                    vs.append(a)
                    off += a.nbytes
                views.append(tuple(vs))
            self._rings[wid] = (shms, views,
                                self._workers[wid]["label"])
        elif kind == "batch":
            _, _, s, e, b, slot = msg
            ring = self._rings.get(wid)
            if ring is None:
                return  # retired incarnation: will be re-produced
            key = (e, b, s)
            duplicate = (key in self._buffer or s in self._fin
                         or (e, b) < (self._e[s], self._b[s]))
            if duplicate:
                w = self._workers.get(wid)
                if w is not None:
                    w["free_q"].put(slot)
                return
            self._buffer[key] = ("slot", wid, slot)
            if self._metrics:
                self._metrics["batches"].labels(
                    worker=str(ring[2])).inc()
                self._metrics["occupancy"].set(len(self._buffer))
        elif kind == "eof":
            _, _, s, _e, total = msg
            self._totals.setdefault(s, int(total))
        elif kind == "error":
            _, _, tb = msg
            if wid in self._workers:
                self._crash(wid, tb)
        elif kind == "done":
            w = self._workers.get(wid)
            if w is not None:
                w["finished"] = True

    def _check_liveness(self):
        self._last_liveness = time.monotonic()
        for wid, w in list(self._workers.items()):
            if w.get("finished") or w["proc"].is_alive():
                continue
            # sweep its queue once: a clean exit's "done" (or a dying
            # worker's "error" — which _handle routes to _crash with
            # the real worker traceback) may still be in the pipe
            self._drain_worker_queue(w)
            if wid not in self._workers or \
                    self._workers[wid].get("finished"):
                continue
            self._crash(wid, f"worker process died with exit code "
                             f"{w['proc'].exitcode} (no farewell "
                             "message: killed or crashed hard)")

    # -- pool management -----------------------------------------------
    def _spawn_pool(self, n: int):
        n = max(1, min(n, self.cfg.max_workers, len(self.cfg.shards)))
        order = list(range(len(self.cfg.shards)))
        for i in range(n):
            self._spawn_worker(i, order[i::n])
        if self._metrics:
            self._metrics["workers"].set(len(self._workers))
            self._metrics["capacity"].set(
                len(self._workers) * self.cfg.slots_per_worker)

    def _spawn_worker(self, label: int, shard_list: List[int]):
        wid = self._next_wid
        self._next_wid += 1
        specs = [(s, self._e[s], self._b[s])
                 for s in shard_list if s not in self._fin]
        if not specs:
            # every assigned shard is already finished (restore near
            # end-of-stream, or a crash after its shards completed):
            # nothing to produce, so don't pay a worker process for it
            return
        free_q = self._ctx.Queue()
        out_q = self._ctx.Queue()
        proc = self._ctx.Process(
            target=_service_worker_main,
            args=(wid, specs, self.cfg, self.cfg.slots_per_worker,
                  free_q, out_q, self._stop_ev, os.getpid()),
            daemon=True)
        proc.start()
        self._workers[wid] = {"proc": proc, "free_q": free_q,
                              "out_q": out_q,
                              "shards": list(shard_list), "label": label,
                              "finished": False}

    def _retire_ring(self, wid: int):
        ring = self._rings.pop(wid, None)
        if ring is None:
            return
        shms, views, _label = ring
        for key, entry in list(self._buffer.items()):
            if entry[0] == "slot" and entry[1] == wid:
                self._buffer[key] = (
                    "data",
                    tuple(np.array(v) for v in views[entry[2]]))
        views = None
        ring = None
        for m in shms:
            try:
                m.close()
            except BufferError:
                m.__class__ = _EscapedSegment
            try:
                m.unlink()
            except FileNotFoundError:
                pass

    def _crash(self, wid: int, tb: str):
        w = self._workers.pop(wid)
        w["proc"].join(timeout=2.0)
        if w["proc"].is_alive():
            w["proc"].terminate()
            w["proc"].join(1.0)
        # salvage everything it managed to ship before dying (the
        # worker is already out of self._workers, so a queued "error"
        # can't recurse into _crash)
        self._drain_worker_queue(w)
        self._retire_ring(wid)
        w["out_q"].close()
        w["out_q"].cancel_join_thread()
        if self._stopped or self._stop_ev.is_set():
            # teardown (stop()/rescale) in progress: a straggling error
            # message must neither spawn an orphan into the dying pool
            # nor raise out of the caller's `finally: svc.stop()`
            return
        self._respawns += 1
        if self._metrics:
            self._metrics["respawns"].inc()
        if self._respawns > self.cfg.max_respawns:
            self._fatal = RuntimeError(
                f"streaming input worker crashed and the respawn budget "
                f"({self.cfg.max_respawns}) is exhausted; last failure:\n"
                f"{tb}")
            raise self._fatal
        time.sleep(self.cfg.respawn_delay_s)
        self._spawn_worker(w["label"], w["shards"])

    def _rescale(self, n: int, direction: str):
        old = list(self._workers.values())
        self._stop_ev.set()
        for w in old:
            w["proc"].join(timeout=5.0)
        for w in old:
            if w["proc"].is_alive():
                w["proc"].terminate()
                w["proc"].join(1.0)
        for w in old:
            self._drain_worker_queue(w)
        self._workers.clear()
        for wid in list(self._rings):
            self._retire_ring(wid)
        for w in old:
            w["out_q"].close()
            w["out_q"].cancel_join_thread()
        self._stop_ev = self._ctx.Event()
        self._scale_events[direction] += 1
        if self._metrics:
            self._metrics["scale"].labels(direction=direction).inc()
        self._spawn_pool(n)

    # -- elastic scaling + metrics --------------------------------------
    def _account(self, starved: bool, occ: int):
        self._win_deliv += 1
        self._win_starved += int(starved)
        self._win_min_occ = occ if self._win_min_occ is None \
            else min(self._win_min_occ, occ)
        if self._metrics:
            self._metrics["occupancy"].set(len(self._buffer))
            self._update_lag()
        cfg = self.cfg
        now = time.monotonic()
        if cfg.scale_interval_s <= 0 or \
                now - self._win_t0 < cfg.scale_interval_s or \
                self._win_deliv < 4:
            return
        n = len(self._workers)
        cap = n * cfg.slots_per_worker
        starved_frac = self._win_starved / self._win_deliv
        hi = min(cfg.max_workers, len(cfg.shards))
        if starved_frac > cfg.scale_up_starved and n < hi:
            self._rescale(n + 1, "up")
        elif self._win_starved == 0 and n > cfg.min_workers and \
                self._win_min_occ is not None and \
                self._win_min_occ >= cap - n:
            self._rescale(n - 1, "down")
        # window restarts AFTER any rescale (which blocks for the pool
        # restart): anchoring it to the pre-rescale timestamp would
        # expire the next window immediately, and the cold new pool's
        # first starved deliveries would cascade another rescale
        self._win_t0 = time.monotonic()
        self._win_deliv = 0
        self._win_starved = 0
        self._win_min_occ = None

    def _update_lag(self):
        # shard lag in delivered batches, against the most advanced
        # shard (absolute = epoch * total + next_batch once the epoch
        # size is known; before that, next_batch alone)
        def absol(s):
            tot = self._totals.get(s)
            return (self._e[s] * tot + self._b[s]) if tot is not None \
                else self._b[s]

        vals = {s: absol(s) for s in range(len(self.cfg.shards))}
        top = max(vals.values(), default=0)
        for s, v in vals.items():
            self._metrics["lag"].labels(shard=str(s)).set(top - v)

    def _init_metrics(self):
        from ..observability.registry import default_registry
        reg = default_registry()
        if not reg.enabled:
            self._metrics = None
            return
        self._metrics = {
            "batches": reg.counter(
                "paddle_tpu_input_batches_total",
                "Batches produced by streaming input workers (labelled "
                "by worker pool slot).", ("worker",)),
            "occupancy": reg.gauge(
                "paddle_tpu_input_queue_occupancy",
                "Produced-but-undelivered batches buffered in the "
                "streaming input service (live prefetch-queue depth; "
                "the elastic-scaling signal)."),
            "capacity": reg.gauge(
                "paddle_tpu_input_queue_capacity",
                "Streaming input buffer capacity: workers x "
                "slots_per_worker shared-memory ring slots."),
            "workers": reg.gauge(
                "paddle_tpu_input_workers",
                "Current streaming input worker-process count."),
            "scale": reg.counter(
                "paddle_tpu_input_scale_events_total",
                "Elastic worker-pool rescale events.", ("direction",)),
            "respawns": reg.counter(
                "paddle_tpu_input_worker_respawns_total",
                "Streaming input workers respawned after a crash."),
            "lag": reg.gauge(
                "paddle_tpu_input_shard_lag",
                "Delivered-batch lag of each shard behind the most "
                "advanced shard.", ("shard",)),
        }

    # -- introspection --------------------------------------------------
    @property
    def delivered(self) -> int:
        return self._delivered

    def stats(self) -> dict:
        return {
            "delivered": self._delivered,
            "workers": len(self._workers),
            "respawns": self._respawns,
            "scale_events": dict(self._scale_events),
            "buffered": len(self._buffer),
            "totals": dict(self._totals),
            "finished_shards": sorted(self._fin),
        }
